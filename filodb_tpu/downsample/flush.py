"""Flush-time downsample emission: ds records produced as chunks encode.

(core/downsample/ShardDownsampler.scala:40,62
populateDownsampleRecords — when enabled, every flushed chunkset also
emits downsample records for each resolution, so the ds tier is
continuously fresh without waiting for the batch job. Like the
reference, records are per (chunk, period): a period spanning two chunks
yields two partial rows at distinct timestamps, which window aggregation
over nested periods combines exactly for sum/count/min/max.)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from filodb_tpu.core.record import PartKey, RecordContainer
from filodb_tpu.core.schemas import ColumnType, DatasetRef, Schemas
from filodb_tpu.downsample.job import ds_dataset
from filodb_tpu.memory import vectors as bv


class FlushDownsampler:
    """Per-shard flush-time downsampler writing into the derived
    ``<dataset>_ds_<res>`` datasets of the same ColumnStore.

    NOTE: downsample/job.py implements the same per-period semantics as
    DEVICE kernels for whole-history batches; this host path handles one
    small chunk at a time. tests/test_flush_downsample.py and
    tests/test_downsample.py pin both to the same raw-parity oracle, so
    a semantic change to one that misses the other fails tests."""

    def __init__(self, column_store, dataset: str, shard_num: int,
                 schemas: Schemas,
                 resolutions: Sequence[int] = (300_000,)):
        from filodb_tpu.core.memstore import TimeSeriesShard
        self._shard_cls = TimeSeriesShard
        self.store = column_store
        self.dataset = dataset
        self.shard_num = shard_num
        self.schemas = schemas
        self.resolutions = tuple(resolutions)
        self._out: Dict[str, object] = {}
        self.samples_emitted = 0

    def _out_shard(self, name: str):
        sh = self._out.get(name)
        if sh is None:
            sh = self._shard_cls(DatasetRef(name), self.schemas,
                                 self.shard_num,
                                 column_store=self.store)
            # recover per-series end times: crash-recovery replay re-emits
            # the same ds rows and the OOO guard drops them — the same
            # idempotency story as the raw tier
            sh.bootstrap_from_store()
            self._out[name] = sh
        return sh

    # -- emission ---------------------------------------------------------
    def on_chunk(self, part_key: PartKey, schema, info) -> None:
        """Downsample one freshly-encoded chunkset
        (populateDownsampleRecords per-chunk semantics)."""
        if not schema.downsamplers:
            return
        vci = schema.value_column_index()
        if schema.columns[vci].col_type == ColumnType.HISTOGRAM:
            return      # histograms: batch job (hLast) covers them
        ts = bv.decode_longs(info.vectors[0])
        vals = bv.decode_doubles(info.vectors[vci])
        marker = schema.downsample_period_marker
        for res in self.resolutions:
            if marker.startswith("counter"):
                self._emit_counter(part_key, schema, ts, vals, res)
            else:
                self._emit_gauge(part_key, ts, vals, res)

    def _emit_gauge(self, pk: PartKey, ts, vals, res: int) -> None:
        ds_schema = self.schemas.by_name("ds-gauge")
        base = (int(ts[0]) // res) * res
        period = (ts - base) // res
        nper = int(period[-1]) + 1
        cnt = np.bincount(period, minlength=nper)
        s = np.bincount(period, weights=vals, minlength=nper)
        mins = np.full(nper, np.inf)
        maxs = np.full(nper, -np.inf)
        np.minimum.at(mins, period, vals)
        np.maximum.at(maxs, period, vals)
        last_ts = np.zeros(nper, dtype=np.int64)
        last_ts[period] = ts            # sorted: last write wins
        out = self._out_shard(ds_dataset(self.dataset, res))
        cont = RecordContainer(ds_schema)
        out_pk = PartKey(ds_schema.schema_id, pk.labels)
        for p in np.nonzero(cnt)[0]:
            cont.add(out_pk, int(last_ts[p]), float(mins[p]),
                     float(maxs[p]), float(s[p]), float(cnt[p]),
                     float(s[p] / cnt[p]))
            self.samples_emitted += 1
        out.ingest(cont)

    def _emit_counter(self, pk: PartKey, schema, ts, vals, res: int
                      ) -> None:
        """Boundary-sample preservation (first/last per period + drops),
        the counter downsampling scheme (ChunkDownsampler dLast +
        counter period marker)."""
        base = (int(ts[0]) // res) * res
        period = (ts - base) // res
        keep = np.zeros(ts.size, dtype=bool)
        keep[0] = True
        keep[np.nonzero(np.diff(period))[0]] = True       # period lasts
        keep[np.nonzero(np.diff(period))[0] + 1] = True   # period firsts
        keep[-1] = True
        drops = np.nonzero(np.diff(vals) < 0)[0]
        keep[drops] = True                                # pre-drop peak
        keep[drops + 1] = True                            # post-drop
        ds_name = schema.downsample_schema or schema.name
        ds_schema = self.schemas.by_name(ds_name)
        out = self._out_shard(ds_dataset(self.dataset, res))
        cont = RecordContainer(ds_schema)
        out_pk = PartKey(ds_schema.schema_id, pk.labels)
        for i in np.nonzero(keep)[0]:
            cont.add(out_pk, int(ts[i]), float(vals[i]))
            self.samples_emitted += 1
        out.ingest(cont)

    # -- persistence ------------------------------------------------------
    def flush(self) -> None:
        """Persist emitted ds chunks (called after the raw flush group),
        then release them from memory — the ds tier is READ from the
        ColumnStore (DownsampledTimeSeriesStore pages it in), so keeping
        a second in-memory copy would only grow without bound."""
        for sh in self._out.values():
            sh.flush_all()
            sh.evict_partitions(cutoff_ts=1 << 62)
