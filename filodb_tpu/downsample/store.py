"""Query-side downsample store: resolution selection + plan rewriting.

(Reference: DownsampledTimeSeriesShard.scala:63 — query-only shards over
downsampled data, resolution chosen per query; the gauge query path reads
the ds-gauge column matching the range function. LongTimeRangePlanner
splits raw vs downsample by retention — the split/stitch lives in the
planner layer; this store answers the downsample side.)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef, Schemas
from filodb_tpu.downsample.job import ds_dataset
from filodb_tpu.query import logical as lp

# range function -> (ds-gauge column, function to run over that column)
# min of per-period minima is the min; sums/counts add; avg falls back to
# the avg column (exact when windows nest periods, the standard ds tradeoff).
# last_over_time is deliberately absent: ds-gauge (matching the reference
# schema) has no `last` column, and mapping it to `avg` would silently
# return the period average — those queries fall back to raw data.
_GAUGE_REWRITES: Dict[str, Tuple[str, str]] = {
    "min_over_time": ("min", "min_over_time"),
    "max_over_time": ("max", "max_over_time"),
    "sum_over_time": ("sum", "sum_over_time"),
    "count_over_time": ("count", "sum_over_time"),
    "avg_over_time": ("avg", "avg_over_time"),
}


def select_resolution(resolutions: Sequence[int], window_ms: int,
                      step_ms: int) -> Optional[int]:
    """Coarsest resolution that still gives every window >= 2 periods
    (DownsampledTimeSeriesShard pickles resolution by query range)."""
    best = None
    for res in sorted(resolutions):
        if window_ms >= 2 * res and step_ms >= res:
            best = res
    return best


def rewrite_plan(plan, resolution_ms: int):
    """Rewrite a LogicalPlan to run against ds data: gauge over-time
    functions select the matching ds-gauge column. Counter functions
    (rate/increase) read the same value column and need no rewrite —
    counter downsampling preserved boundary samples.

    Returns None when the plan contains a window function the downsample
    schema cannot serve exactly (e.g. last_over_time, quantile_over_time on
    ds-gauge) — the caller must fall back to raw data."""
    if isinstance(plan, lp.PeriodicSeriesWithWindowing):
        rw = _GAUGE_REWRITES.get(plan.function)
        if rw is None:
            from filodb_tpu.query.rangefn import COUNTER_FUNCTIONS
            if plan.function in COUNTER_FUNCTIONS or plan.function == "delta":
                return plan     # counter ds preserved boundary samples
            # every other window function (changes, deriv, quantile_over_
            # time, holt_winters, ...) has no exact ds column: use raw
            return None
        col, func = rw
        raw = dataclasses.replace(plan.raw, column=plan.raw.column or col)
        return dataclasses.replace(plan, raw=raw, function=func)
    if hasattr(plan, "__dataclass_fields__"):
        changes = {}
        for f in plan.__dataclass_fields__:
            v = getattr(plan, f)
            if isinstance(v, tuple):
                nv = []
                for x in v:
                    if hasattr(x, "__dataclass_fields__"):
                        rx = rewrite_plan(x, resolution_ms)
                        if rx is None:
                            return None
                        nv.append(rx)
                    else:
                        nv.append(x)
                nv = tuple(nv)
                if nv != v:
                    changes[f] = nv
            elif hasattr(v, "__dataclass_fields__"):
                nv = rewrite_plan(v, resolution_ms)
                if nv is None:
                    return None
                if nv is not v:
                    changes[f] = nv
        if changes:
            return dataclasses.replace(plan, **changes)
    return plan


class DownsampledTimeSeriesStore:
    """Read-only store over the downsample datasets of one raw dataset.

    ``shards_for`` picks the resolution for a query and returns the shard
    set (bootstrapped lazily from the ColumnStore) plus the rewritten
    plan; callers hand both to the ordinary engine/planner — downsampled
    chunks are ordinary chunks."""

    def __init__(self, column_store, dataset: str, num_shards: int,
                 resolutions: Sequence[int] = (300_000, 3_600_000),
                 schemas: Optional[Schemas] = None):
        self.store = column_store
        self.dataset = dataset
        self.num_shards = num_shards
        self.resolutions = tuple(sorted(resolutions))
        self.schemas = schemas or DEFAULT_SCHEMAS
        self._shards: Dict[int, List[TimeSeriesShard]] = {}

    def shards_for_resolution(self, res: int) -> List[TimeSeriesShard]:
        got = self._shards.get(res)
        if got is None:
            name = ds_dataset(self.dataset, res)
            got = []
            for sh in range(self.num_shards):
                shard = TimeSeriesShard(DatasetRef(name), self.schemas, sh,
                                        column_store=self.store)
                shard.bootstrap_from_store()
                got.append(shard)
            self._shards[res] = got
        return got

    def plan_query(self, plan, window_ms: int, step_ms: int
                   ) -> Optional[Tuple[List[TimeSeriesShard], object]]:
        """(shards, rewritten_plan) when a downsample resolution can serve
        this query, else None (caller uses the raw store)."""
        res = select_resolution(self.resolutions, window_ms, step_ms)
        if res is None:
            return None
        rewritten = rewrite_plan(plan, res)
        if rewritten is None:
            return None     # function has no exact ds mapping: use raw
        return self.shards_for_resolution(res), rewritten
