"""Downsampling: device-kernel batch job + query-side resolution selection.

(Reference: core/downsample/ChunkDownsampler.scala:38-353,
DownsamplePeriodMarker.scala, ShardDownsampler.scala:40;
spark-jobs/downsampler/chunk/DownsamplerMain.scala:69,
BatchDownsampler.scala:119,192; query side
DownsampledTimeSeriesShard.scala:63.)"""

from filodb_tpu.downsample.job import DownsamplerJob, ds_dataset
from filodb_tpu.downsample.store import DownsampledTimeSeriesStore

__all__ = ["DownsamplerJob", "DownsampledTimeSeriesStore", "ds_dataset"]
