"""Device downsample kernels: per-period aggregates over [S, N] tiles.

The reference computes one value per chunk per period with per-row iterator
``ChunkDownsampler``s (core/downsample/ChunkDownsampler.scala:38-353 —
SumDownsampler, CountDownsampler, MinDownsampler, MaxDownsampler,
AvgDownsampler, LastValueDDownsampler, TimeDownsampler) driven by
``DownsamplePeriodMarker`` row ranges (time-aligned, plus counter-correction
boundaries for counters).

Here the whole batch is one fused XLA program: period assignment is integer
arithmetic per sample, aggregation is scatter-add/min/max onto a dense
[S, P] period grid (same trick as the query engine's window bounds — the
scatter rides the VPU, results stay on device until the host encodes
chunks). Counter period boundaries (resets) come out as an emit mask, since
counter downsampling persists boundary samples rather than aggregates.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from filodb_tpu.lint.contracts import kernel_contract


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _ds_example(extra_statics, S=8, N=64):
    args = (_sds((S, N), jnp.int64), _sds((S, N), jnp.float64),
            _sds((S,), jnp.int32), _sds((), jnp.int64),
            _sds((), jnp.int64))
    return args, dict(extra_statics)


def _six_expect(S, P):
    """The (sum, count, min, max, last_v, last_ts) output family."""
    def expect(out):
        shapes = [tuple(o.shape) for o in out]
        if shapes != [(S, P)] * 6:
            return f"outputs {shapes} != 6x({S}, {P})"
        if str(out[-1].dtype) != "int64" \
                or any(str(o.dtype) != "float64" for o in out[:5]):
            return "dtypes != (5x f64, i64)"
        return None
    return expect


@kernel_contract(
    "downsample_gauge", kind="jit",
    example=lambda: _ds_example({"nperiods": 16, "w_bound": 8}),
    expect=_six_expect(8, 16),
    notes="general gather path: [S, P, W] bounded gather for order "
          "statistics, prefix sums for sum/count; W static")
@functools.partial(jax.jit, static_argnames=("nperiods", "w_bound"))
def downsample_gauge_tiles(ts, vals, lens, base, res, nperiods: int,
                           w_bound: int = 64):
    """Per-period (sum, count, min, max, last_v, last_ts) for gauge tiles.

    Period p = (ts - base) // res; samples outside [0, nperiods) and row
    padding are dropped; empty periods are NaN. (dSum/dCount/dMin/dMax/
    dAvg/tTime of the gauge schema in one pass; avg = sum/count is
    computed by the caller.)

    Timestamps are sorted per row, so periods are CONTIGUOUS index ranges:
    this is the query engine's uniform window machinery with
    window == step == res — int32 scatter-histogram bounds + f64 prefix
    sums + a [S, P, W] bounded gather for the order statistics. (A direct
    f64 scatter-add/min/max onto [S, P] lowers to a serialized TPU scatter
    and ran ~500x slower.) ``w_bound`` is a static cap on samples per
    period for the min/max gather."""
    from filodb_tpu.query.tpu import _bounds, _prefix, _take

    S, N = ts.shape
    idx = jnp.arange(N)[None, :]
    valid = idx < lens[:, None]
    ts = jnp.where(valid, ts, jnp.int64(1) << 60)   # pad -> no period
    lo, hi = _bounds(ts, base, base + res - 1, res, nperiods)   # [S, P]
    counts = (hi - lo + 1).astype(jnp.float64)
    has = counts >= 1
    nan = jnp.nan
    v = jnp.where(valid, vals, 0.0)
    cs = _prefix(v)
    sums = _take(cs, jnp.clip(hi + 1, 0, N)) - _take(cs, jnp.clip(lo, 0, N))
    hi_c = jnp.clip(hi, 0, N - 1)
    last_v = _take(vals, hi_c)
    last_ts = _take(ts, hi_c)
    # order statistics: bounded gather over each period's index range
    offs = jnp.arange(w_bound)
    gidx = lo[:, :, None] + offs[None, None, :]          # [S, P, W]
    in_p = (gidx <= hi[:, :, None]) & (gidx < lens[:, None, None])
    gidx_c = jnp.clip(gidx, 0, N - 1)
    g = jnp.take_along_axis(vals, gidx_c.reshape(S, -1), axis=1).reshape(
        gidx.shape)
    mins = jnp.min(jnp.where(in_p, g, jnp.inf), axis=2)
    maxs = jnp.max(jnp.where(in_p, g, -jnp.inf), axis=2)
    return (jnp.where(has, sums, nan), jnp.where(has, counts, 0.0),
            jnp.where(has, mins, nan), jnp.where(has, maxs, nan),
            jnp.where(has, last_v, nan),
            jnp.where(has, last_ts, jnp.int64(0)))


def cascade_gauge(prev, base, res, nperiods: int, w_bound: int):
    """Downsample one resolution level from the previous level's outputs
    (sum of sums, count of counts, min of mins, max of maxes, last of
    lasts) — the multi-resolution cascade: only the finest level reads raw
    samples. ``prev`` is the previous level's 6-tuple."""
    p_sums, p_cnts, p_mins, p_maxs, p_last_v, p_last_ts = prev
    S, P = p_sums.shape
    has = p_cnts > 0
    pts = jnp.where(has, p_last_ts, jnp.int64(1) << 60)  # empty -> dropped
    lens = jnp.full((S,), P, dtype=jnp.int32)

    def run(chan):
        return downsample_gauge_tiles(pts, jnp.where(has, chan, 0.0), lens,
                                      base, res, nperiods, w_bound)

    s_out = run(p_sums)
    c_out = run(p_cnts)
    m_out = run(p_mins)
    x_out = run(p_maxs)
    l_out = run(p_last_v)
    counts = jnp.where(jnp.isnan(c_out[0]), 0.0, c_out[0])
    return (s_out[0], counts, m_out[2], x_out[3], l_out[4], s_out[5])


@kernel_contract(
    "counter_emit_mask", kind="jit",
    example=lambda: _ds_example({"nperiods": 16}),
    expect=lambda out: None if tuple(out.shape) == (8, 64)
    and str(out.dtype) == "bool" else f"mask {out.shape}/{out.dtype}",
    notes="pure lane arithmetic (no scatter): last-of-period + both "
          "sides of every counter reset")
@functools.partial(jax.jit, static_argnames=("nperiods",))
def counter_emit_mask(ts, vals, lens, base, res, nperiods: int):
    """Emit mask for counter downsampling: keep the LAST sample of every
    period plus BOTH sides of every reset — the peak right before it and
    the reset sample itself (DownsamplePeriodMarker counter boundaries,
    DownsamplePeriodMarker.scala; dLast of prom-counter).

    Emitting both sides makes every drop visible to query-time counter
    correction even when the counter climbs back above the old peak before
    the period ends, so sum-of-increases over the emitted rows equals the
    raw correction's from any emitted baseline onward."""
    S, N = ts.shape
    idx = jnp.arange(N)[None, :]
    valid = idx < lens[:, None]
    p = ((ts - base) // jnp.maximum(res, 1)).astype(jnp.int32)
    p_ok = valid & (p >= 0) & (p < nperiods)
    # rows are time-sorted: a sample is last-in-period iff its successor is
    # invalid or falls in a different period (pure lane arithmetic — no
    # scatter, which TPU would serialize)
    nxt_p = jnp.concatenate([p[:, 1:],
                             jnp.full((S, 1), -1, p.dtype)], axis=1)
    nxt_valid = jnp.concatenate([valid[:, 1:],
                                 jnp.zeros((S, 1), bool)], axis=1)
    is_last = ~nxt_valid | (nxt_p != p)
    nxt = jnp.concatenate([vals[:, 1:], vals[:, -1:]], axis=1)
    peak = (nxt < vals) & nxt_valid                       # next is a reset
    prev = jnp.concatenate([vals[:, :1], vals[:, :-1]], axis=1)
    is_reset = (vals < prev) & (idx > 0) & valid          # first after drop
    return (is_last | peak | is_reset) & p_ok


# ---------------------------------------------------------------------------
# Regular-cadence fast path: reshape instead of gather
# ---------------------------------------------------------------------------
# For a batch whose rows share one scrape cadence (nominal ticks
# t0 + i*dt, |jitter| < dt/2 — the realistic downsampler input) every
# period's samples form a CONSTANT-length run of R = res//dt sample
# indices, with at most ONE boundary slot per period whose jitter can
# push it into a neighbouring period — and the grid phase decides
# STATICALLY which direction that is. So the whole per-period
# aggregation is reshape + reduce (HBM-bound, compiles in seconds); the
# general [S, P, W] gather kernel above stays as the fallback for
# ragged/irregular batches (its XLA program takes minutes to compile at
# batch shapes and gathers at ~1/6 of streaming bandwidth).


@kernel_contract(
    "downsample_regular", kind="jit",
    example=lambda: (
        (_sds((8, 64), jnp.int64), _sds((8, 64), jnp.float64),
         _sds((), jnp.int64), _sds((), jnp.int64)),
        {"R": 4, "nperiods": 8, "c0": 2, "down": False}),
    expect=_six_expect(8, 8),
    notes="regular-cadence reshape fast path; dispatch gated by "
          "regular_cadence (jitter strictly under dt/2, res % dt == 0)")
@functools.partial(jax.jit,
                   static_argnames=("R", "nperiods", "c0", "down"))
def _ds_regular(ts, vals, base, res, R: int, nperiods: int, c0: int,
                down: bool):
    S, N = ts.shape
    P = nperiods
    SENT = jnp.int64(1) << 60
    if c0 < 0:
        ts = jnp.concatenate(
            [jnp.full((S, -c0), SENT, ts.dtype), ts], axis=1)
        vals = jnp.concatenate(
            [jnp.zeros((S, -c0), vals.dtype), vals], axis=1)
        N -= c0
        c0 = 0
    need = c0 + P * R
    if need > N:
        ts = jnp.concatenate(
            [ts, jnp.full((S, need - N), SENT, ts.dtype)], axis=1)
        vals = jnp.concatenate(
            [vals, jnp.zeros((S, need - N), vals.dtype)], axis=1)
    tw = ts[:, c0:c0 + P * R].reshape(S, P, R)
    vw = vals[:, c0:c0 + P * R].reshape(S, P, R)
    valid = tw < (jnp.int64(1) << 59)
    pb = base + jnp.arange(P, dtype=jnp.int64) * res      # period starts
    # the tick just OUTSIDE the reshape slice can jitter into a covered
    # edge period: in up-mode tick c0-1 into period 0, in down-mode tick
    # c0 + P*R into period P-1 (out-of-range indices read the sentinel
    # padding and fall out via the validity check)
    SENT_LO = jnp.int64(1) << 59
    if down:
        e_ts = ts[:, c0 + P * R] if ts.shape[1] > c0 + P * R \
            else jnp.full((S,), SENT, ts.dtype)
        e_v = vals[:, c0 + P * R] if ts.shape[1] > c0 + P * R \
            else jnp.zeros((S,), vals.dtype)
        e_ok = (e_ts < SENT_LO) & (e_ts < base + P * res) \
            & (e_ts >= base + (P - 1) * res)
        e_period = P - 1
    else:
        e_ts = ts[:, c0 - 1] if c0 >= 1 \
            else jnp.full((S,), SENT, ts.dtype)
        e_v = vals[:, c0 - 1] if c0 >= 1 else jnp.zeros((S,), vals.dtype)
        e_ok = (e_ts < SENT_LO) & (e_ts >= base) & (e_ts < base + res)
        e_period = 0
    if down:
        # only the FIRST slot of a period can cross (into the previous)
        bpos = 0
        b_ts, b_v, b_ok = tw[:, :, 0], vw[:, :, 0], valid[:, :, 0]
        crossed = b_ts < pb[None, :]
    else:
        # only the LAST slot can cross (into the next)
        bpos = R - 1
        b_ts, b_v, b_ok = tw[:, :, -1], vw[:, :, -1], valid[:, :, -1]
        crossed = b_ts >= (pb + res)[None, :]

    own_ok = b_ok & ~crossed
    mv_ok = b_ok & crossed
    # full member mask of window p's OWN samples: every valid slot,
    # with the boundary slot gated on not-crossed
    pos = jnp.arange(R)
    member_ok = jnp.where(pos[None, None, :] == bpos,
                          own_ok[:, :, None], valid)

    def nb(arr, fill):
        """The neighbour period's view of the moved boundary sample."""
        if down:        # b_{p+1} moves INTO p
            return jnp.concatenate(
                [arr[:, 1:], jnp.full_like(arr[:, :1], fill)], axis=1)
        return jnp.concatenate(                     # b_{p-1} moves INTO p
            [jnp.full_like(arr[:, :1], fill), arr[:, :-1]], axis=1)

    mv_ok_n = nb(mv_ok, False)
    mv_v_n = nb(jnp.where(mv_ok, b_v, 0.0), 0.0)
    cnt = (member_ok.sum(axis=2) + mv_ok_n).astype(jnp.float64)
    sums = jnp.where(member_ok, vw, 0.0).sum(axis=2) + mv_v_n
    inf = jnp.inf
    mins = jnp.minimum(jnp.where(member_ok, vw, inf).min(axis=2),
                       nb(jnp.where(mv_ok, b_v, inf), inf))
    maxs = jnp.maximum(jnp.where(member_ok, vw, -inf).max(axis=2),
                       nb(jnp.where(mv_ok, b_v, -inf), -inf))
    # latest own sample: masked ts-max (windows at the batch tail end in
    # padding, so a fixed slot index would miss it), then the value at
    # that (unique, strictly-increasing) timestamp
    IMIN = jnp.int64(-1) << 62
    own_last_ts = jnp.where(member_ok, tw, IMIN).max(axis=2)
    own_last_v = jnp.where(member_ok & (tw == own_last_ts[:, :, None]),
                           vw, 0.0).sum(axis=2)
    own_has = member_ok.any(axis=2)
    if down:
        # an incoming crossed boundary (index (p+1)R + c0) postdates
        # every own sample
        mv_ts_n = nb(jnp.where(mv_ok, b_ts, jnp.int64(0)), jnp.int64(0))
        last_ts = jnp.where(mv_ok_n, mv_ts_n,
                            jnp.where(own_has, own_last_ts, 0))
        last_v = jnp.where(mv_ok_n, mv_v_n,
                           jnp.where(own_has, own_last_v, jnp.nan))
    else:
        # an incoming crossed boundary (index pR + c0 - 1) PREdates
        # every own sample — it is the latest only for windows with no
        # own members
        mv_ts_n = nb(jnp.where(mv_ok, b_ts, jnp.int64(0)), jnp.int64(0))
        last_ts = jnp.where(own_has, own_last_ts,
                            jnp.where(mv_ok_n, mv_ts_n, 0))
        last_v = jnp.where(own_has, own_last_v,
                           jnp.where(mv_ok_n, mv_v_n, jnp.nan))
    # fold the out-of-slice edge tick into its edge period
    ecol = jnp.zeros((P,), bool).at[e_period].set(True)[None, :]
    e_in = e_ok[:, None] & ecol
    cnt = cnt + e_in
    sums = sums + jnp.where(e_in, e_v[:, None], 0.0)
    mins = jnp.minimum(mins, jnp.where(e_in, e_v[:, None], jnp.inf))
    maxs = jnp.maximum(maxs, jnp.where(e_in, e_v[:, None], -jnp.inf))
    if down:
        # the edge tick postdates every covered sample of period P-1
        last_ts = jnp.where(e_in, e_ts[:, None], last_ts)
        last_v = jnp.where(e_in, e_v[:, None], last_v)
    else:
        # the edge tick (c0-1) PREdates period 0's own samples: it is
        # the latest only when the period had none
        e_only = e_in & (last_ts == 0)
        last_ts = jnp.where(e_only, e_ts[:, None], last_ts)
        last_v = jnp.where(e_only, e_v[:, None], last_v)
    has = cnt > 0
    nan = jnp.nan
    return (jnp.where(has, sums, nan), cnt,
            jnp.where(has & jnp.isfinite(mins), mins, nan),
            jnp.where(has & jnp.isfinite(maxs), maxs, nan),
            jnp.where(has, last_v, nan),
            jnp.where(has, last_ts, jnp.int64(0)))


def regular_cadence(ts_pad: np.ndarray, lens: np.ndarray, res: int
                    ) -> Optional[Tuple[int, int]]:
    """Host-side gate for the reshape fast path: dense rows sharing one
    nominal tick grid t0 + i*dt with max |jitter| strictly under dt/2,
    and res a whole number of ticks. Returns (t0, dt) or None."""
    S, N = ts_pad.shape
    if S == 0 or N < 2 or not bool((lens == N).all()):
        return None
    ts = np.asarray(ts_pad)
    dt_raw = float(ts[0, -1] - ts[0, 0]) / (N - 1)
    # jitter makes the raw estimate off by a few ms: snap to round
    # cadences and let the jitter bound (the actual correctness gate)
    # pick the first that fits
    cands = []
    for m in (60_000, 30_000, 15_000, 10_000, 5_000, 1_000, 500, 100,
              10, 1):
        c = int(round(dt_raw / m)) * m
        if c > 0 and c not in cands:
            cands.append(c)
    idx = np.arange(N, dtype=np.int64)
    for dt in cands:
        if res % dt != 0:
            continue
        t0 = int(np.round((ts - idx[None, :] * dt).mean()))
        j = np.abs(ts - (t0 + idx[None, :] * dt)).max()
        if j < dt / 2:
            return t0, dt
    return None


def downsample_gauge_fast(ts_pad, vals_pad, lens, base, res,
                          nperiods: int, cadence=None):
    """Dispatch the reshape fast path when the batch qualifies
    (regular_cadence); None -> caller falls back to the gather kernel.
    ``cadence=(t0, dt)`` skips the host gate for callers that know the
    grid by construction (device-resident benches: the gate would pull
    the whole ts tile across the tunnel)."""
    rc = cadence if cadence is not None \
        else regular_cadence(ts_pad, lens, int(res))
    if rc is None:
        return None
    t0, dt = rc
    if int(res) % dt != 0:
        return None
    R = int(res) // dt
    if R < 2:
        return None
    o0 = t0 - int(base)
    c0 = -(-(-o0) // dt)                 # ceil(-o0 / dt)
    d1 = o0 + c0 * dt                    # grid phase within the period
    down = d1 < dt / 2
    return _ds_regular(jnp.asarray(ts_pad), jnp.asarray(vals_pad),
                       jnp.int64(base), jnp.int64(res), R, nperiods,
                       c0, down)


@kernel_contract(
    "cascade_aligned", kind="jit",
    example=lambda: (
        (tuple(_sds((8, 16), jnp.float64) for _ in range(5))
         + (_sds((8, 16), jnp.int64),), 4, 1),
        {}),
    expect=_six_expect(8, 5),       # Q = ceil((16 + 1) / 4)
    notes="nested-resolution cascade: reshape + NaN-aware reduce over "
          "ratio consecutive fine periods")
@functools.partial(jax.jit, static_argnames=("ratio", "lead"))
def cascade_gauge_aligned(prev, ratio: int, lead: int):
    """Coarse level from a fine level when the resolutions nest
    (res_coarse % res_fine == 0): each coarse period is `ratio`
    consecutive fine periods (offset by `lead` fine periods for the
    base alignment) — pure reshape + NaN-aware reduce, no kernel."""
    p_sums, p_cnts, p_mins, p_maxs, p_last_v, p_last_ts = prev
    S, P = p_sums.shape
    Q = -(-(P + lead) // ratio)
    padR = Q * ratio - P - lead

    def grp(a, fill):
        a = jnp.concatenate(
            [jnp.full((S, lead), fill, a.dtype), a,
             jnp.full((S, padR), fill, a.dtype)], axis=1)
        return a.reshape(S, Q, ratio)

    has = grp(p_cnts, 0.0) > 0
    cnt = jnp.where(has, grp(p_cnts, 0.0), 0.0).sum(axis=2)
    sums = jnp.where(has, grp(jnp.nan_to_num(p_sums), 0.0), 0.0).sum(axis=2)
    mins = jnp.where(has, grp(jnp.nan_to_num(p_mins, nan=jnp.inf),
                              jnp.inf), jnp.inf).min(axis=2)
    maxs = jnp.where(has, grp(jnp.nan_to_num(p_maxs, nan=-jnp.inf),
                              -jnp.inf), -jnp.inf).max(axis=2)
    lts = jnp.where(has, grp(p_last_ts, jnp.int64(0)), 0)
    lv = grp(jnp.nan_to_num(p_last_v), 0.0)
    # latest non-empty fine period wins (fine last_ts increase with index)
    pick = jnp.argmax(
        jnp.where(has, jnp.arange(ratio, dtype=jnp.int32)[None, None, :],
                  -1), axis=2)
    last_ts = jnp.take_along_axis(lts, pick[:, :, None], axis=2)[:, :, 0]
    last_v = jnp.take_along_axis(lv, pick[:, :, None], axis=2)[:, :, 0]
    okp = cnt > 0
    nan = jnp.nan
    return (jnp.where(okp, sums, nan), cnt,
            jnp.where(okp & jnp.isfinite(mins), mins, nan),
            jnp.where(okp & jnp.isfinite(maxs), maxs, nan),
            jnp.where(okp, last_v, nan),
            jnp.where(okp, last_ts, jnp.int64(0)))


# ---------------------------------------------------------------------------
# numpy oracle (parity model for the kernels)
# ---------------------------------------------------------------------------

def downsample_gauge_oracle(ts: np.ndarray, vals: np.ndarray, base: int,
                            res: int, nperiods: int
                            ) -> Tuple[np.ndarray, ...]:
    """Reference semantics, one series, plain numpy loops."""
    sums = np.full(nperiods, np.nan)
    cnts = np.zeros(nperiods)
    mins = np.full(nperiods, np.nan)
    maxs = np.full(nperiods, np.nan)
    last_v = np.full(nperiods, np.nan)
    last_ts = np.zeros(nperiods, dtype=np.int64)
    for t, v in zip(ts, vals):
        p = (int(t) - base) // res
        if not (0 <= p < nperiods):
            continue
        if cnts[p] == 0:
            sums[p] = v
            mins[p] = v
            maxs[p] = v
        else:
            sums[p] += v
            mins[p] = min(mins[p], v)
            maxs[p] = max(maxs[p], v)
        cnts[p] += 1
        last_v[p] = v
        last_ts[p] = t
    return sums, cnts, mins, maxs, last_v, last_ts
