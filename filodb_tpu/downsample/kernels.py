"""Device downsample kernels: per-period aggregates over [S, N] tiles.

The reference computes one value per chunk per period with per-row iterator
``ChunkDownsampler``s (core/downsample/ChunkDownsampler.scala:38-353 —
SumDownsampler, CountDownsampler, MinDownsampler, MaxDownsampler,
AvgDownsampler, LastValueDDownsampler, TimeDownsampler) driven by
``DownsamplePeriodMarker`` row ranges (time-aligned, plus counter-correction
boundaries for counters).

Here the whole batch is one fused XLA program: period assignment is integer
arithmetic per sample, aggregation is scatter-add/min/max onto a dense
[S, P] period grid (same trick as the query engine's window bounds — the
scatter rides the VPU, results stay on device until the host encodes
chunks). Counter period boundaries (resets) come out as an emit mask, since
counter downsampling persists boundary samples rather than aggregates.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402


@functools.partial(jax.jit, static_argnames=("nperiods", "w_bound"))
def downsample_gauge_tiles(ts, vals, lens, base, res, nperiods: int,
                           w_bound: int = 64):
    """Per-period (sum, count, min, max, last_v, last_ts) for gauge tiles.

    Period p = (ts - base) // res; samples outside [0, nperiods) and row
    padding are dropped; empty periods are NaN. (dSum/dCount/dMin/dMax/
    dAvg/tTime of the gauge schema in one pass; avg = sum/count is
    computed by the caller.)

    Timestamps are sorted per row, so periods are CONTIGUOUS index ranges:
    this is the query engine's uniform window machinery with
    window == step == res — int32 scatter-histogram bounds + f64 prefix
    sums + a [S, P, W] bounded gather for the order statistics. (A direct
    f64 scatter-add/min/max onto [S, P] lowers to a serialized TPU scatter
    and ran ~500x slower.) ``w_bound`` is a static cap on samples per
    period for the min/max gather."""
    from filodb_tpu.query.tpu import _bounds, _prefix, _take

    S, N = ts.shape
    idx = jnp.arange(N)[None, :]
    valid = idx < lens[:, None]
    ts = jnp.where(valid, ts, jnp.int64(1) << 60)   # pad -> no period
    lo, hi = _bounds(ts, base, base + res - 1, res, nperiods)   # [S, P]
    counts = (hi - lo + 1).astype(jnp.float64)
    has = counts >= 1
    nan = jnp.nan
    v = jnp.where(valid, vals, 0.0)
    cs = _prefix(v)
    sums = _take(cs, jnp.clip(hi + 1, 0, N)) - _take(cs, jnp.clip(lo, 0, N))
    hi_c = jnp.clip(hi, 0, N - 1)
    last_v = _take(vals, hi_c)
    last_ts = _take(ts, hi_c)
    # order statistics: bounded gather over each period's index range
    offs = jnp.arange(w_bound)
    gidx = lo[:, :, None] + offs[None, None, :]          # [S, P, W]
    in_p = (gidx <= hi[:, :, None]) & (gidx < lens[:, None, None])
    gidx_c = jnp.clip(gidx, 0, N - 1)
    g = jnp.take_along_axis(vals, gidx_c.reshape(S, -1), axis=1).reshape(
        gidx.shape)
    mins = jnp.min(jnp.where(in_p, g, jnp.inf), axis=2)
    maxs = jnp.max(jnp.where(in_p, g, -jnp.inf), axis=2)
    return (jnp.where(has, sums, nan), jnp.where(has, counts, 0.0),
            jnp.where(has, mins, nan), jnp.where(has, maxs, nan),
            jnp.where(has, last_v, nan),
            jnp.where(has, last_ts, jnp.int64(0)))


def cascade_gauge(prev, base, res, nperiods: int, w_bound: int):
    """Downsample one resolution level from the previous level's outputs
    (sum of sums, count of counts, min of mins, max of maxes, last of
    lasts) — the multi-resolution cascade: only the finest level reads raw
    samples. ``prev`` is the previous level's 6-tuple."""
    p_sums, p_cnts, p_mins, p_maxs, p_last_v, p_last_ts = prev
    S, P = p_sums.shape
    has = p_cnts > 0
    pts = jnp.where(has, p_last_ts, jnp.int64(1) << 60)  # empty -> dropped
    lens = jnp.full((S,), P, dtype=jnp.int32)

    def run(chan):
        return downsample_gauge_tiles(pts, jnp.where(has, chan, 0.0), lens,
                                      base, res, nperiods, w_bound)

    s_out = run(p_sums)
    c_out = run(p_cnts)
    m_out = run(p_mins)
    x_out = run(p_maxs)
    l_out = run(p_last_v)
    counts = jnp.where(jnp.isnan(c_out[0]), 0.0, c_out[0])
    return (s_out[0], counts, m_out[2], x_out[3], l_out[4], s_out[5])


@functools.partial(jax.jit, static_argnames=("nperiods",))
def counter_emit_mask(ts, vals, lens, base, res, nperiods: int):
    """Emit mask for counter downsampling: keep the LAST sample of every
    period plus BOTH sides of every reset — the peak right before it and
    the reset sample itself (DownsamplePeriodMarker counter boundaries,
    DownsamplePeriodMarker.scala; dLast of prom-counter).

    Emitting both sides makes every drop visible to query-time counter
    correction even when the counter climbs back above the old peak before
    the period ends, so sum-of-increases over the emitted rows equals the
    raw correction's from any emitted baseline onward."""
    S, N = ts.shape
    idx = jnp.arange(N)[None, :]
    valid = idx < lens[:, None]
    p = ((ts - base) // jnp.maximum(res, 1)).astype(jnp.int32)
    p_ok = valid & (p >= 0) & (p < nperiods)
    # rows are time-sorted: a sample is last-in-period iff its successor is
    # invalid or falls in a different period (pure lane arithmetic — no
    # scatter, which TPU would serialize)
    nxt_p = jnp.concatenate([p[:, 1:],
                             jnp.full((S, 1), -1, p.dtype)], axis=1)
    nxt_valid = jnp.concatenate([valid[:, 1:],
                                 jnp.zeros((S, 1), bool)], axis=1)
    is_last = ~nxt_valid | (nxt_p != p)
    nxt = jnp.concatenate([vals[:, 1:], vals[:, -1:]], axis=1)
    peak = (nxt < vals) & nxt_valid                       # next is a reset
    prev = jnp.concatenate([vals[:, :1], vals[:, :-1]], axis=1)
    is_reset = (vals < prev) & (idx > 0) & valid          # first after drop
    return (is_last | peak | is_reset) & p_ok


# ---------------------------------------------------------------------------
# numpy oracle (parity model for the kernels)
# ---------------------------------------------------------------------------

def downsample_gauge_oracle(ts: np.ndarray, vals: np.ndarray, base: int,
                            res: int, nperiods: int
                            ) -> Tuple[np.ndarray, ...]:
    """Reference semantics, one series, plain numpy loops."""
    sums = np.full(nperiods, np.nan)
    cnts = np.zeros(nperiods)
    mins = np.full(nperiods, np.nan)
    maxs = np.full(nperiods, np.nan)
    last_v = np.full(nperiods, np.nan)
    last_ts = np.zeros(nperiods, dtype=np.int64)
    for t, v in zip(ts, vals):
        p = (int(t) - base) // res
        if not (0 <= p < nperiods):
            continue
        if cnts[p] == 0:
            sums[p] = v
            mins[p] = v
            maxs[p] = v
        else:
            sums[p] += v
            mins[p] = min(mins[p], v)
            maxs[p] = max(maxs[p], v)
        cnts[p] += 1
        last_v[p] = v
        last_ts[p] = t
    return sums, cnts, mins, maxs, last_v, last_ts
