"""Downsampler batch job: persisted raw chunks → multi-resolution ds chunks.

The reference runs this as a Spark job over Cassandra token-range splits
(spark-jobs/downsampler/chunk/DownsamplerMain.scala:69 →
BatchDownsampler.downsampleBatch :119 → downsamplePart :192: rebuild
off-heap partition, mark periods, run ChunkDownsamplers per resolution,
re-encode, persist to the downsample keyspace).

TPU-native shape: one process per shard batch, all per-period math as ONE
device program per [S, N] tile batch (downsample/kernels.py), host only
decoding input chunks and encoding output chunks. Output lands in the same
ColumnStore under the derived dataset ``<dataset>_ds_<res>`` with the
schema's declared downsample schema (gauge → ds-gauge, prom-counter →
prom-counter), so the ordinary query path (and the downsampled-store
resolution selector) reads it like any other dataset.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.record import PartKey, RecordContainer
from filodb_tpu.core.schemas import (DEFAULT_SCHEMAS, ColumnType, DatasetRef,
                                     Schemas)
from filodb_tpu.downsample import kernels
from filodb_tpu.lint.capacity import capacity
from filodb_tpu.memory import vectors as bv
from filodb_tpu.query.tpu import _TS_PAD, _next_pow2


def ds_dataset(dataset: str, res_ms: int) -> str:
    """Derived downsample dataset name (reference: separate downsample
    keyspace/cluster per resolution, DownsamplerSettings)."""
    return f"{dataset}_ds_{res_ms}"


@dataclass
class DownsampleStats:
    partitions_read: int = 0
    samples_read: int = 0
    samples_written: int = 0
    chunks_written: int = 0
    skipped_schemas: Dict[str, int] = field(default_factory=dict)


class DownsamplerJob:
    """Batch-downsample one shard of one dataset into all resolutions."""

    def __init__(self, column_store, schemas: Optional[Schemas] = None,
                 resolutions: Sequence[int] = (300_000, 3_600_000),
                 batch_series: int = 256):
        self.store = column_store
        self.schemas = schemas or DEFAULT_SCHEMAS
        self.resolutions = tuple(resolutions)
        self.batch_series = batch_series

    # -- input ------------------------------------------------------------
    def _load_partitions(self, dataset: str, shard: int):
        """Decode every persisted partition's (ts, value-column) arrays.
        Yields (part_key, schema, ts, vals). For histogram schemas vals is
        a dict: {"cols": [per-double-column f64 arrays...],
        "hist": [n, nb] f64, "scheme": bucket scheme}."""
        for e in self.store.scan_part_keys(dataset, shard):
            pk = PartKey.from_bytes(e.part_key)
            schema = self.schemas.by_id(pk.schema_id)
            vci = schema.value_column_index()
            col = schema.columns[vci]
            if col.col_type == ColumnType.HISTOGRAM:
                got = self._load_hist_partition(dataset, shard, e, schema,
                                                vci)
                if got is not None:
                    yield pk, schema, got[0], got[1]
                else:
                    yield pk, schema, None, None      # counted as skipped
                continue
            ts_parts, val_parts = [], []
            for c in self.store.read_chunks(dataset, shard, e.part_key):
                ts_parts.append(bv.decode_longs(c.vectors[0]))
                val_parts.append(bv.decode_doubles(c.vectors[vci]))
            if not ts_parts:
                continue
            yield (pk, schema, np.concatenate(ts_parts),
                   np.concatenate(val_parts))

    def _load_hist_partition(self, dataset, shard, e, schema, vci):
        """All columns of a histogram partition: (ts, payload dict).
        Only the (ts, sum, count, h) shape is handled (prom-histogram /
        delta-histogram layout); wider schemas are skipped."""
        from filodb_tpu.memory import histogram as bh
        dbl_idx = [i for i, c in enumerate(schema.columns)
                   if i != 0 and i != vci]
        if len(schema.columns) != 4 or len(dbl_idx) != 2:
            return None
        ts_parts, hist_parts, dbl_parts = [], [], [[] for _ in dbl_idx]
        scheme = None
        les = None
        for c in self.store.read_chunks(dataset, shard, e.part_key):
            ts_parts.append(bv.decode_longs(c.vectors[0]))
            sch, _, mat = bh.decode_histograms(c.vectors[vci])
            cur_les = sch.les()
            if scheme is None:
                scheme, les = sch, cur_les
            elif not np.array_equal(les, cur_les):
                return None     # bucket boundaries changed mid-history
            hist_parts.append(mat)
            for j, di in enumerate(dbl_idx):
                dbl_parts[j].append(bv.decode_doubles(c.vectors[di]))
        if not ts_parts or scheme is None:
            return None
        return (np.concatenate(ts_parts),
                {"cols": [np.concatenate(p) for p in dbl_parts],
                 "hist": np.concatenate(hist_parts, axis=0),
                 "scheme": scheme})

    # -- output -----------------------------------------------------------
    def _out_shard(self, out_shards: Dict[str, TimeSeriesShard],
                   dataset: str, res: int, shard: int) -> TimeSeriesShard:
        name = ds_dataset(dataset, res)
        sh = out_shards.get(name)
        if sh is None:
            sh = TimeSeriesShard(DatasetRef(name), self.schemas, shard,
                                 column_store=self.store)
            out_shards[name] = sh
        return sh

    # -- the job ----------------------------------------------------------
    def run(self, dataset: str, shard: int,
            start_ms: Optional[int] = None,
            end_ms: Optional[int] = None) -> DownsampleStats:
        stats = DownsampleStats()
        gauges: List[Tuple[PartKey, object, np.ndarray, np.ndarray]] = []
        counters: List[Tuple[PartKey, object, np.ndarray, np.ndarray]] = []
        hists: List[Tuple[PartKey, object, np.ndarray, dict]] = []
        for pk, schema, ts, vals in self._load_partitions(dataset, shard):
            if ts is None or not schema.downsamplers:
                stats.skipped_schemas[schema.name] = \
                    stats.skipped_schemas.get(schema.name, 0) + 1
                continue
            if start_ms is not None or end_ms is not None:
                lo = np.searchsorted(ts, start_ms or 0, side="left")
                hi = np.searchsorted(ts, end_ms or (1 << 62), side="right")
                ts = ts[lo:hi]
                if isinstance(vals, dict):
                    vals = {"cols": [c[lo:hi] for c in vals["cols"]],
                            "hist": vals["hist"][lo:hi],
                            "scheme": vals["scheme"]}
                else:
                    vals = vals[lo:hi]
            if not ts.size:
                continue
            stats.partitions_read += 1
            stats.samples_read += int(ts.size)
            marker = schema.downsample_period_marker
            if isinstance(vals, dict):
                hists.append((pk, schema, ts, vals))
            elif marker.startswith("counter"):
                counters.append((pk, schema, ts, vals))
            else:
                gauges.append((pk, schema, ts, vals))

        out_shards: Dict[str, TimeSeriesShard] = {}
        for batch in _batches(gauges, self.batch_series):
            self._downsample_gauge_batch(batch, dataset, shard,
                                         out_shards, stats)
        for res in self.resolutions:
            for batch in _batches(counters, self.batch_series):
                self._downsample_counter_batch(batch, dataset, shard, res,
                                               out_shards, stats)
            for item in hists:
                self._downsample_hist_partition(item, dataset, shard, res,
                                                out_shards, stats)
        for sh in out_shards.values():
            sh.flush_all()
        stats.chunks_written = sum(
            s.stats.chunks_persisted for s in out_shards.values())
        return stats

    @capacity(
        "downsample-pack-buffers", bytes_per_sample=16.0,
        reason="the padded batch staging block the downsample kernels "
               "consume on device is [S, pow2(maxlen)] int64 "
               "timestamps (8 B) + f64 values (8 B) = 16 B per padded "
               "slot, alive for one batch dispatch (the lens vector "
               "and period outputs are host-side)")
    def _pack(self, batch):
        S = len(batch)
        maxlen = max(ts.size for _, _, ts, _ in batch)
        N = _next_pow2(maxlen)
        ts_pad = np.full((S, N), _TS_PAD, dtype=np.int64)
        vals_pad = np.zeros((S, N), dtype=np.float64)
        lens = np.zeros(S, dtype=np.int32)
        t_lo, t_hi = None, None
        for i, (_, _, ts, vals) in enumerate(batch):
            m = ~np.isnan(vals)
            ts, vals = ts[m], vals[m]
            n = ts.size
            ts_pad[i, :n] = ts
            vals_pad[i, :n] = vals
            lens[i] = n
            if n:
                t_lo = int(ts[0]) if t_lo is None else min(t_lo, int(ts[0]))
                t_hi = int(ts[-1]) if t_hi is None else max(t_hi,
                                                            int(ts[-1]))
        return ts_pad, vals_pad, lens, t_lo, t_hi

    @staticmethod
    def _w_bound(ts_pad, lens, res) -> int:
        """Static samples-per-period cap for the min/max gather."""
        d = np.diff(ts_pad, axis=1)
        valid = (np.arange(1, ts_pad.shape[1])[None, :] < lens[:, None])
        d = d[valid & (d > 0)]
        min_dt = int(d.min()) if d.size else res
        return min(_next_pow2(int(res // max(min_dt, 1)) + 2, 4),
                   max(int(ts_pad.shape[1]), 4))

    def _downsample_gauge_batch(self, batch, dataset, shard,
                                out_shards, stats) -> None:
        """All resolutions for one gauge batch: the finest level reads raw
        tiles, coarser levels cascade from the previous level (sum of sums,
        min of mins, ... — the multi-resolution trick that keeps device
        work O(samples + total periods))."""
        ts_pad, vals_pad, lens, t_lo, t_hi = self._pack(batch)
        if t_lo is None:
            return
        rc = kernels.regular_cadence(ts_pad, lens,
                                     int(min(self.resolutions)))
        prev = prev_res = prev_base = None
        for res in sorted(self.resolutions):
            base = (t_lo // res) * res
            nperiods = int((t_hi - base) // res) + 1
            if prev is not None and res % prev_res == 0 \
                    and (prev_base - base) % prev_res == 0:
                # coarser level from the finer one: aligned reshape when
                # the resolutions nest, gather cascade otherwise
                arrays = kernels.cascade_gauge_aligned(
                    prev, res // prev_res,
                    int((prev_base - base) // prev_res))
            else:
                arrays = None
                if rc is not None:
                    arrays = kernels.downsample_gauge_fast(
                        ts_pad, vals_pad, lens, base, res, nperiods,
                        cadence=rc)
                if arrays is None:
                    wb = self._w_bound(ts_pad, lens, res)
                    arrays = kernels.downsample_gauge_tiles(
                        ts_pad, vals_pad, lens, np.int64(base),
                        np.int64(res), nperiods, wb)
            self._emit_gauge(batch, [np.asarray(a) for a in arrays],
                             dataset, res, shard, out_shards, stats)
            prev, prev_res, prev_base = arrays, res, base

    def _emit_gauge(self, batch, arrays, dataset, res, shard, out_shards,
                    stats) -> None:
        sums, cnts, mins, maxs, last_v, last_ts = arrays
        out = self._out_shard(out_shards, dataset, res, shard)
        ds_schema = self.schemas.by_name("ds-gauge")
        for i, (pk, schema, _, _) in enumerate(batch):
            has = cnts[i] > 0
            if not has.any():
                continue
            cont = RecordContainer(ds_schema)
            out_pk = PartKey(ds_schema.schema_id, pk.labels)
            c = cnts[i][has]
            for t, mn, mx, s, cc in zip(last_ts[i][has], mins[i][has],
                                        maxs[i][has], sums[i][has], c):
                cont.add(out_pk, int(t), mn, mx, s, cc, s / cc)
                stats.samples_written += 1
            out.ingest(cont)

    def _downsample_counter_batch(self, batch, dataset, shard, res,
                                  out_shards, stats) -> None:
        ts_pad, vals_pad, lens, t_lo, t_hi = self._pack(batch)
        if t_lo is None:
            return
        base = (t_lo // res) * res
        nperiods = int((t_hi - base) // res) + 1
        mask = np.asarray(kernels.counter_emit_mask(
            ts_pad, vals_pad, lens, np.int64(base), np.int64(res), nperiods))
        out = self._out_shard(out_shards, dataset, res, shard)
        for i, (pk, schema, _, _) in enumerate(batch):
            m = mask[i]
            if not m.any():
                continue
            ds_name = schema.downsample_schema or schema.name
            ds_schema = self.schemas.by_name(ds_name)
            cont = RecordContainer(ds_schema)
            out_pk = PartKey(ds_schema.schema_id, pk.labels)
            for t, v in zip(ts_pad[i][m], vals_pad[i][m]):
                cont.add(out_pk, int(t), float(v))
                stats.samples_written += 1
            out.ingest(cont)


    def _downsample_hist_partition(self, item, dataset, shard, res,
                                   out_shards, stats) -> None:
        """One histogram partition → ds chunks at one resolution.

        Cumulative schemas (downsample-period-marker = counter(N), e.g.
        prom-histogram: hLast/dLast downsamplers) keep the period-boundary
        samples of every column, marked by counter dips of the count
        column — rate() over the ds data then sees the same increases.
        Delta schemas (time marker, hSum/dSum) sum every column per period.
        (ChunkDownsampler.scala:38-353 HistSumDownsampler/LastValueHDowns.)"""
        pk, schema, ts, payload = item
        sums, cnts = payload["cols"]
        hist, scheme = payload["hist"], payload["scheme"]
        marker = schema.downsample_period_marker
        base = (int(ts[0]) // res) * res
        nperiods = int((int(ts[-1]) - base) // res) + 1
        ds_name = schema.downsample_schema or schema.name
        ds_schema = self.schemas.by_name(ds_name)
        out = self._out_shard(out_shards, dataset, res, shard)
        cont = RecordContainer(ds_schema)
        out_pk = PartKey(ds_schema.schema_id, pk.labels)
        if marker.startswith("counter"):
            n = ts.size
            N = _next_pow2(n)       # pow2 pad: kernel compile reuse
            ts_p = np.full(N, _TS_PAD, dtype=np.int64)
            ts_p[:n] = ts
            cn_p = np.zeros(N)
            cn_p[:n] = cnts
            mask = np.asarray(kernels.counter_emit_mask(
                ts_p[None, :], cn_p[None, :],
                np.array([n], dtype=np.int32),
                np.int64(base), np.int64(res), nperiods))[0][:n]
            for i in np.nonzero(mask)[0]:
                cont.add(out_pk, int(ts[i]), float(sums[i]), float(cnts[i]),
                         (scheme, hist[i].astype(np.int64)))
                stats.samples_written += 1
        else:
            period = np.clip((ts - base) // res, 0, nperiods - 1)
            pe_sum = np.zeros(nperiods)
            pe_cnt = np.zeros(nperiods)
            pe_hist = np.zeros((nperiods, hist.shape[1]))
            pe_n = np.bincount(period, minlength=nperiods)
            np.add.at(pe_sum, period, sums)
            np.add.at(pe_cnt, period, cnts)
            np.add.at(pe_hist, period, hist)
            last_ts = np.zeros(nperiods, dtype=np.int64)
            last_ts[period] = ts       # sorted: last write per period wins
            for p in np.nonzero(pe_n)[0]:
                cont.add(out_pk, int(last_ts[p]), float(pe_sum[p]),
                         float(pe_cnt[p]),
                         (scheme, pe_hist[p].astype(np.int64)))
                stats.samples_written += 1
        if len(cont):
            out.ingest(cont)


def _batches(items, size):
    for i in range(0, len(items), size):
        yield items[i:i + size]
