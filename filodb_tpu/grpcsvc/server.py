"""gRPC query service server (PromQLGrpcServer.scala:44).

Serves two unary RPCs on `/filodb.QueryService/`:

  * ``FetchRaw`` — the leaf-dispatch data plane: span-bounded raw series
    with node-scoped snapshot keys, protobuf + NibblePack on the wire
    (replaces the base64-JSON POST /api/v1/raw hop).
  * ``Exec`` — whole-query pushdown / federation: evaluate a PromQL
    string locally and return the grid as packed columns
    (exec/PromQlRemoteExec.scala without the JSON).

Implemented over grpcio's generic handlers with identity serializers —
message codecs live in grpcsvc.wire; no protoc codegen needed."""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Optional

from filodb_tpu.grpcsvc import wire
from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.query import qos

_SERVICE = "filodb.QueryService"


def _req_qos(req) -> Optional[qos.QosContext]:
    """QoS context of a peer hop: tenant/priority decoded off the wire,
    ``forced`` set — the ENTRY node made the admission decision, this
    leg only inherits the charge and the batcher ordering. None when
    the caller sent no tenant (pre-QoS peer or budgets off)."""
    if not req.get("tenant"):
        return None
    return qos.QosContext(tenant=req["tenant"],
                          priority=int(req.get("priority") or 0),
                          forced=True)


@guarded_by("_rpc_lock", "rpcs_served")
class GrpcQueryServer:
    """Binds the service to a FiloHttpServer's query surface (the HTTP
    server owns planners, shard maps, and guardrails; this is a second
    wire onto the same brain)."""

    def __init__(self, http_server, port: int = 0, host: str = "127.0.0.1",
                 max_workers: int = 8):
        import grpc
        self.http = http_server
        self.rpcs_served = 0
        # handlers run on ThreadPoolExecutor threads; unsynchronized
        # `+= 1` would lose increments the /metrics gauge relies on
        self._rpc_lock = threading.Lock()
        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                name = details.method.rsplit("/", 1)[-1]
                if details.method.startswith(f"/{_SERVICE}/"):
                    if name == "FetchRaw":
                        return grpc.unary_unary_rpc_method_handler(
                            outer._fetch_raw,
                            request_deserializer=lambda b: b,
                            response_serializer=lambda b: b)
                    if name == "Exec":
                        return grpc.unary_unary_rpc_method_handler(
                            outer._exec,
                            request_deserializer=lambda b: b,
                            response_serializer=lambda b: b)
                return None

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        # grpc spawns an internal completion-queue polling thread the
        # AST cannot see; register its actual entry point so the thread
        # inventory and the sampling profiler both attribute it
        try:
            from grpc import _server as _grpc_server
            thread_root("grpc-serve")(_grpc_server._serve)
        except (ImportError, AttributeError):
            pass                # private surface — tolerate its absence

    def start(self) -> "GrpcQueryServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=1)

    # -- RPC implementations ---------------------------------------------

    @staticmethod
    def _req_deadline(req, default_timeout_s: float):
        """Server-side deadline propagation: the caller forwarded its
        remaining budget; this node inherits it (clipped to the local
        default so a buggy caller can't grant itself infinity)."""
        from filodb_tpu.parallel.resilience import Deadline
        ms = int(req.get("deadline_ms") or 0)
        if ms <= 0:
            return None
        return Deadline.after(min(ms / 1000.0, default_timeout_s))

    def _req_trace(self, req):
        """A local Trace for a propagated context (trace propagation on
        the binary plane): spans recorded here ship back in the
        response and the CALLER's recorder stitches them — nothing is
        stored on this node. None (no tracing at all) when the caller
        didn't forward a context."""
        ctx = obs_trace.parse_context(req.get("trace"))
        if ctx is None:
            return None
        tracer = getattr(self.http, "tracer", None)
        if tracer is not None:
            return tracer.start(ctx)
        return obs_trace.Trace(ctx[0], root_parent=ctx[1])

    def _fetch_raw(self, request: bytes, context) -> bytes:
        from filodb_tpu.query.model import QueryError, QueryStats
        with self._rpc_lock:
            self.rpcs_served += 1
        tr = None
        try:
            req = wire.decode_raw_request(request)
            tr = self._req_trace(req)
            qctx = _req_qos(req)
            adm = getattr(self.http, "admission", None)
            if qctx is not None and adm is not None \
                    and adm.budgets.enabled:
                # budget inheritance: the leg's cost lands on the same
                # tenant bucket the entry node charged (forced — a leg
                # must never shed mid-query)
                shards = self.http.shards_by_dataset.get(
                    req["dataset"], ())
                adm.budgets.charge_forced(
                    qctx.tenant, qos.estimate_leaf_cost(
                        req["filters"], shards, req["start_ms"],
                        req["end_ms"]))
            with qos.activate(qctx), obs_trace.activate(tr), \
                    obs_trace.span("peer-fetch-raw",
                                   node=getattr(self.http, "node_id", "")
                                   or "", dataset=req["dataset"]):
                series = self.http.leaf_select(
                    req["dataset"], req["filters"], req["start_ms"],
                    req["end_ms"], req["column"], req["shards"],
                    span_snap=req["span_snap"], stats=QueryStats(),
                    deadline=self._req_deadline(
                        req, getattr(self.http, "query_timeout_s",
                                     30.0)))
            if series is None:
                return wire.encode_raw_response(
                    [], error=f"dataset {req['dataset']} not set up",
                    trace_spans=obs_trace.spans_wire(tr))
            return wire.encode_raw_response(
                series, trace_spans=obs_trace.spans_wire(tr))
        except QueryError as e:
            return wire.encode_raw_response(
                [], error=str(e), trace_spans=obs_trace.spans_wire(tr))
        except Exception as e:           # wire errors back, never crash
            return wire.encode_raw_response(
                [], error=f"internal: {type(e).__name__}: {e}",
                trace_spans=obs_trace.spans_wire(tr))

    def _exec(self, request: bytes, context) -> bytes:
        from filodb_tpu.promql.parser import (TimeStepParams, parse_query,
                                              parse_query_range)
        from filodb_tpu.query.model import (GridResult, QueryError,
                                            ScalarResult)
        with self._rpc_lock:
            self.rpcs_served += 1
        tr = None
        try:
            req = wire.decode_exec_request(request)
            tr = self._req_trace(req)
            if req["local_only"] and req.get("expect_shards"):
                # stale-routing guard (ExecRequest field 12): bounce
                # instead of silently evaluating over a subset when a
                # planned handoff moved one of the expected shards away
                have = {getattr(s, "shard_num", i) for i, s in
                        enumerate(self.http.shards_by_dataset.get(
                            req["dataset"], ()))}
                missing = [n for n in req["expect_shards"]
                           if n not in have]
                if missing:
                    from filodb_tpu.query.model import StaleRoutingError
                    mapper = self.http.shard_mapper
                    self.http.stale_routing_bounces += 1
                    err = StaleRoutingError(
                        owners={n: mapper.node_of(n) for n in missing}
                        if mapper is not None else {},
                        epoch=getattr(mapper, "topology_epoch", 0)
                        if mapper is not None else 0,
                        node=getattr(self.http, "node_id", "") or "",
                        detail=f"shards {sorted(missing)} are not "
                               f"served here")
                    return wire.encode_exec_response(
                        None, error=str(err),
                        trace_spans=obs_trace.spans_wire(tr))
            engine = self.http.make_planner(
                req["dataset"], local_dispatch=req["local_only"],
                deadline=self._req_deadline(
                    req, getattr(self.http, "query_timeout_s", 30.0)),
                no_result_cache=bool(req.get("no_cache")))
            if engine is None:
                return wire.encode_exec_response(
                    None, error=f"dataset {req['dataset']} not set up",
                    trace_spans=obs_trace.spans_wire(tr))
            qctx = _req_qos(req)
            with qos.activate(qctx), obs_trace.activate(tr), \
                    obs_trace.span("peer-exec",
                                   node=getattr(self.http, "node_id", "")
                                   or "", dataset=req["dataset"]):
                if req["plan_wire"]:
                    # structural plan tree: no PromQL printer/parser in
                    # the loop (exec_plan.proto capability)
                    from filodb_tpu.query.planwire import plan_from_wire
                    plan = plan_from_wire(req["plan_wire"])
                elif req["step_ms"] > 0:
                    plan = parse_query_range(
                        req["query"],
                        TimeStepParams(req["start_ms"] // 1000,
                                       req["step_ms"] // 1000,
                                       req["end_ms"] // 1000))
                else:
                    plan = parse_query(req["query"],
                                       req["start_ms"] // 1000)
                adm = getattr(self.http, "admission", None)
                if qctx is not None and adm is not None \
                        and adm.budgets.enabled:
                    # budget inheritance on the exec plane: forced —
                    # the entry node already made the shed decision
                    adm.budgets.charge_forced(
                        qctx.tenant,
                        engine.estimate_cost(plan).total)
                rc = getattr(self.http, "result_cache", None)
                if rc is not None and not req["plan_wire"] \
                        and req["step_ms"] > 0:
                    # pushdown/federation range queries share the
                    # node's results cache (the &cache=false escape
                    # hatch rides ExecRequest field 11 as no_cache)
                    res, _ses = rc.execute(
                        engine, req["dataset"], req["query"], plan,
                        req["start_ms"], req["step_ms"], req["end_ms"],
                        bypass=bool(req.get("no_cache")))
                else:
                    res = engine.execute(plan)
            if isinstance(res, ScalarResult):
                res = GridResult(res.steps, [{}], res.values[None, :])
            return wire.encode_exec_response(
                res, stats=engine.stats,
                trace_spans=obs_trace.spans_wire(tr))
        except QueryError as e:
            return wire.encode_exec_response(
                None, error=str(e),
                trace_spans=obs_trace.spans_wire(tr))
        except Exception as e:
            return wire.encode_exec_response(
                None, error=f"internal: {type(e).__name__}: {e}",
                trace_spans=obs_trace.spans_wire(tr))
