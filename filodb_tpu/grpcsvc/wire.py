"""Protobuf wire codecs for the gRPC query service.

This fills the role of the reference's grpc/src/main/protobuf
(query_service.proto Request/Response, range_vector.proto
SerializedRangeVector) but defines its OWN message schema — the field
layout below is not interoperable with the reference service; only the
protobuf encoding primitives (the same varint / length-delimited field
encoding protoc emits, reused from the remote-read implementation) are
shared. Sample columns ride NibblePack (memory/format/NibblePack.scala
semantics — delta-packed sorted timestamps, XOR-packed doubles),
typically 2-6x smaller than the base64-JSON control-plane wire they
replace.

Messages (field numbers):
  Filter        {1: label, 2: op, 3: value}
  RawRequest    {1: dataset, 2: Filter*, 3: start_ms, 4: end_ms,
                 5: column, 6: shards packed, 7: span_snap,
                 8: deadline_ms (caller's remaining budget; 0 = none),
                 9: trace ctx "trace_id-parent_span-1" (absent = untraced),
                 10: tenant (QoS budget inheritance; absent = default),
                 11: priority class (absent = interactive)}
  SnapKey       {1: node, 2: ds, 3: shard, 4: part, 5: num_chunks,
                 6: col, 7: start_ms, 8: end_ms}
  Srv           {1: label entry {1:k,2:v}*, 2: n, 3: ts nibble,
                 4: vals nibble, 5: is_counter, 6: nb, 7: les f64le,
                 8: drops nibble, 9: chunk_len+1, 10: SnapKey}
  RawResponse   {1: Srv*, 2: error,
                 3: trace spans (JSON list; present only when traced)}
  ExecRequest   {1: dataset, 2: query, 3: start_ms, 4: step_ms,
                 5: end_ms, 6: local_only, 7: hist_wire,
                 9: deadline_ms (caller's remaining budget; 0 = none),
                 10: trace ctx "trace_id-parent_span-1",
                 11: no_cache (results-cache bypass propagation),
                 12: expect_shards packed (stale-routing guard on
                 local_only pushdown hops),
                 13: tenant (QoS budget inheritance; absent = default),
                 14: priority class (absent = interactive)}
  ExecSeries    {1: label entry*, 2: values nibble (grid-aligned,
                 NaN where absent), 3: hist nibble flat, 4: nb}
  ExecResponse  {1: ExecSeries*, 2: error, 3: steps nibble,
                 4: series_scanned, 5: samples_scanned,
                 6: les f64le, 7: scalar flag, 8: partial flag,
                 9: warning string*,
                 10: trace spans (JSON list; present only when traced)}

The trace fields carry the Dapper-style propagated context (obs/trace):
the caller forwards its trace id + parent span id; the peer records its
spans under that parent and ships them back, so the entry node's
recorder holds ONE stitched trace across the gRPC plane. Span payloads
ride as JSON — they exist only on sampled traces, so wire compactness
is irrelevant next to the NibblePack sample columns.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.http.remote_read import (_fields, _ld, _read_uvarint,
                                         _signed, _uvarint, _vi)
from filodb_tpu.memory import nibblepack as np_codec
from filodb_tpu.query.model import RawSeries


def _pack_i64(vals: np.ndarray) -> bytes:
    """NibblePack a sorted/monotone-friendly int64 column (delta)."""
    out = bytearray()
    np_codec.pack_delta([int(v) for v in np.asarray(vals, np.int64)], out)
    return bytes(out)


def _unpack_i64(buf: bytes, n: int) -> np.ndarray:
    if n == 0:
        return np.zeros(0, np.int64)
    vals, _ = np_codec.unpack_delta(buf, 0, n)
    return np.asarray(vals, np.int64)


def _pack_f64(vals: np.ndarray) -> bytes:
    out = bytearray()
    np_codec.pack_doubles(np.asarray(vals, np.float64).ravel(), out)
    return bytes(out)


def _unpack_f64(buf: bytes, n: int) -> np.ndarray:
    if n == 0:
        return np.zeros(0, np.float64)
    vals, _ = np_codec.unpack_double_xor(buf, 0, n)
    return np.asarray(vals, np.float64)


def _labels_enc(labels: Dict[str, str]) -> bytes:
    out = bytearray()
    for k, v in labels.items():
        entry = _ld(1, k.encode()) + _ld(2, v.encode())
        out += _ld(1, entry)
    return bytes(out)


def _entry_dec(buf: bytes) -> Tuple[str, str]:
    k = v = ""
    for f, _, val in _fields(buf):
        if f == 1:
            k = val.decode()
        elif f == 2:
            v = val.decode()
    return k, v


# -- RawRequest --------------------------------------------------------------

def encode_raw_request(dataset: str, filters, start_ms: int, end_ms: int,
                       column: Optional[str],
                       shards: Optional[Sequence[int]],
                       span_snap: bool = True,
                       deadline_ms: int = 0,
                       trace_ctx: str = "",
                       tenant: str = "",
                       priority: int = 0) -> bytes:
    out = bytearray(_ld(1, dataset.encode()))
    for f in filters:
        out += _ld(2, _ld(1, f.label.encode()) + _ld(2, f.op.encode())
                   + _ld(3, f.value.encode()))
    out += _vi(3, int(start_ms)) + _vi(4, int(end_ms))
    if column:
        out += _ld(5, column.encode())
    if shards is not None:
        out += _ld(6, b"".join(_uvarint(int(s)) for s in shards))
    out += _vi(7, 1 if span_snap else 0)
    if deadline_ms > 0:
        out += _vi(8, int(deadline_ms))
    if trace_ctx:
        out += _ld(9, trace_ctx.encode())
    if tenant:
        out += _ld(10, tenant.encode())
    if priority:
        out += _vi(11, int(priority))
    return bytes(out)


def decode_raw_request(buf: bytes) -> Dict:
    from filodb_tpu.core.index import ColumnFilter
    req = {"dataset": "", "filters": [], "start_ms": 0, "end_ms": 0,
           "column": None, "shards": None, "span_snap": True,
           "deadline_ms": 0, "trace": "", "tenant": "", "priority": 0}
    for f, _, v in _fields(buf):
        if f == 1:
            req["dataset"] = v.decode()
        elif f == 2:
            lbl = op = val = ""
            for ff, _, vv in _fields(v):
                if ff == 1:
                    lbl = vv.decode()
                elif ff == 2:
                    op = vv.decode()
                elif ff == 3:
                    val = vv.decode()
            req["filters"].append(ColumnFilter(lbl, op, val))
        elif f == 3:
            req["start_ms"] = _signed(v)
        elif f == 4:
            req["end_ms"] = _signed(v)
        elif f == 5:
            req["column"] = v.decode()
        elif f == 6:
            shards, pos = [], 0
            while pos < len(v):
                s, pos = _read_uvarint(v, pos)
                shards.append(s)
            req["shards"] = shards
        elif f == 7:
            req["span_snap"] = bool(v)
        elif f == 8:
            req["deadline_ms"] = _signed(v)
        elif f == 9:
            req["trace"] = v.decode()
        elif f == 10:
            req["tenant"] = v.decode()
        elif f == 11:
            req["priority"] = _signed(v)
    return req


# -- SerializedRangeVector ---------------------------------------------------

def _snap_enc(snap: Tuple) -> bytes:
    node, ds, shard, part, nchunks, col, start, end = snap
    return (_ld(1, str(node).encode()) + _ld(2, str(ds).encode())
            + _vi(3, int(shard)) + _vi(4, int(part)) + _vi(5, int(nchunks))
            + _vi(6, int(col)) + _vi(7, int(start)) + _vi(8, int(end)))


def _snap_dec(buf: bytes) -> Tuple:
    vals = ["", "", 0, 0, 0, 0, 0, 0]
    for f, _, v in _fields(buf):
        if f in (1, 2):
            vals[f - 1] = v.decode()
        elif 3 <= f <= 8:
            vals[f - 1] = _signed(v)
    return tuple(vals)


def encode_series(s: RawSeries) -> bytes:
    out = bytearray(_labels_enc(dict(s.labels)))
    n = int(s.ts.size)
    out += _vi(2, n)
    if n:
        out += _ld(3, _pack_i64(s.ts))
        out += _ld(4, _pack_f64(s.values))
    out += _vi(5, 1 if s.is_counter else 0)
    if s.values.ndim == 2:
        out += _vi(6, int(s.values.shape[1]))
    if s.bucket_les is not None:
        out += _ld(7, np.asarray(s.bucket_les, "<f8").tobytes())
    if s.hist_drop_rows is not None:
        d = np.asarray(s.hist_drop_rows, np.int64)
        out += _ld(8, _uvarint(d.size) + _pack_i64(d))
    if s.chunk_len >= 0:
        out += _vi(9, int(s.chunk_len) + 1)
    if s.snapshot_key is not None:
        out += _ld(10, _snap_enc(s.snapshot_key))
    return bytes(out)


def decode_series(buf: bytes) -> RawSeries:
    labels: Dict[str, str] = {}
    n = 0
    ts_b = vals_b = b""
    is_counter = False
    nb = 0
    les = None
    drops_b = None
    chunk_len = -1
    snap = None
    for f, _, v in _fields(buf):
        if f == 1:
            k, val = _entry_dec(v)
            labels[k] = val
        elif f == 2:
            n = v
        elif f == 3:
            ts_b = v
        elif f == 4:
            vals_b = v
        elif f == 5:
            is_counter = bool(v)
        elif f == 6:
            nb = v
        elif f == 7:
            les = np.frombuffer(v, "<f8")
        elif f == 8:
            drops_b = v
        elif f == 9:
            chunk_len = v - 1
        elif f == 10:
            snap = _snap_dec(v)
    ts = _unpack_i64(ts_b, n)
    total = n * nb if nb else n
    vals = _unpack_f64(vals_b, total)
    if nb:
        vals = vals.reshape(n, nb)
    drops = None
    if drops_b is not None:
        nd, pos = _read_uvarint(drops_b, 0)
        drops = _unpack_i64(drops_b[pos:], nd)
    return RawSeries(labels=labels, ts=ts, values=vals,
                     is_counter=is_counter, bucket_les=les,
                     hist_drop_rows=drops, snapshot_key=snap,
                     chunk_len=chunk_len)


def encode_raw_response(series: Sequence[RawSeries],
                        error: str = "",
                        trace_spans: bytes = b"") -> bytes:
    out = bytearray()
    for s in series:
        out += _ld(1, encode_series(s))
    if error:
        out += _ld(2, error.encode())
    if trace_spans:
        out += _ld(3, trace_spans)
    return bytes(out)


def decode_raw_response(buf: bytes):
    """-> (series, error, trace_spans_bytes)."""
    series: List[RawSeries] = []
    error = ""
    trace_spans = b""
    for f, _, v in _fields(buf):
        if f == 1:
            series.append(decode_series(v))
        elif f == 2:
            error = v.decode()
        elif f == 3:
            trace_spans = v
    return series, error, trace_spans


# -- Exec (whole-query pushdown / federation) --------------------------------

def encode_exec_request(dataset: str, query: str, start_ms: int,
                        step_ms: int, end_ms: int,
                        local_only: bool = True,
                        plan_wire: bytes = b"",
                        deadline_ms: int = 0,
                        trace_ctx: str = "",
                        no_cache: bool = False,
                        expect_shards=None,
                        tenant: str = "",
                        priority: int = 0) -> bytes:
    """Field 8 carries a STRUCTURAL LogicalPlan tree (query.planwire) —
    the reference's exec_plan.proto capability; the printed query text
    stays alongside for debuggability and older peers. Field 9 carries
    the caller's remaining deadline budget in ms (server-side deadline
    propagation; 0/absent = none). Field 10 carries the propagated
    trace context (absent = untraced). Field 11 propagates the caller's
    results-cache bypass (&cache=false) so the peer skips its cache.
    Field 12 (packed uvarints) names the shards the caller expects the
    peer to serve on a local_only hop — the peer bounces stale_routing
    instead of silently evaluating over a subset after a handoff."""
    out = (_ld(1, dataset.encode()) + _ld(2, query.encode())
           + _vi(3, int(start_ms)) + _vi(4, int(step_ms))
           + _vi(5, int(end_ms)) + _vi(6, 1 if local_only else 0))
    if plan_wire:
        out += _ld(8, plan_wire)
    if deadline_ms > 0:
        out += _vi(9, int(deadline_ms))
    if trace_ctx:
        out += _ld(10, trace_ctx.encode())
    if no_cache:
        out += _vi(11, 1)
    if expect_shards:
        out += _ld(12, b"".join(_uvarint(int(s))
                                for s in expect_shards))
    if tenant:
        out += _ld(13, tenant.encode())
    if priority:
        out += _vi(14, int(priority))
    return out


def decode_exec_request(buf: bytes) -> Dict:
    req = {"dataset": "", "query": "", "start_ms": 0, "step_ms": 0,
           "end_ms": 0, "local_only": True, "plan_wire": b"",
           "deadline_ms": 0, "trace": "", "no_cache": False,
           "expect_shards": None, "tenant": "", "priority": 0}
    for f, _, v in _fields(buf):
        if f == 1:
            req["dataset"] = v.decode()
        elif f == 2:
            req["query"] = v.decode()
        elif f == 3:
            req["start_ms"] = _signed(v)
        elif f == 4:
            req["step_ms"] = _signed(v)
        elif f == 5:
            req["end_ms"] = _signed(v)
        elif f == 6:
            req["local_only"] = bool(v)
        elif f == 8:
            req["plan_wire"] = v
        elif f == 9:
            req["deadline_ms"] = _signed(v)
        elif f == 10:
            req["trace"] = v.decode()
        elif f == 11:
            req["no_cache"] = bool(v)
        elif f == 12:
            shards, pos = [], 0
            while pos < len(v):
                s, pos = _read_uvarint(v, pos)
                shards.append(s)
            req["expect_shards"] = shards
        elif f == 13:
            req["tenant"] = v.decode()
        elif f == 14:
            req["priority"] = _signed(v)
    return req


def encode_exec_response(grid, stats=None, error: str = "",
                         trace_spans: bytes = b"") -> bytes:
    """GridResult -> ExecResponse (grid-aligned nibble-packed rows)."""
    out = bytearray()
    if error:
        out += _ld(2, error.encode())
        if trace_spans:
            out += _ld(10, trace_spans)
        return bytes(out)
    steps = np.asarray(grid.steps, np.int64)
    out += _ld(3, _uvarint(steps.size) + _pack_i64(steps))
    nb = 0
    if grid.hist_values is not None and grid.bucket_les is not None:
        nb = int(grid.bucket_les.size)
        out += _ld(6, np.asarray(grid.bucket_les, "<f8").tobytes())
    for i, key in enumerate(grid.keys):
        msg = bytearray(_labels_enc(dict(key)))
        msg += _ld(2, _pack_f64(grid.values[i]))
        if nb and grid.hist_values is not None \
                and grid.hist_values[i] is not None:
            msg += _ld(3, _pack_f64(grid.hist_values[i].ravel()))
            msg += _vi(4, nb)
        out += _ld(1, bytes(msg))
    if stats is not None:
        out += _vi(4, int(getattr(stats, "series_scanned", 0)))
        out += _vi(5, int(getattr(stats, "samples_scanned", 0)))
    # degraded-mode provenance (the HTTP plane's "partial"/"warnings"
    # envelope): union of grid- and stats-level markers so a pushdown
    # peer's degradation survives the binary hop
    partial = bool(getattr(grid, "partial", False)) \
        or bool(getattr(stats, "partial", False))
    warnings = list(getattr(grid, "warnings", ()) or ())
    for w in getattr(stats, "warnings", ()) or ():
        if w not in warnings:
            warnings.append(w)
    if partial:
        out += _vi(8, 1)
    for w in warnings:
        out += _ld(9, str(w).encode())
    if trace_spans:
        out += _ld(10, trace_spans)
    return bytes(out)


def decode_exec_response(buf: bytes):
    """-> (steps i64, keys, values [S,T], hist [S,T,nb]|None, les|None,
    stats dict, error). The peer's trace spans (if any) ride
    ``stats["trace_spans"]`` as raw JSON bytes."""
    steps = np.zeros(0, np.int64)
    rows = []
    les = None
    stats = {"seriesScanned": 0, "samplesScanned": 0,
             "partial": False, "warnings": [], "trace_spans": b""}
    error = ""
    for f, _, v in _fields(buf):
        if f == 3:
            steps = v          # count-prefixed; decoded below
        elif f == 1:
            rows.append(v)
        elif f == 2:
            error = v.decode()
        elif f == 4:
            stats["seriesScanned"] = v
        elif f == 5:
            stats["samplesScanned"] = v
        elif f == 6:
            les = np.frombuffer(v, "<f8")
        elif f == 8:
            stats["partial"] = bool(v)
        elif f == 9:
            stats["warnings"].append(v.decode())
        elif f == 10:
            stats["trace_spans"] = v
    if error:
        return None, [], None, None, None, stats, error
    # nibble streams decode in 8-word groups, so counts ride explicitly
    n, pos = _read_uvarint(steps, 0)
    steps_arr = _unpack_i64(steps[pos:], n) if n else np.zeros(0, np.int64)
    keys, values, hists = [], [], []
    any_hist = False
    for row in rows:
        labels: Dict[str, str] = {}
        vals_b = b""
        hist_b = None
        nb = 0
        for f, _, v in _fields(row):
            if f == 1:
                k, val = _entry_dec(v)
                labels[k] = val
            elif f == 2:
                vals_b = v
            elif f == 3:
                hist_b = v
            elif f == 4:
                nb = v
        keys.append(labels)
        values.append(_unpack_f64(vals_b, n))
        if hist_b is not None and nb:
            any_hist = True
            hists.append(_unpack_f64(hist_b, n * nb).reshape(n, nb))
        else:
            hists.append(None)
    vals = np.vstack(values) if values else np.zeros((0, n))
    hv = None
    if any_hist:
        nb = les.size
        hv = np.stack([h if h is not None
                       else np.full((n, nb), np.nan) for h in hists])
    return steps_arr, keys, vals, hv, les, stats, error
