"""gRPC query service: the binary data plane for peer leaf dispatch and
cross-cluster federation (http/PromQLGrpcServer.scala:44;
grpc/src/main/protobuf/query_service.proto, range_vector.proto).

Runs on the real grpcio runtime (persistent HTTP/2 channels, multiplexed
RPCs) with hand-encoded protobuf messages — no codegen; the wire module
builds the same length-delimited field encoding the reference's .proto
files compile to, and sample payloads ride NibblePack (delta-packed
timestamps, XOR-packed doubles), the reference's own chunk codec.
"""

from filodb_tpu.grpcsvc.client import (GrpcRemoteExec,  # noqa: F401
                                       GrpcShardGroup)
from filodb_tpu.grpcsvc.server import GrpcQueryServer  # noqa: F401
