"""gRPC query service clients: drop-in peers for the planner.

``GrpcShardGroup`` replaces ``parallel.cluster.RemoteShardGroup`` (leaf
dispatch) and ``GrpcRemoteExec`` replaces ``PromQlRemoteExec``
(whole-query pushdown / federation) when a peer advertises a gRPC
address. Channels are cached per address — gRPC keeps one persistent
HTTP/2 connection per peer and multiplexes RPCs over it
(PromQLGrpcServer.scala client side; RemoteActorPlanDispatcher).

Degraded-mode behavior (parallel/resilience.py): transport failures map
to TransportError, retry per policy inside the query's deadline budget,
and count against the peer address's circuit breaker. When the binary
data plane is exhausted (retries spent or breaker open) and the caller
provided an HTTP fallback URL, the call falls back to the JSON control
plane — a restarted peer whose gRPC port moved keeps serving through
HTTP while the failure detector re-learns the new address."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from filodb_tpu.grpcsvc import wire
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.query import qos
from filodb_tpu.parallel.resilience import (BreakerRegistry, Deadline,
                                            RetryPolicy, TransportError,
                                            resilient_call)
from filodb_tpu.query.model import (QueryError, RawSeries,
                                    StaleRoutingError)
from filodb_tpu.testing import chaos


def _raise_peer_error(node_id: str, error: str) -> None:
    """Map a peer's error string back to the right exception: a
    stale-routing sentinel (the peer no longer serves the shards we
    routed at it) round-trips losslessly through the wire's error
    field; anything else is a plain peer QueryError."""
    sr = StaleRoutingError.parse(error)
    if sr is not None:
        raise sr
    raise QueryError(f"remote node {node_id}: {error}")

_SERVICE = "filodb.QueryService"
_channels: Dict[str, object] = {}
_channels_lock = threading.Lock()
# graftlint lock-discipline declaration for module-global state: the
# channel cache is shared by every query thread dialing peers
__guarded_by__ = {"_channels": "_channels_lock"}


def _channel(addr: str):
    import grpc
    with _channels_lock:
        ch = _channels.get(addr)
        if ch is None:
            ch = grpc.insecure_channel(addr)
            _channels[addr] = ch
        return ch


def drop_channel(addr: str) -> None:
    """Evict + close the cached channel for a peer that died or moved
    to a new ephemeral port (the failure detector calls this when the
    peer sink is invalidated)."""
    with _channels_lock:
        ch = _channels.pop(addr, None)
    if ch is not None:
        try:
            ch.close()
        except Exception:
            pass


def _call(addr: str, method: str, payload: bytes, timeout_s: float,
          node_id: str) -> bytes:
    import grpc
    try:
        chaos.fire("grpc.call", node=node_id, addr=addr, method=method)
        stub = _channel(addr).unary_unary(
            f"/{_SERVICE}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        return stub(payload, timeout=timeout_s)
    except grpc.RpcError as e:
        raise TransportError(f"remote node {node_id} grpc unreachable: "
                             f"{e.code().name}")
    except OSError as e:                     # injected/chaos connection
        raise TransportError(f"remote node {node_id} grpc unreachable: "
                             f"{e}")


class GrpcShardGroup:
    """Peer leaf dispatch over gRPC (see RemoteShardGroup for the plan
    contract: stands in a planner shard list for one peer's shards).

    ``http_fallback`` (the peer's HTTP base URL) downgrades the fetch to
    the JSON control plane when the gRPC plane is exhausted."""

    def __init__(self, node_id: str, addr: str, dataset: str,
                 shard_nums: Optional[Sequence[int]],
                 timeout_s: float = 60.0,
                 retry: Optional[RetryPolicy] = None,
                 breakers: Optional[BreakerRegistry] = None,
                 deadline: Optional[Deadline] = None,
                 allow_partial: bool = False,
                 http_fallback: Optional[str] = None):
        self.node_id = node_id
        self.addr = addr
        self.dataset = dataset
        self.shard_nums = list(shard_nums) if shard_nums is not None \
            else None
        self.timeout_s = timeout_s
        self.retry = retry
        self.breakers = breakers
        self.deadline = deadline
        self.allow_partial = allow_partial
        self.http_fallback = http_fallback
        self.shard_num = tuple(self.shard_nums or ())

    def describe(self) -> str:
        sh = ("all" if self.shard_nums is None
              else ",".join(map(str, self.shard_nums)))
        return f"shards [{sh}] on {self.node_id}"

    def _http_group(self):
        from filodb_tpu.parallel.cluster import RemoteShardGroup
        return RemoteShardGroup(
            self.node_id, self.http_fallback, self.dataset,
            self.shard_nums, timeout_s=self.timeout_s, retry=self.retry,
            breakers=self.breakers, deadline=self.deadline,
            allow_partial=self.allow_partial)

    def _deadline_ms(self) -> int:
        """Caller's remaining budget, forwarded so the peer inherits it
        (server-side deadline propagation); 0 = no deadline."""
        if self.deadline is None:
            return 0
        return max(int(self.deadline.remaining() * 1000), 1)

    def fetch_raw(self, filters, start_ms: int, end_ms: int,
                  column: Optional[str],
                  full: bool = True) -> List[RawSeries]:
        with obs_trace.span("remote-peer", node=self.node_id,
                            plane="grpc", rpc="FetchRaw",
                            addr=self.addr):
            return self._fetch_raw(filters, start_ms, end_ms, column,
                                   full)

    def _fetch_raw(self, filters, start_ms: int, end_ms: int,
                   column: Optional[str],
                   full: bool = True) -> List[RawSeries]:
        def dial(timeout_s: float) -> bytes:
            # payload re-encoded per attempt: a retry must forward the
            # REMAINING budget, not the original one (the trace context
            # is re-read too: the parent is the live attempt span).
            # Tenant/priority ride along so the peer force-charges the
            # same budget and orders its batcher by the same class.
            qctx = qos.current()
            payload = wire.encode_raw_request(
                self.dataset, filters, start_ms, end_ms, column,
                self.shard_nums, span_snap=bool(full),
                deadline_ms=self._deadline_ms(),
                trace_ctx=obs_trace.inject_header() or "",
                tenant=qctx.tenant if qctx is not None else "",
                priority=qctx.priority if qctx is not None else 0)
            return _call(self.addr, "FetchRaw", payload, timeout_s,
                         self.node_id)

        try:
            buf = resilient_call(
                dial, key=self.addr, node_id=self.node_id,
                timeout_s=self.timeout_s, retry=self.retry,
                breakers=self.breakers, deadline=self.deadline)
        except TransportError:
            if self.http_fallback is None:
                raise
            # binary plane down: downgrade to the JSON control plane
            obs_trace.event("plane-fallback", node=self.node_id,
                            to="http")
            return self._http_group().fetch_raw(
                filters, start_ms, end_ms, column, full=full)
        series, error, spans = wire.decode_raw_response(buf)
        obs_trace.absorb_wire(spans)      # stitch the peer's subspans
        if error:
            _raise_peer_error(self.node_id, error)
        return series

    def lookup_partitions(self, filters, start_ts, end_ts):
        return []


class GrpcRemoteExec:
    """Whole-query pushdown over gRPC: the peer evaluates the PromQL and
    ships the grid as packed columns (PromQlRemoteExec semantics without
    the JSON hop). Falls back to PromQlRemoteExec over ``http_fallback``
    when the binary plane is exhausted."""

    def __init__(self, query: str, start_ms: int, step_ms: int,
                 end_ms: int, node_id: str, addr: str, dataset: str,
                 timeout_s: float = 60.0, stats=None,
                 local_only: bool = True, plan_wire: bytes = b"",
                 retry: Optional[RetryPolicy] = None,
                 breakers: Optional[BreakerRegistry] = None,
                 deadline: Optional[Deadline] = None,
                 http_fallback: Optional[str] = None,
                 no_cache: bool = False,
                 expect_shards: Optional[Sequence[int]] = None):
        # structural plan tree (query.planwire); when present the peer
        # executes it directly and `query` is only a debug label
        self.plan_wire = plan_wire
        # stale-routing guard: the shards the entry node believes this
        # peer owns; the peer bounces instead of silently evaluating a
        # subset when a handoff moved one away (ExecRequest field 12)
        self.expect_shards = list(expect_shards) \
            if expect_shards is not None else None
        self.query = query
        self.start_ms = start_ms
        self.step_ms = step_ms
        self.end_ms = end_ms
        self.node_id = node_id
        self.addr = addr
        self.dataset = dataset
        self.timeout_s = timeout_s
        self.stats = stats
        self.local_only = local_only
        self.retry = retry
        self.breakers = breakers
        self.deadline = deadline
        self.http_fallback = http_fallback
        # &cache=false propagation across the binary plane (ExecRequest
        # field 11): the peer skips its results cache for this query
        self.no_cache = no_cache

    def _fallback_exec(self):
        from filodb_tpu.parallel.cluster import PromQlRemoteExec
        return PromQlRemoteExec(
            self.query, self.start_ms, self.step_ms, self.end_ms,
            self.node_id, self.http_fallback, self.dataset,
            timeout_s=self.timeout_s, stats=self.stats,
            local_only=self.local_only, retry=self.retry,
            breakers=self.breakers, deadline=self.deadline,
            no_cache=self.no_cache, expect_shards=self.expect_shards)

    def _deadline_ms(self) -> int:
        if self.deadline is None:
            return 0
        return max(int(self.deadline.remaining() * 1000), 1)

    def execute(self):
        with obs_trace.span("remote-peer", node=self.node_id,
                            plane="grpc", rpc="Exec", addr=self.addr):
            return self._execute()

    def _execute(self):
        from filodb_tpu.query.model import GridResult, RangeParams

        def dial(timeout_s: float) -> bytes:
            # re-encoded per attempt: forward the REMAINING budget
            # (tenant/priority ride fields 13/14 — budget inheritance)
            qctx = qos.current()
            payload = wire.encode_exec_request(
                self.dataset, self.query, self.start_ms, self.step_ms,
                self.end_ms, local_only=self.local_only,
                plan_wire=self.plan_wire,
                deadline_ms=self._deadline_ms(),
                trace_ctx=obs_trace.inject_header() or "",
                no_cache=self.no_cache,
                expect_shards=(self.expect_shards
                               if self.local_only else None),
                tenant=qctx.tenant if qctx is not None else "",
                priority=qctx.priority if qctx is not None else 0)
            return _call(self.addr, "Exec", payload, timeout_s,
                         self.node_id)

        try:
            buf = resilient_call(
                dial, key=self.addr, node_id=self.node_id,
                timeout_s=self.timeout_s, retry=self.retry,
                breakers=self.breakers, deadline=self.deadline)
        except TransportError:
            if self.http_fallback is None:
                raise
            # the HTTP edge can't carry a structural plan; only PromQL-
            # printable pushdowns downgrade (the planner only sets
            # http_fallback when a query string exists)
            obs_trace.event("plane-fallback", node=self.node_id,
                            to="http")
            return self._fallback_exec().execute()
        steps, keys, values, hv, les, stats, error = \
            wire.decode_exec_response(buf)
        obs_trace.absorb_wire(stats.get("trace_spans"))
        if error:
            _raise_peer_error(self.node_id, error)
        partial = bool(stats.get("partial"))
        warnings = list(stats.get("warnings") or ())
        if self.stats is not None:
            self.stats.series_scanned += stats.get("seriesScanned", 0)
            self.stats.samples_scanned += stats.get("samplesScanned", 0)
            # degraded peer: keep the markers flowing exactly like the
            # HTTP plane (prom_json.attach_degraded reads these)
            self.stats.partial = self.stats.partial or partial
            self.stats.warnings.extend(
                w for w in warnings if w not in self.stats.warnings)
        # align the peer's grid onto the local step grid (identical for
        # range queries; instant queries return a single step)
        params = RangeParams(self.start_ms, self.step_ms, self.end_ms)
        want = params.steps
        if steps.size == want.size and np.array_equal(steps, want):
            return GridResult(want, keys, values, hist_values=hv,
                              bucket_les=les, partial=partial,
                              warnings=warnings)
        out = np.full((len(keys), want.size), np.nan)
        idx = np.searchsorted(want, steps)
        ok = (idx < want.size) & (want[np.clip(idx, 0, want.size - 1)]
                                  == steps)
        out[:, idx[ok]] = values[:, ok]
        # realign histogram columns with the same mapping (dropping them
        # while keeping bucket_les would hand downstream ops an
        # inconsistent grid)
        hv_out = None
        if hv is not None:
            hv_out = np.full((hv.shape[0], want.size, hv.shape[2]),
                             np.nan)
            hv_out[:, idx[ok], :] = hv[:, ok, :]
        return GridResult(want, keys, out, hist_values=hv_out,
                          bucket_les=les if hv_out is not None else None,
                          partial=partial, warnings=warnings)

    def plan_tree(self, indent: int = 0) -> str:
        return (" " * indent + f"GrpcRemoteExec(node={self.node_id}, "
                f"query={self.query!r})")
