"""gRPC query service clients: drop-in peers for the planner.

``GrpcShardGroup`` replaces ``parallel.cluster.RemoteShardGroup`` (leaf
dispatch) and ``GrpcRemoteExec`` replaces ``PromQlRemoteExec``
(whole-query pushdown / federation) when a peer advertises a gRPC
address. Channels are cached per address — gRPC keeps one persistent
HTTP/2 connection per peer and multiplexes RPCs over it
(PromQLGrpcServer.scala client side; RemoteActorPlanDispatcher)."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from filodb_tpu.grpcsvc import wire
from filodb_tpu.query.model import QueryError, RawSeries

_SERVICE = "filodb.QueryService"
_channels: Dict[str, object] = {}
_channels_lock = threading.Lock()


def _channel(addr: str):
    import grpc
    with _channels_lock:
        ch = _channels.get(addr)
        if ch is None:
            ch = grpc.insecure_channel(addr)
            _channels[addr] = ch
        return ch


def _call(addr: str, method: str, payload: bytes, timeout_s: float,
          node_id: str) -> bytes:
    import grpc
    stub = _channel(addr).unary_unary(
        f"/{_SERVICE}/{method}",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b)
    try:
        return stub(payload, timeout=timeout_s)
    except grpc.RpcError as e:
        raise QueryError(f"remote node {node_id} grpc unreachable: "
                         f"{e.code().name}")


class GrpcShardGroup:
    """Peer leaf dispatch over gRPC (see RemoteShardGroup for the plan
    contract: stands in a planner shard list for one peer's shards)."""

    def __init__(self, node_id: str, addr: str, dataset: str,
                 shard_nums: Optional[Sequence[int]],
                 timeout_s: float = 60.0):
        self.node_id = node_id
        self.addr = addr
        self.dataset = dataset
        self.shard_nums = list(shard_nums) if shard_nums is not None \
            else None
        self.timeout_s = timeout_s
        self.shard_num = tuple(self.shard_nums or ())

    def fetch_raw(self, filters, start_ms: int, end_ms: int,
                  column: Optional[str],
                  full: bool = True) -> List[RawSeries]:
        payload = wire.encode_raw_request(
            self.dataset, filters, start_ms, end_ms, column,
            self.shard_nums, span_snap=bool(full))
        buf = _call(self.addr, "FetchRaw", payload, self.timeout_s,
                    self.node_id)
        series, error = wire.decode_raw_response(buf)
        if error:
            raise QueryError(f"remote node {self.node_id}: {error}")
        return series

    def lookup_partitions(self, filters, start_ts, end_ts):
        return []


class GrpcRemoteExec:
    """Whole-query pushdown over gRPC: the peer evaluates the PromQL and
    ships the grid as packed columns (PromQlRemoteExec semantics without
    the JSON hop)."""

    def __init__(self, query: str, start_ms: int, step_ms: int,
                 end_ms: int, node_id: str, addr: str, dataset: str,
                 timeout_s: float = 60.0, stats=None,
                 local_only: bool = True, plan_wire: bytes = b""):
        # structural plan tree (query.planwire); when present the peer
        # executes it directly and `query` is only a debug label
        self.plan_wire = plan_wire
        self.query = query
        self.start_ms = start_ms
        self.step_ms = step_ms
        self.end_ms = end_ms
        self.node_id = node_id
        self.addr = addr
        self.dataset = dataset
        self.timeout_s = timeout_s
        self.stats = stats
        self.local_only = local_only

    def execute(self):
        from filodb_tpu.query.model import GridResult, RangeParams
        payload = wire.encode_exec_request(
            self.dataset, self.query, self.start_ms, self.step_ms,
            self.end_ms, local_only=self.local_only,
            plan_wire=self.plan_wire)
        buf = _call(self.addr, "Exec", payload, self.timeout_s,
                    self.node_id)
        steps, keys, values, hv, les, stats, error = \
            wire.decode_exec_response(buf)
        if error:
            raise QueryError(f"remote node {self.node_id}: {error}")
        if self.stats is not None:
            self.stats.series_scanned += stats.get("seriesScanned", 0)
            self.stats.samples_scanned += stats.get("samplesScanned", 0)
        # align the peer's grid onto the local step grid (identical for
        # range queries; instant queries return a single step)
        params = RangeParams(self.start_ms, self.step_ms, self.end_ms)
        want = params.steps
        if steps.size == want.size and np.array_equal(steps, want):
            return GridResult(want, keys, values, hist_values=hv,
                              bucket_les=les)
        out = np.full((len(keys), want.size), np.nan)
        idx = np.searchsorted(want, steps)
        ok = (idx < want.size) & (want[np.clip(idx, 0, want.size - 1)]
                                  == steps)
        out[:, idx[ok]] = values[:, ok]
        # realign histogram columns with the same mapping (dropping them
        # while keeping bucket_les would hand downstream ops an
        # inconsistent grid)
        hv_out = None
        if hv is not None:
            hv_out = np.full((hv.shape[0], want.size, hv.shape[2]),
                             np.nan)
            hv_out[:, idx[ok], :] = hv[:, ok, :]
        return GridResult(want, keys, out, hist_values=hv_out,
                          bucket_les=les if hv_out is not None else None)

    def plan_tree(self, indent: int = 0) -> str:
        return (" " * indent + f"GrpcRemoteExec(node={self.node_id}, "
                f"query={self.query!r})")
