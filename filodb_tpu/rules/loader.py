"""Rule-group loader: Prometheus-style rule files -> typed groups.

The file format is the Prometheus ruler's (``groups:`` with per-group
``interval`` and ``rules:`` carrying either ``record:`` or ``alert:``
entries), parsed from YAML when PyYAML is present (it is baked into the
serving image) or from JSON otherwise — the loader never *requires* the
YAML dependency, matching the repo's no-new-deps rule. Two extensions:

* ``dataset:`` (group) — the SOURCE dataset the group's expressions
  evaluate against (default: the node's main dataset; ``__selfmon__``
  turns a group into alerting-on-our-own-telemetry).
* ``schema: counter|gauge`` (recording rule) — the ingest schema of the
  recorded series. Default is the counter-suffix heuristic the selfmon
  rail uses (``*_total``/``_bucket``/``_count``/``_sum`` -> counter
  schema, so ``rate()`` over a recorded counter gets reset correction).

Validation is promtool-shaped: structural errors, PromQL syntax through
the NORMAL parser (the engine evaluates exactly what validated),
duplicate-rule detection (same type + name + static labels anywhere in
the file, plus parser-NORMALIZED expression comparison so whitespace/
label-order variants are caught), and promlint semantic analysis
(:mod:`filodb_tpu.promql.semant`): type/schema errors — e.g. ``rate()``
on a metric another rule declares ``schema: gauge`` — and provably-
broken vector matching REJECT the file at load time; warning-severity
findings surface without failing. ``python -m filodb_tpu.rules --check
<file>`` runs it from the command line; the shipped example file is
validated in the tier-1 gate.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from filodb_tpu.promql import semant
from filodb_tpu.promql.parser import (ParseError, TimeStepParams,
                                      normalize_query,
                                      parse_duration_ms,
                                      parse_query_range)

DEFAULT_GROUP_INTERVAL_S = 60.0

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class RuleLoadError(ValueError):
    """A rule file failed to load; ``errors`` carries every finding."""

    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = list(errors)


@dataclass(frozen=True)
class Rule:
    """One recording or alerting rule (immutable; runtime state lives
    in the engine)."""
    name: str                   # record metric name / alert name
    expr: str
    kind: str                   # "recording" | "alerting"
    labels: Tuple[Tuple[str, str], ...] = ()
    annotations: Tuple[Tuple[str, str], ...] = ()
    for_s: float = 0.0          # alerting: pending hold duration
    schema: Optional[str] = None  # recording: "counter" | "gauge"

    @property
    def is_alert(self) -> bool:
        return self.kind == "alerting"


@dataclass(frozen=True)
class RuleGroup:
    name: str
    interval_s: float
    rules: Tuple[Rule, ...]
    dataset: Optional[str] = None   # None = the node's main dataset
    limit: int = 0                  # max series a rule may produce


def _parse_duration_s(raw, where: str, errors: List[str],
                      default: float = 0.0) -> float:
    if raw is None:
        return default
    if isinstance(raw, (int, float)) and not isinstance(raw, bool):
        return float(raw)
    try:
        return parse_duration_ms(str(raw)) / 1000.0
    except (ValueError, TypeError):
        errors.append(f"{where}: bad duration {raw!r}")
        return default


def _str_map(raw, where: str, errors: List[str],
             check_names: bool = False) -> Tuple[Tuple[str, str], ...]:
    if raw is None:
        return ()
    if not isinstance(raw, dict):
        errors.append(f"{where}: expected a mapping, got {type(raw).__name__}")
        return ()
    out = []
    for k, v in raw.items():
        if check_names and not _LABEL_NAME_RE.match(str(k)):
            errors.append(f"{where}: bad label name {k!r}")
            continue
        out.append((str(k), str(v)))
    return tuple(sorted(out))


def _check_expr(expr: str, where: str, errors: List[str]) -> None:
    """PromQL syntax check through the normal parser — the exact code
    path the engine evaluates with (no second grammar to drift)."""
    try:
        parse_query_range(str(expr), TimeStepParams(0, 60, 600))
    except ParseError as e:
        errors.append(f"{where}: PromQL syntax error: {e}")
    except Exception as e:   # noqa: BLE001 — a validator must not crash
        errors.append(f"{where}: expression rejected: {e}")


def load_groups(obj, errors: Optional[List[str]] = None,
                warnings: Optional[List[str]] = None) -> List[RuleGroup]:
    """Parse the Python-object form (``{"groups": [...]}`` or a bare
    group list). With ``errors=None`` raises :class:`RuleLoadError` on
    any finding; otherwise appends findings and returns what parsed.
    ``warnings`` (optional) collects non-fatal promlint findings."""
    own_errors = errors if errors is not None else []
    own_warnings = warnings if warnings is not None else []
    groups: List[RuleGroup] = []
    if isinstance(obj, dict):
        raw_groups = obj.get("groups")
        unknown = set(obj) - {"groups"}
        if unknown:
            own_errors.append(
                f"top level: unknown keys {sorted(unknown)}")
    else:
        raw_groups = obj
    if not isinstance(raw_groups, list) or not raw_groups:
        own_errors.append("no rule groups found (want groups: [...])")
        raw_groups = []
    seen_groups: set = set()
    seen_rules: Dict[Tuple, str] = {}
    # (where, kind, name, expr) for the promlint/normalization post-pass
    pending: List[Tuple[str, str, str, str]] = []
    for gi, g in enumerate(raw_groups):
        gw = f"group[{gi}]"
        if not isinstance(g, dict):
            own_errors.append(f"{gw}: expected a mapping")
            continue
        name = str(g.get("name") or "")
        if not name:
            own_errors.append(f"{gw}: missing name")
            name = f"group{gi}"
        gw = f"group {name!r}"
        if name in seen_groups:
            own_errors.append(f"{gw}: duplicate group name")
        seen_groups.add(name)
        interval_s = _parse_duration_s(g.get("interval"), gw, own_errors,
                                       DEFAULT_GROUP_INTERVAL_S)
        if interval_s <= 0:
            own_errors.append(f"{gw}: interval must be positive")
            interval_s = DEFAULT_GROUP_INTERVAL_S
        unknown = set(g) - {"name", "interval", "rules", "dataset",
                            "limit"}
        if unknown:
            own_errors.append(f"{gw}: unknown keys {sorted(unknown)}")
        rules: List[Rule] = []
        for ri, r in enumerate(g.get("rules") or ()):
            rw = f"{gw} rule[{ri}]"
            if not isinstance(r, dict):
                own_errors.append(f"{rw}: expected a mapping")
                continue
            record = r.get("record")
            alert = r.get("alert")
            if bool(record) == bool(alert):
                own_errors.append(
                    f"{rw}: exactly one of record:/alert: required")
                continue
            kind = "recording" if record else "alerting"
            rname = str(record or alert)
            rw = f"{gw} {kind} rule {rname!r}"
            expr = r.get("expr")
            if not expr:
                own_errors.append(f"{rw}: missing expr")
                continue
            _check_expr(expr, rw, own_errors)
            pending.append((rw, kind, rname, str(expr)))
            labels = _str_map(r.get("labels"), rw, own_errors,
                              check_names=True)
            annotations = _str_map(r.get("annotations"), rw, own_errors)
            schema = r.get("schema")
            allowed = {"expr", "labels"}
            if kind == "recording":
                allowed |= {"record", "schema"}
                if not _METRIC_NAME_RE.match(rname):
                    own_errors.append(f"{rw}: invalid metric name")
                if r.get("for") is not None:
                    own_errors.append(f"{rw}: for: is alert-only")
                if r.get("annotations") is not None:
                    own_errors.append(f"{rw}: annotations are alert-only")
                if schema is not None and schema not in ("counter",
                                                         "gauge"):
                    own_errors.append(
                        f"{rw}: schema must be counter|gauge")
            else:
                allowed |= {"alert", "for", "annotations",
                            "keep_firing_for"}
                if schema is not None:
                    own_errors.append(f"{rw}: schema: is recording-only")
            unknown = set(r) - allowed
            if unknown:
                own_errors.append(f"{rw}: unknown keys {sorted(unknown)}")
            for_s = _parse_duration_s(r.get("for"), rw, own_errors)
            dup_key = (kind, rname, labels)
            if dup_key in seen_rules:
                own_errors.append(
                    f"{rw}: duplicate rule (same name + labels as one "
                    f"in {seen_rules[dup_key]})")
            else:
                seen_rules[dup_key] = f"group {name!r}"
            rules.append(Rule(
                name=rname, expr=str(expr), kind=kind, labels=labels,
                annotations=annotations, for_s=for_s,
                schema=str(schema) if schema else None))
        if not rules:
            own_errors.append(f"{gw}: no rules")
        ds = g.get("dataset")
        groups.append(RuleGroup(
            name=name, interval_s=interval_s, rules=tuple(rules),
            dataset=str(ds) if ds else None,
            limit=int(g.get("limit") or 0)))
    _semantic_pass(groups, pending, own_errors, own_warnings)
    if errors is None and own_errors:
        raise RuleLoadError(own_errors)
    return groups


def _semantic_pass(groups: List[RuleGroup],
                   pending: List[Tuple[str, str, str, str]],
                   errors: List[str], warnings: List[str]) -> None:
    """Post-parse pass over every rule expression: promlint semantic
    diagnostics (error severity rejects the file; warnings surface),
    and parser-NORMALIZED duplicate detection — whitespace/label-order
    expression variants compare equal, and two recording rules that
    evaluate the identical normalized expression warn (the second is a
    wasted standing evaluation)."""
    # schema resolution sees EVERY recording rule's schema: extension,
    # across groups, so forward references resolve
    schemas = semant.MetricSchemas.from_rule_groups(groups)
    norm_seen: Dict[str, Tuple[str, str]] = {}
    for where, kind, rname, expr in pending:
        for d in semant.lint_query(expr, schemas):
            msg = f"{where}: promlint: {d.render(expr)}"
            if d.severity == semant.ERROR and \
                    d.rule != "promql-syntax":
                # syntax errors were already reported by _check_expr
                errors.append(msg)
            elif d.severity == semant.WARNING:
                warnings.append(msg)
        if kind != "recording":
            continue
        try:
            norm = normalize_query(expr)
        except (ParseError, ValueError):
            continue
        prev = norm_seen.get(norm)
        if prev is not None and prev[1] != rname:
            warnings.append(
                f"{where}: semantically identical expression to "
                f"{prev[0]} (normalized: {norm}) — one standing "
                f"evaluation would serve both")
        elif prev is None:
            norm_seen[norm] = (where, rname)


def parse_rules_text(text: str, errors: Optional[List[str]] = None,
                     warnings: Optional[List[str]] = None
                     ) -> List[RuleGroup]:
    """Parse YAML (when PyYAML is importable) or JSON rule-file text."""
    own_errors = errors if errors is not None else []
    stripped = text.lstrip()
    obj = None
    if stripped.startswith(("{", "[")):
        try:
            obj = json.loads(text)
        except ValueError as e:
            own_errors.append(f"JSON parse error: {e}")
    else:
        try:
            import yaml
        except ImportError:
            own_errors.append(
                "PyYAML is not available in this environment; supply "
                "the rule file as JSON ({\"groups\": [...]})")
        else:
            try:
                obj = yaml.safe_load(text)
            except yaml.YAMLError as e:
                own_errors.append(f"YAML parse error: {e}")
    if obj is None:
        if errors is None and own_errors:
            raise RuleLoadError(own_errors)
        return []
    out = load_groups(obj, errors=own_errors, warnings=warnings)
    if errors is None and own_errors:
        raise RuleLoadError(own_errors)
    return out


def load_rules_file(path: str) -> List[RuleGroup]:
    with open(path) as f:
        return parse_rules_text(f.read())


def check_rules_file(path: str) -> List[str]:
    """promtool-style validation: returns human-readable findings
    (empty = clean). Never raises on content errors — unreadable files
    come back as a finding too."""
    return check_rules_file_full(path)[0]


def check_rules_file_full(path: str) -> Tuple[List[str], List[str]]:
    """(errors, warnings) — errors reject the file (exit 1 from
    ``--check``); warning-severity promlint findings surface without
    failing (promtool's check-rules warning behavior)."""
    errors: List[str] = []
    warnings: List[str] = []
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return [f"cannot read {path}: {e}"], warnings
    groups = parse_rules_text(text, errors=errors, warnings=warnings)
    if not errors and not groups:
        errors.append("no rule groups found")
    return errors, warnings
