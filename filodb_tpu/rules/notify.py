"""Webhook alert notifier: retry/backoff/breaker on the delivery path.

Alert transitions (``firing`` / ``resolved``) enqueue onto a bounded
queue drained by a dedicated notifier thread — delivery latency and
receiver outages must never stall the rule scheduler's tick loop. Each
delivery runs under the full :func:`~filodb_tpu.parallel.resilience.
resilient_call` policy stack: bounded retries with exponential backoff
+ jitter on transport failure (connection refused, 5xx), and a
per-receiver circuit breaker so a dead webhook endpoint stops being
dialed entirely until its reset probe succeeds.

The payload is Alertmanager-webhook-shaped (``version``, ``status``,
``alerts: [{labels, annotations, ...}]``) so a real Alertmanager or any
generic webhook consumer can sit on the other end.
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.request
from typing import Dict, Optional

from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.parallel.resilience import (BreakerRegistry, RetryPolicy,
                                            TransportError,
                                            resilient_call)


@guarded_by("_lock", "delivered", "failed", "dropped")
class WebhookNotifier:
    """One receiver URL, one delivery thread, one breaker."""

    def __init__(self, url: str,
                 retry: Optional[RetryPolicy] = None,
                 breakers: Optional[BreakerRegistry] = None,
                 timeout_s: float = 5.0,
                 queue_size: int = 256):
        self.url = str(url)
        self.timeout_s = float(timeout_s)
        self.retry = retry or RetryPolicy(max_attempts=3,
                                          base_delay_s=0.1)
        # a private registry by default: webhook-receiver breaker state
        # must not open/close the QUERY plane's per-peer breakers
        self.breakers = breakers or BreakerRegistry(
            failure_threshold=3, reset_timeout_s=5.0)
        self._q: "queue.Queue[Dict]" = queue.Queue(maxsize=queue_size)
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.delivered = 0
        self.failed = 0
        self.dropped = 0
        reg = obs_metrics.GLOBAL_REGISTRY
        self._m_sent = reg.counter(
            "filodb_rule_notifications_total",
            "Webhook alert notifications, by delivery outcome")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "WebhookNotifier":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rules-notifier")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- producer side (the rule scheduler) --------------------------------
    def enqueue(self, notification: Dict) -> bool:
        """Non-blocking enqueue; a full queue DROPS (counted) rather
        than stalling the scheduler tick."""
        try:
            self._q.put_nowait(notification)
            return True
        except queue.Full:
            with self._lock:
                self.dropped += 1
            self._m_sent.inc(outcome="dropped")
            return False

    # -- delivery ----------------------------------------------------------
    @thread_root("rules-notifier")
    def _run(self) -> None:
        while not self._stop_evt.is_set():
            try:
                notification = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            try:
                self.deliver(notification)
                with self._lock:
                    self.delivered += 1
                self._m_sent.inc(outcome="delivered")
            except Exception:   # noqa: BLE001 — a dead receiver must not
                with self._lock:        # kill the notifier loop
                    self.failed += 1
                self._m_sent.inc(outcome="failed")

    def deliver(self, notification: Dict) -> None:
        """One delivery under the resilience stack (public for tests).
        Raises on exhausted retries / open breaker."""
        body = json.dumps({
            "version": "4",
            "status": notification.get("status", "firing"),
            "receiver": "filodb-rules",
            "alerts": [{
                "status": notification.get("status", "firing"),
                "labels": notification.get("labels") or {},
                "annotations": notification.get("annotations") or {},
                "value": notification.get("value"),
                "activeAt": notification.get("activeAt"),
            }],
        }).encode()

        def do_call(timeout_s: float):
            req = urllib.request.Request(
                self.url, data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req,
                                            timeout=timeout_s) as r:
                    if r.status >= 500:
                        raise TransportError(
                            f"webhook {self.url} answered {r.status}")
                    return r.status
            except OSError as e:
                # urllib surfaces 5xx as HTTPError (an OSError): the
                # receiver is broken, not the request — retryable
                code = getattr(e, "code", None)
                if code is not None and code < 500:
                    raise   # 4xx: our payload's fault; retrying repeats it
                raise TransportError(
                    f"webhook {self.url} unreachable: {e}") from e

        resilient_call(do_call, key=self.url, node_id="webhook",
                       timeout_s=self.timeout_s, retry=self.retry,
                       breakers=self.breakers)

    def snapshot(self) -> Dict:
        with self._lock:
            out = {"delivered": self.delivered, "failed": self.failed,
                   "dropped": self.dropped, "queued": self._q.qsize(),
                   "alive": self.alive}
        out["breaker"] = self.breakers.get(self.url).state
        return out
