"""In-process continuous-query engine: recording rules and alerting.

The reference system is PromQL-compatible but defers rules and alerting
to an external ruler. This engine leapfrogs that: rule groups evaluate
*inside* the node as standing queries through the NORMAL
planner/engine/QoS path, so every piece of serving machinery the repo
has grown — plan cache, incremental results cache, cost-based
admission, priority micro-batching, degraded-mode fan-out — applies to
rule evaluation for free, and every dashboard query a recording rule
precomputes converts per-user traffic into O(rules) background work.

Design points:

* **Step-aligned tail recomputes** — each group tick evaluates its
  rules as a RANGE query over the last ``span_steps`` interval-aligned
  steps ending at the tick boundary, not as an isolated instant query.
  Consecutive ticks therefore share the same results-cache key (same
  text, same step, same grid phase) and the cache serves the warm
  prefix; only the newest step computes. The tick's sample is the last
  grid column.

* **QoS** — evaluations run under the reserved ``__rules__`` tenant:
  BACKGROUND priority (the micro-batcher never lets a rule scan
  head-of-line block an interactive query) and FORCED charges (rule
  evaluation must never bounce off a drained admission bucket — the
  standing workload keeps evaluating through brownouts, visibly driving
  its bucket into debt instead of silently pausing).

* **Write-back** — recorded series and the synthetic ``ALERTS`` /
  ``ALERTS_FOR_STATE`` state series re-enter through the shared
  :class:`~filodb_tpu.obs.writeback.IngestWriteBack` rail into the
  reserved ``__rules__`` dataset (strictly node-local planner, own
  cardinality tracker, durable WAL + driver replay under ``stream-dir``
  — recorded series survive restarts).

* **Single-owner scheduling** — under the worker supervisor exactly ONE
  worker evaluates: the lowest ALIVE ordinal. Every worker loads the
  (supervisor-propagated) rules config; non-evaluators stand by and
  re-elect on the bus ``worker-exit``/``worker-up`` lifecycle events.
  A newly-activated evaluator SKIPS the in-progress boundary (its
  predecessor is assumed to have run it) and owns the next one — no
  duplicated tick by construction, no missed tick as long as failover
  completes within one interval.

* **First-class rule observability** — per-rule eval/failure counters,
  the ``filodb_rule_tick_seconds`` duty-cycle histogram, per-group
  staleness gauges (rising staleness = the alerter itself is in
  trouble), alert-state gauges and transition counters all ride the
  metrics registry — so with ``--self-monitor`` on,
  ``rate(filodb_rule_eval_failures_total[5m])`` is a PromQL query over
  ``/promql/__selfmon__``: alerting on the alerter works. The last
  evaluation (query, range, cache dispositions, duration, error) is
  retained per rule and surfaced through ``/api/v1/rules`` with
  ``&explain=analyze``; alert state transitions land in a bounded
  structured-event ring on ``/api/v1/alerts``.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from filodb_tpu.lint.caches import cache_registry
from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.promql.parser import TimeStepParams, parse_query_range
from filodb_tpu.query.engine import lp_replace_range
from filodb_tpu.query.model import GridResult, ScalarResult
from filodb_tpu.query.plancache import _cacheable
from filodb_tpu.query.qos import RULES_TENANT
from filodb_tpu.rules.loader import Rule, RuleGroup

# the reserved internal dataset recorded series and alert-state series
# are written into (strictly node-local, like __selfmon__); its name
# doubles as the reserved tenant rule evaluation runs under
RULES_DATASET = RULES_TENANT

# alert states (Prometheus rule-state names)
STATE_INACTIVE = "inactive"
STATE_PENDING = "pending"
STATE_FIRING = "firing"

_TICK_HELP = "Wall seconds per rule-group evaluation tick"
_EVAL_HELP = "Wall seconds per single rule evaluation"

# labels the source result carries that must not leak into the
# recorded series identity (re-tagged into the internal dataset)
_RESERVED_LABELS = ("_ws_", "_ns_", "_metric_")


def _render_template(text: str, value, labels: Dict[str, str]) -> str:
    """Minimal annotation templating: ``{{ $value }}`` and
    ``{{ $labels.<name> }}`` (the two forms alert annotations actually
    use; anything else passes through verbatim)."""
    import re
    if "{{" not in text:
        return text
    out = re.sub(r"\{\{\s*\$value\s*\}\}",
                 ("" if value is None else f"{value:g}"), text)
    return re.sub(
        r"\{\{\s*\$labels\.([a-zA-Z_][a-zA-Z0-9_]*)\s*\}\}",
        lambda m: str(labels.get(m.group(1), "")), out)


# inventory declaration (graftlint cache-invalidation-completeness):
# the per-rule parsed-plan cache is topology- and schema-dependent
# exactly like the server's PlanCache (the evaluation range is rebased
# out of the key) — every @publishes of these events must reach
# `invalidate_plans` through the plan cache's listener chain (the
# standalone server registers it with add_invalidation_listener).
@cache_registry("rule-plans",
                invalidated_by={"topology-epoch": "invalidate_plans",
                                "schema": "invalidate_plans"},
                keyed=("dataset", "query-text", "step"))
@guarded_by("_lock", "_plan_cache", "_alive", "_last_run", "_rule_state",
            "_alerts", "_transitions", "_group_state", "active",
            "_announced", "_final_until", "_election_log", "ticks",
            "errors", "plan_invalidations", "notifications_enqueued")
class RulesEngine:
    """The per-process rules scheduler (a declared thread root).

    ``evaluator(ds, query, plan, start_ms, step_ms, end_ms)`` runs one
    standing-query evaluation through the serving path and returns
    ``(result, stages)`` — the HTTP server's ``rule_eval_range`` in
    production, a stub in unit tests. ``writeback`` is this engine's
    own :class:`~filodb_tpu.obs.writeback.IngestWriteBack` into the
    reserved rules dataset."""

    def __init__(self, groups: Sequence[RuleGroup],
                 evaluator: Callable,
                 writeback,
                 default_dataset: str = "timeseries",
                 node: str = "", worker_id: Optional[int] = None,
                 num_workers: int = 1,
                 span_steps: int = 8,
                 notifier=None,
                 announced: bool = True,
                 clock: Callable[[], float] = time.time):
        self.groups: Tuple[RuleGroup, ...] = tuple(groups)
        self.evaluator = evaluator
        self.writeback = writeback
        self.default_dataset = default_dataset
        self.node = node or ""
        self.worker_id = worker_id
        self.num_workers = max(1, int(num_workers))
        self.span_steps = max(2, int(span_steps))
        self.notifier = notifier
        self._clock = clock
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._lock = threading.Lock()
        # election: ordinals believed alive (supervisor fleet); the
        # evaluator is the lowest ALIVE *announced* ordinal. A
        # standalone process (worker_id None, or no bus) is announced
        # from birth; a supervised worker stays in standby until its
        # OWN ``worker-up`` broadcast arrives — the same fan-out that
        # tells the stand-in to step down, so a restarting ordinal 0
        # reclaims evaluation in one bus beat instead of racing the
        # stand-in through a half-second double-evaluation window.
        self._ordinal = int(worker_id) if worker_id is not None else 0
        self._alive = set(range(self.num_workers)) \
            if worker_id is not None else {0}
        self._announced = bool(announced) or worker_id is None
        self.active = self._announced \
            and self._ordinal == min(self._alive)
        # per-group scheduling state: group name -> last evaluated (or
        # claimed) boundary. Activation stamps the CURRENT boundary
        # per group (the predecessor is assumed to have run it — no
        # duplicated tick); deactivation leaves it in place and arms a
        # bounded final catch-up pass (see evaluate_due) so a boundary
        # that fell due in the handover beat is not missed.
        self._last_run: Dict[str, float] = {}
        self._final_until: Optional[float] = None
        # per-group health: last tick wall time/duration, last success
        self._group_state: Dict[str, Dict] = {}
        # per-rule runtime state: (group, rule) -> {health, last_error,
        # last_eval {...}}
        self._rule_state: Dict[Tuple[str, str], Dict] = {}
        # alert instances: (group, rule) -> {inst_key: {...}}
        self._alerts: Dict[Tuple[str, str], Dict[Tuple, Dict]] = {}
        # bounded structured-event ring of alert state transitions
        self._transitions: deque = deque(maxlen=256)
        # bounded election-event ring (activations, step-downs, the
        # alive-set edges that caused them) — the failover audit trail
        self._election_log: deque = deque(maxlen=64)
        # parsed-plan cache (see the registry declaration above)
        self._plan_cache: Dict[Tuple, object] = {}
        self.ticks = 0
        self.errors = 0
        self.plan_invalidations = 0
        self.notifications_enqueued = 0
        # scheduler poll cadence: fine enough for the smallest interval
        min_iv = min((g.interval_s for g in self.groups), default=60.0)
        self._poll_s = max(0.02, min(0.25, min_iv / 8.0))
        reg = obs_metrics.GLOBAL_REGISTRY
        self._m_evals = reg.counter(
            "filodb_rule_evals_total",
            "Rule evaluations completed, by group and rule")
        self._m_failures = reg.counter(
            "filodb_rule_eval_failures_total",
            "Rule evaluations that raised (state is kept, alerts do "
            "not flap on an evaluation failure)")
        self._m_ticks = reg.counter(
            "filodb_rule_group_ticks_total",
            "Rule-group evaluation ticks completed")
        self._m_missed = reg.counter(
            "filodb_rule_group_ticks_missed_total",
            "Interval boundaries skipped because the previous tick "
            "overran (the skipped-evaluation signal)")
        self._m_samples = reg.counter(
            "filodb_rule_samples_written_total",
            "Derived samples written back by the rules engine")
        self._m_transitions = reg.counter(
            "filodb_alert_transitions_total",
            "Alert state transitions, by alertname and target state")
        self._m_active = reg.gauge(
            "filodb_rules_active",
            "1 while THIS process is the elected rule evaluator")
        self._m_interval = reg.gauge(
            "filodb_rule_group_interval_seconds",
            "Configured per-group evaluation interval")
        self._m_rules = reg.gauge(
            "filodb_rule_group_rules",
            "Rules per group")
        self._m_duration = reg.gauge(
            "filodb_rule_group_last_duration_seconds",
            "Wall seconds of the group's last evaluation tick")
        self._m_staleness = reg.gauge(
            "filodb_rule_group_staleness_seconds",
            "Seconds since the group's last SUCCESSFUL evaluation "
            "(rising = the rules engine itself is in trouble)")
        self._m_alerts = reg.gauge(
            "filodb_alerts",
            "Active alert instances by alertname and state")
        reg.register_collector(self._collect)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "RulesEngine":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rules-scheduler")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        self._stopped = True
        if self._thread is not None:
            self._thread.join(timeout)
        if self.notifier is not None:
            self.notifier.stop(timeout)

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @thread_root("rules-scheduler")
    def _run(self) -> None:
        while not self._stop_evt.wait(self._poll_s):
            try:
                self.evaluate_due()
            except Exception:   # noqa: BLE001 — the scheduler must not die
                with self._lock:
                    self.errors += 1

    # -- election (single-owner scheduling under the supervisor) ----------
    def note_worker_exit(self, ordinal: int) -> None:
        """Bus ``worker-exit``: a sibling worker process is GONE. If it
        was the evaluator, the next-lowest ordinal takes over."""
        with self._lock:
            self._alive.discard(int(ordinal))
            self._election_log.append(
                {"at": self._clock(), "event": "worker-exit",
                 "ordinal": int(ordinal)})
        self._recompute_active()

    def note_worker_up(self, ordinal: int) -> None:
        """Bus ``worker-up``: a worker is serving. Our OWN event is the
        activation edge (the supervisor announced us to the fleet); a
        returning lower ordinal's event makes the stand-in step down
        before its next boundary."""
        with self._lock:
            if 0 <= int(ordinal) < self.num_workers:
                self._alive.add(int(ordinal))
            if int(ordinal) == self._ordinal:
                self._announced = True
            self._election_log.append(
                {"at": self._clock(), "event": "worker-up",
                 "ordinal": int(ordinal)})
        self._recompute_active()

    def evaluator_ordinal(self) -> int:
        with self._lock:
            return min(self._alive) if self._alive else self._ordinal

    def _recompute_active(self) -> None:
        now = self._clock()
        with self._lock:
            alive = self._alive or {self._ordinal}
            act = self._announced and self._ordinal == min(alive)
            changed = act != self.active
            self.active = act
            if changed and act:
                # taking over: claim the CURRENT boundary of every
                # group AT THE ELECTION INSTANT (the same bus beat that
                # steps the predecessor down), so the two schedules
                # partition the boundary walk with no overlap — the
                # predecessor owns everything up to this beat, we own
                # everything after it
                self._final_until = None
                for g in self.groups:
                    self._last_run[g.name] = \
                        math.floor(now / g.interval_s) * g.interval_s
            elif changed and not act:
                # stepping down: arm ONE bounded catch-up pass — a
                # boundary that fell due before this beat but was not
                # yet evaluated (scheduler-poll race) is still ours;
                # everything after the beat belongs to the successor
                self._final_until = now
            if changed:
                self._election_log.append(
                    {"at": now,
                     "event": "activated" if act else "stepped-down",
                     "alive": sorted(alive)})
        if changed:
            obs_trace.event("rules-election", active=act,
                            ordinal=self._ordinal)

    # -- scheduling --------------------------------------------------------
    def evaluate_due(self, now_s: Optional[float] = None) -> int:
        """Evaluate every group whose interval boundary has passed;
        returns the number of group ticks run. Public so tests can
        drive deterministic clocks. A group's FIRST due check after
        (re)activation only claims the current boundary — the previous
        evaluator is assumed to have run it (no duplicated tick)."""
        now = self._clock() if now_s is None else float(now_s)
        with self._lock:
            active = self.active
            final_until = self._final_until
        if not active:
            if final_until is None:
                return 0
            # the step-down catch-up: evaluate boundaries that fell
            # due BEFORE the handover beat but had not run yet (the
            # successor claimed everything after the beat), then
            # retire the schedule
            ran = self._run_due(min(now, final_until))
            with self._lock:
                self._final_until = None
                self._last_run.clear()
            return ran
        return self._run_due(now)

    def _run_due(self, now: float) -> int:
        ran = 0
        for g in self.groups:
            boundary = math.floor(now / g.interval_s) * g.interval_s
            with self._lock:
                last = self._last_run.get(g.name)
                if last is None:
                    self._last_run[g.name] = boundary
                    continue
                if boundary <= last:
                    continue
                missed = int(round((boundary - last) / g.interval_s)) - 1
            if missed > 0:
                self._m_missed.inc(missed, group=g.name)
            self.eval_group_once(g, boundary)
            with self._lock:
                self._last_run[g.name] = boundary
            ran += 1
        return ran

    # -- one group tick ----------------------------------------------------
    def eval_group_once(self, group: RuleGroup, at_s: float) -> Dict:
        """Evaluate one group at the aligned boundary ``at_s``: every
        rule runs as a step-aligned tail recompute, recorded/alert
        samples write back through the rail, per-rule state updates.
        Public for tests (deterministic manual ticks)."""
        t0 = time.perf_counter()
        ds = group.dataset or self.default_dataset
        step_ms = max(1, int(round(group.interval_s * 1000)))
        end_ms = int(round(at_s * 1000))
        # keep the grid phase constant across ticks: consecutive ticks
        # share the results-cache key and only the tail recomputes
        end_ms -= end_ms % step_ms
        start_ms = end_ms - (self.span_steps - 1) * step_ms
        samples: List[Tuple[str, Dict, int, float]] = []
        ok = True
        for rule in group.rules:
            t1 = time.perf_counter()
            err: Optional[str] = None
            stages: Dict[str, object] = {}
            n_out = 0
            try:
                plan, pc_state = self._plan_for(ds, rule.expr, start_ms,
                                                step_ms, end_ms)
                res, stages = self.evaluator(ds, rule.expr, plan,
                                             start_ms, step_ms, end_ms)
                stages = dict(stages or {})
                stages["rulePlanCache"] = pc_state
                last_col = self._last_column(res, group, rule)
                if rule.is_alert:
                    n_out = self._apply_alert_state(
                        group, rule, last_col, at_s, samples)
                else:
                    n_out = self._record_samples(
                        group, rule, last_col, end_ms, samples)
            except Exception as e:   # noqa: BLE001 — one rule must not
                err = f"{type(e).__name__}: {e}"     # kill the group
                ok = False
                self._m_failures.inc(group=group.name, rule=rule.name)
            dt = time.perf_counter() - t1
            self._m_evals.inc(group=group.name, rule=rule.name)
            obs_metrics.observe("filodb_rule_eval_seconds", _EVAL_HELP,
                                dt)
            with self._lock:
                self._rule_state[(group.name, rule.name)] = {
                    "health": "err" if err else "ok",
                    "last_error": err,
                    "last_eval": {
                        "at": at_s,
                        "duration_s": round(dt, 6),
                        "query": rule.expr,
                        "dataset": ds,
                        "start_ms": start_ms,
                        "step_ms": step_ms,
                        "end_ms": end_ms,
                        "samples": n_out,
                        "stages": stages,
                    },
                }
        written = 0
        if samples:
            try:
                written = self.writeback.write(samples)
                self.writeback.flush()
            except Exception:   # noqa: BLE001 — write-back failure is a
                ok = False      # tick failure, not a crash
                self._m_failures.inc(group=group.name,
                                     rule="__writeback__")
        if written:
            self._m_samples.inc(written, group=group.name)
        dt_group = time.perf_counter() - t0
        self._m_ticks.inc(group=group.name)
        self._m_duration.set(round(dt_group, 6), group=group.name)
        obs_metrics.observe("filodb_rule_tick_seconds", _TICK_HELP,
                            dt_group)
        now_wall = self._clock()
        with self._lock:
            st = self._group_state.setdefault(group.name, {})
            st["last_tick"] = at_s
            st["last_tick_wall"] = now_wall
            st["last_duration_s"] = round(dt_group, 6)
            if ok:
                st["last_success_wall"] = now_wall
            self.ticks += 1
        return {"group": group.name, "at": at_s,
                "samples": written, "ok": ok,
                "duration_s": round(dt_group, 6)}

    # -- rule-plan cache (see @cache_registry above) ----------------------
    def _plan_for(self, ds: str, expr: str, start_ms: int, step_ms: int,
                  end_ms: int):
        """Parsed plan for one rule, range-rebased onto this tick's
        grid. Parsing happens once per (dataset, expr, step); every
        subsequent tick rebases the cached plan like the server's plan
        cache does. Non-rebasable shapes (@/subquery) re-parse."""
        key = (ds, expr, step_ms)
        with self._lock:
            cached = self._plan_cache.get(key)
        if cached is not None:
            return (lp_replace_range(cached, start_ms, step_ms, end_ms),
                    "hit")
        plan = parse_query_range(
            expr, TimeStepParams(start_ms // 1000,
                                 max(1, step_ms // 1000),
                                 end_ms // 1000))
        if _cacheable(plan):
            with self._lock:
                self._plan_cache[key] = plan
            # the parse above used second-granularity params; rebase
            # onto the exact ms grid (sub-second intervals included)
            return (lp_replace_range(plan, start_ms, step_ms, end_ms),
                    "miss")
        return plan, "uncacheable"

    def invalidate_plans(self, reason: str = "") -> None:
        """Topology/schema invalidation hook — wired to the server plan
        cache's listener chain, so every publisher that clears parsed
        plans clears the rules engine's too."""
        with self._lock:
            self._plan_cache.clear()
            self.plan_invalidations += 1

    # -- result extraction -------------------------------------------------
    @staticmethod
    def _last_column(res, group: RuleGroup, rule: Rule
                     ) -> List[Tuple[Dict[str, str], float]]:
        """The tick's samples: (labels, value) per series at the LAST
        grid step, NaN (no sample / filtered-out comparison) dropped."""
        out: List[Tuple[Dict[str, str], float]] = []
        if isinstance(res, ScalarResult):
            v = float(res.values[-1])
            if math.isfinite(v):
                out.append(({}, v))
            return out
        if not isinstance(res, GridResult):
            raise ValueError(
                f"rule {rule.name!r}: unsupported result "
                f"{type(res).__name__}")
        if res.is_hist():
            raise ValueError(
                f"rule {rule.name!r}: native-histogram results cannot "
                f"be recorded; aggregate to buckets/quantiles first")
        for i, key in enumerate(res.keys):
            v = float(res.values[i, -1])
            if math.isfinite(v):
                out.append((dict(key), v))
        if group.limit and len(out) > group.limit:
            raise ValueError(
                f"rule {rule.name!r}: produced {len(out)} series, over "
                f"the group limit {group.limit}")
        return out

    def _out_labels(self, metric: str, series_labels: Dict[str, str],
                    rule: Rule, extra: Optional[Dict[str, str]] = None
                    ) -> Dict[str, str]:
        """Re-tag one output series into the reserved rules dataset:
        internal identity labels, then the source series' labels, then
        the rule's static labels (which override, Prometheus
        semantics). No worker label — a recorded series is a LOGICAL
        series whose identity must survive evaluator failover."""
        labels = {"_ws_": RULES_TENANT, "_ns_": self.node or "node",
                  "_metric_": metric}
        for k, v in series_labels.items():
            if k not in _RESERVED_LABELS:
                labels[k] = v
        for k, v in rule.labels:
            labels[k] = v
        for k, v in (extra or {}).items():
            labels[k] = v
        return labels

    def _record_samples(self, group: RuleGroup, rule: Rule,
                        col: List[Tuple[Dict, float]], end_ms: int,
                        samples: List) -> int:
        from filodb_tpu.obs.writeback import schema_for_sample
        if rule.schema == "counter":
            schema = "prom-counter"
        elif rule.schema == "gauge":
            schema = "gauge"
        else:
            schema = schema_for_sample("", rule.name)
        for series_labels, value in col:
            samples.append((schema,
                            self._out_labels(rule.name, series_labels,
                                             rule),
                            end_ms, value))
        return len(col)

    # -- alert state machine ----------------------------------------------
    def _apply_alert_state(self, group: RuleGroup, rule: Rule,
                           col: List[Tuple[Dict, float]], at_s: float,
                           samples: List) -> int:
        """inactive -> pending -> firing (and back): the expression's
        surviving series are the ACTIVE set; a series held active for
        ``for:`` promotes to firing; a series that drops out resolves
        immediately. Called only on a SUCCESSFUL evaluation — an eval
        error keeps the previous state (alerts must not flap to
        inactive because the evaluator had a bad tick)."""
        rkey = (group.name, rule.name)
        fired: List[Dict] = []
        resolved: List[Dict] = []
        events: List[Dict] = []

        def note(labels: Dict, frm: str, to: str, value) -> None:
            events.append({
                "at": at_s, "group": group.name, "alert": rule.name,
                "from": frm, "to": to, "labels": dict(labels),
                "value": None if value is None else float(value)})

        with self._lock:
            insts = self._alerts.setdefault(rkey, {})
            active_now: Dict[Tuple, Tuple[Dict, float]] = {}
            for series_labels, value in col:
                ident = dict(series_labels)
                for k, v in rule.labels:
                    ident[k] = v
                ident.pop("_metric_", None)
                key = tuple(sorted(ident.items()))
                active_now[key] = (ident, value)
            for key, (ident, value) in active_now.items():
                inst = insts.get(key)
                if inst is None:
                    state = STATE_FIRING if rule.for_s <= 0 \
                        else STATE_PENDING
                    inst = {"labels": ident, "state": state,
                            "active_at": at_s, "value": value}
                    insts[key] = inst
                    note(ident, STATE_INACTIVE, state, value)
                    if state == STATE_FIRING:
                        fired.append(inst)
                else:
                    inst["value"] = value
                    if inst["state"] == STATE_PENDING \
                            and at_s - inst["active_at"] >= rule.for_s:
                        inst["state"] = STATE_FIRING
                        note(ident, STATE_PENDING, STATE_FIRING, value)
                        fired.append(inst)
            for key in [k for k in insts if k not in active_now]:
                inst = insts.pop(key)
                note(inst["labels"], inst["state"], STATE_INACTIVE,
                     inst.get("value"))
                if inst["state"] == STATE_FIRING:
                    resolved.append(inst)
            live = list(insts.values())
            self._transitions.extend(events)
        # counters + trace point events outside the lock (registry
        # family leaves are locked internally)
        for ev in events:
            self._m_transitions.inc(alertname=rule.name, to=ev["to"])
            obs_trace.event("alert-transition", alert=rule.name,
                            frm=ev["from"], to=ev["to"])
        # synthetic state series (Prometheus ALERTS/ALERTS_FOR_STATE):
        # one sample per active instance per tick
        end_ms = int(round(at_s * 1000))
        for inst in live:
            samples.append((
                "gauge",
                self._out_labels("ALERTS", inst["labels"], rule,
                                 extra={"alertname": rule.name,
                                        "alertstate": inst["state"]}),
                end_ms, 1.0))
            samples.append((
                "gauge",
                self._out_labels("ALERTS_FOR_STATE", inst["labels"],
                                 rule, extra={"alertname": rule.name}),
                end_ms, float(inst["active_at"])))
        self._update_alert_gauges(rule.name)
        if self.notifier is not None:
            for inst in fired:
                self._notify(group, rule, inst, "firing", at_s)
            for inst in resolved:
                self._notify(group, rule, inst, "resolved", at_s)
        return len(live)

    def _update_alert_gauges(self, alertname: str) -> None:
        # zeroed-by-default counts: a state an alert LEFT reads 0, not
        # its last nonzero value
        counts = {STATE_PENDING: 0, STATE_FIRING: 0}
        with self._lock:
            for (_g, rname), insts in self._alerts.items():
                if rname != alertname:
                    continue
                for inst in insts.values():
                    counts[inst["state"]] = \
                        counts.get(inst["state"], 0) + 1
        for state, n in counts.items():
            self._m_alerts.set(n, alertname=alertname, alertstate=state)

    def _notify(self, group: RuleGroup, rule: Rule, inst: Dict,
                status: str, at_s: float) -> None:
        labels = dict(inst["labels"])
        labels["alertname"] = rule.name
        ann = {k: _render_template(v, inst.get("value"), labels)
               for k, v in rule.annotations}
        self.notifier.enqueue({
            "status": status,
            "labels": labels,
            "annotations": ann,
            "value": inst.get("value"),
            "activeAt": inst.get("active_at"),
            "at": at_s,
            "group": group.name,
        })
        with self._lock:
            self.notifications_enqueued += 1

    # -- observability -----------------------------------------------------
    def _collect(self, builder) -> None:
        """Registry collector: election + per-group health gauges
        (values land on pre-created gauge families, so a reset registry
        is never repopulated by a stale engine)."""
        if self._stopped:
            return
        with self._lock:
            active = self.active
            groups = [(g.name, g.interval_s, len(g.rules),
                       self._group_state.get(g.name, {}))
                      for g in self.groups]
        self._m_active.set(1 if active else 0)
        now = self._clock()
        for name, interval_s, n_rules, st in groups:
            self._m_interval.set(interval_s, group=name)
            self._m_rules.set(n_rules, group=name)
            last_ok = st.get("last_success_wall")
            if last_ok is not None:
                self._m_staleness.set(round(max(0.0, now - last_ok), 3),
                                      group=name)

    # -- API payloads ------------------------------------------------------
    def rules_payload(self, explain: bool = False) -> Dict:
        """The ``/api/v1/rules`` data section (Prometheus shape, plus
        the engine's election/provenance fields; ``explain`` adds the
        retained last-evaluation detail per rule)."""
        groups_out = []
        with self._lock:
            rule_state = {k: dict(v) for k, v in self._rule_state.items()}
            alerts = {k: [dict(i) for i in v.values()]
                      for k, v in self._alerts.items()}
            group_state = {k: dict(v)
                           for k, v in self._group_state.items()}
            active = self.active
        for g in self.groups:
            st = group_state.get(g.name, {})
            rules_out = []
            for r in g.rules:
                rs = rule_state.get((g.name, r.name), {})
                le = rs.get("last_eval") or {}
                entry = {
                    "type": "alerting" if r.is_alert else "recording",
                    "name": r.name,
                    "query": r.expr,
                    "labels": dict(r.labels),
                    "health": rs.get("health", "unknown"),
                    "lastError": rs.get("last_error") or "",
                    "lastEvaluation": le.get("at"),
                    "evaluationTime": le.get("duration_s"),
                }
                if r.is_alert:
                    entry["duration"] = r.for_s
                    entry["annotations"] = dict(r.annotations)
                    insts = alerts.get((g.name, r.name), [])
                    entry["alerts"] = [self._alert_json(r, i)
                                       for i in insts]
                    entry["state"] = self._rule_alert_state(insts)
                if explain:
                    entry["lastEval"] = le
                rules_out.append(entry)
            groups_out.append({
                "name": g.name,
                "interval": g.interval_s,
                "dataset": g.dataset or self.default_dataset,
                "lastEvaluation": st.get("last_tick"),
                "evaluationTime": st.get("last_duration_s"),
                "rules": rules_out,
            })
        return {"groups": groups_out, "evaluating": active,
                "evaluator": self.evaluator_ordinal(),
                "worker": self.worker_id, "node": self.node}

    @staticmethod
    def _rule_alert_state(insts: List[Dict]) -> str:
        if any(i["state"] == STATE_FIRING for i in insts):
            return STATE_FIRING
        if insts:
            return STATE_PENDING
        return STATE_INACTIVE

    def _alert_json(self, rule: Rule, inst: Dict) -> Dict:
        labels = dict(inst["labels"])
        labels["alertname"] = rule.name
        return {
            "labels": labels,
            "annotations": {
                k: _render_template(v, inst.get("value"), labels)
                for k, v in rule.annotations},
            "state": inst["state"],
            "activeAt": inst.get("active_at"),
            "value": inst.get("value"),
        }

    def alerts_payload(self) -> Dict:
        """The ``/api/v1/alerts`` data section + the structured
        transition-event ring."""
        out = []
        with self._lock:
            items = [(rname, [dict(i) for i in insts.values()])
                     for (_g, rname), insts in self._alerts.items()]
            transitions = list(self._transitions)
        by_name = {r.name: r for g in self.groups for r in g.rules}
        for rname, insts in items:
            rule = by_name.get(rname)
            if rule is None:
                continue
            out.extend(self._alert_json(rule, i) for i in insts)
        return {"alerts": out, "transitions": transitions}

    def snapshot(self) -> Dict:
        with self._lock:
            return {"active": self.active,
                    "announced": self._announced,
                    "ordinal": self._ordinal,
                    "alive_ordinals": sorted(self._alive),
                    "groups": len(self.groups),
                    "ticks": self.ticks,
                    "errors": self.errors,
                    "plan_invalidations": self.plan_invalidations,
                    "notifications_enqueued":
                        self.notifications_enqueued,
                    "election_log": list(self._election_log),
                    "alive": self.alive}
