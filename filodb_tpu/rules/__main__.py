"""``python -m filodb_tpu.rules --check <file>``: promtool-style rule
file validation — structural checks, PromQL syntax through the NORMAL
parser (no second grammar to drift), promlint semantic analysis
(type/schema checking, label dataflow — spanned diagnostics), and
normalized duplicate-rule detection. Exit 0 = clean (warnings may
print); exit 1 = errors (printed one per line); exit 2 = usage."""

from __future__ import annotations

import argparse
import sys

from filodb_tpu.rules.loader import (check_rules_file_full,
                                     load_rules_file)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m filodb_tpu.rules")
    p.add_argument("--check", metavar="FILE",
                   help="validate a rule file and exit")
    args = p.parse_args(argv)
    if not args.check:
        p.print_usage(sys.stderr)
        return 2
    errors, warnings = check_rules_file_full(args.check)
    for w in warnings:
        print(f"{args.check}: warning: {w}")
    if errors:
        for e in errors:
            print(f"{args.check}: {e}")
        return 1
    groups = load_rules_file(args.check)
    n_rules = sum(len(g.rules) for g in groups)
    print(f"{args.check}: OK — {len(groups)} group(s), "
          f"{n_rules} rule(s)"
          + (f", {len(warnings)} warning(s)" if warnings else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
