"""Recording rules & alerting: the in-process continuous-query engine.

``loader`` parses Prometheus-style rule files, ``engine`` schedules and
evaluates them as standing queries through the normal serving path,
``notify`` delivers alert webhooks through the resilience stack, and
``python -m filodb_tpu.rules --check <file>`` validates a rule file
promtool-style.
"""

from filodb_tpu.rules.engine import (RULES_DATASET, RulesEngine,
                                     STATE_FIRING, STATE_INACTIVE,
                                     STATE_PENDING)
from filodb_tpu.rules.loader import (Rule, RuleGroup, RuleLoadError,
                                     check_rules_file, load_groups,
                                     load_rules_file, parse_rules_text)
from filodb_tpu.rules.notify import WebhookNotifier

__all__ = [
    "RULES_DATASET", "RulesEngine", "STATE_FIRING", "STATE_INACTIVE",
    "STATE_PENDING", "Rule", "RuleGroup", "RuleLoadError",
    "check_rules_file", "load_groups", "load_rules_file",
    "parse_rules_text", "WebhookNotifier",
]
