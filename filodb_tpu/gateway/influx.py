"""Influx line protocol -> ingest records.

(Reference: gateway/src/main/scala/filodb/gateway/conversion/
InfluxProtocolParser.scala:69 + InputRecord.scala — the gateway's TCP
ingest format. Syntax: `measurement[,tag=value...] field=value[,f2=v2...]
[timestamp-ns]` with escaping of commas/spaces/equals in identifiers.)

Schema mapping mirrors InputRecord.scala:
  * single field `gauge`/`value`   -> gauge schema
  * field `counter`                -> prom-counter
  * fields `sum`,`count`,`+Inf`... -> prom-histogram (le-bucket fields)
  * otherwise each numeric field becomes its own gauge series with
    `_field_` label (the reference appends the field name to the metric)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import Schemas
from filodb_tpu.memory.histogram import CustomBuckets


class InfluxParseError(ValueError):
    pass


@dataclass
class InfluxRecord:
    measurement: str
    tags: Dict[str, str]
    fields: Dict[str, float]
    timestamp_ms: int


def _split_escaped(s: str, sep: str) -> List[str]:
    """Split on sep, honoring backslash escapes."""
    out: List[str] = []
    cur: List[str] = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(s[i + 1])
            i += 2
            continue
        if c == sep:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _split_top(s: str) -> Tuple[str, str, Optional[str]]:
    """Split a line into (identity, fieldset, timestamp) on unescaped
    spaces (InfluxProtocolParser.parse top-level scan)."""
    parts: List[str] = []
    cur: List[str] = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(c)
            cur.append(s[i + 1])
            i += 2
            continue
        if c == " ":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(c)
        i += 1
    parts.append("".join(cur))
    parts = [p for p in parts if p]
    if len(parts) == 2:
        return parts[0], parts[1], None
    if len(parts) == 3:
        return parts[0], parts[1], parts[2]
    raise InfluxParseError(f"bad influx line: {s!r}")


def parse_line(line: str, now_ms: Optional[int] = None) -> InfluxRecord:
    ident, fieldset, ts_raw = _split_top(line.strip())
    id_parts = _split_escaped(ident, ",")
    measurement = id_parts[0]
    tags: Dict[str, str] = {}
    for kv in id_parts[1:]:
        k, _, v = kv.partition("=")
        if not k or not v:
            raise InfluxParseError(f"bad tag {kv!r} in {line!r}")
        tags[k] = v
    fields: Dict[str, float] = {}
    for kv in _split_escaped(fieldset, ","):
        k, _, v = kv.partition("=")
        if not k or not v:
            raise InfluxParseError(f"bad field {kv!r} in {line!r}")
        v = v.strip()
        if v.endswith("i"):
            v = v[:-1]
        if v.startswith('"'):
            continue                      # string fields are not ingestable
        try:
            fields[k] = float(v)
        except ValueError as e:
            raise InfluxParseError(f"bad field value {kv!r}") from e
    if not fields:
        raise InfluxParseError(f"no numeric fields in {line!r}")
    if ts_raw is not None:
        timestamp_ms = int(ts_raw) // 1_000_000      # ns -> ms
    else:
        import time
        timestamp_ms = now_ms if now_ms is not None else int(
            time.time() * 1000)
    return InfluxRecord(measurement, tags, fields, timestamp_ms)


# -- InputRecord mapping (conversion/InputRecord.scala) ---------------------

def input_records(rec: InfluxRecord, ws: str = "demo", ns: str = "App-0"
                  ) -> List[Tuple[str, Dict[str, str], int, Tuple]]:
    """Map one parsed influx record to ingest samples:
    (schema_name, labels, timestamp_ms, values) tuples — the InputRecord
    schema-mapping logic (conversion/InputRecord.scala), separated from
    builder insertion so callers can shard-route each sample first."""
    tags = dict(rec.tags)
    ws = tags.pop("_ws_", ws)
    ns = tags.pop("_ns_", ns)
    base = {"_ws_": ws, "_ns_": ns, **tags}
    fields = rec.fields
    out: List[Tuple[str, Dict[str, str], int, Tuple]] = []
    le_fields = {k: v for k, v in fields.items()
                 if k not in ("sum", "count", "min", "max")
                 and _is_le(k)}
    if "sum" in fields and "count" in fields and le_fields:
        les = sorted(le_fields, key=lambda k: float(
            "inf") if k in ("+Inf", "inf") else float(k))
        scheme = CustomBuckets(tuple(
            float("inf") if k in ("+Inf", "inf") else float(k)
            for k in les))
        counts = np.array([le_fields[k] for k in les], dtype=np.float64)
        out.append(("prom-histogram",
                    {**base, "_metric_": rec.measurement}, rec.timestamp_ms,
                    (fields["sum"], fields["count"], (scheme, counts))))
        return out
    if "counter" in fields:
        out.append(("prom-counter", {**base, "_metric_": rec.measurement},
                    rec.timestamp_ms, (fields["counter"],)))
        return out
    single = None
    for name in ("gauge", "value"):
        if name in fields:
            single = fields[name]
            break
    if single is not None:
        out.append(("gauge", {**base, "_metric_": rec.measurement},
                    rec.timestamp_ms, (single,)))
        return out
    for fname, fval in fields.items():
        metric = f"{rec.measurement}_{fname}"
        out.append(("gauge", {**base, "_metric_": metric},
                    rec.timestamp_ms, (fval,)))
    return out


def record_to_builder(rec: InfluxRecord, builder: RecordBuilder,
                      ws: str = "demo", ns: str = "App-0") -> List[str]:
    """Convert one parsed record into builder samples; returns the schema
    names used. Shard-key labels default like the dev gateway conf."""
    used: List[str] = []
    for schema_name, labels, ts, values in input_records(rec, ws, ns):
        builder.add_sample(schema_name, labels, ts, *values)
        used.append(schema_name)
    return used


def _is_le(k: str) -> bool:
    if k in ("+Inf", "inf"):
        return True
    try:
        float(k)
        return True
    except ValueError:
        return False


def parse_lines(text: str, builder: RecordBuilder,
                now_ms: Optional[int] = None) -> int:
    """Parse a batch of lines into a builder; returns records ingested."""
    n = 0
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        record_to_builder(parse_line(line, now_ms), builder)
        n += 1
    return n
