"""Gateway TCP server: influx line protocol in, per-shard streams out.

(Reference: gateway/src/main/scala/filodb/gateway/GatewayServer.scala —
Netty TCP server :60 parsing influx lines, computing shardKeyHash/
partKeyHash and routing via shardMapper.ingestionShard :120,164, batching
per-shard RecordBuilders, publishing containers to Kafka via
KafkaContainerSink.  Here "Kafka" is the per-shard LogIngestionStream and
the server is a stdlib ThreadingTCPServer — the ingest edge is host-side
I/O, not device work.)

Wire protocol: newline-delimited influx lines; `#`-prefixed lines are
comments.  Batches are published per shard every ``batch_lines`` lines or
when a connection closes, preserving per-connection ordering per shard.
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Dict, List, Optional

from filodb_tpu.core.record import RecordBuilder, ingestion_shard
from filodb_tpu.ingest import health as ingest_health
from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.core.record import PartKey
from filodb_tpu.core.schemas import PartitionSchema, Schemas
from filodb_tpu.gateway.influx import input_records, parse_line
from filodb_tpu.ingest.stream import IngestionStream


@guarded_by("_stats_lock", "lines_ingested", "lines_rejected",
            "batches_dropped")
class GatewayServer:
    """TCP ingest edge, one instance per gateway process.

    Line/drop counters ride ``_stats_lock``: producer threads (one per
    TCP connection) and the HTTP ingest edge (``/api/v1/ingest/influx``
    handler threads) both route lines through this object."""

    def __init__(self, streams: Dict[int, IngestionStream], schemas: Schemas,
                 num_shards: int, spread: int = 1, port: int = 0,
                 host: str = "127.0.0.1", batch_lines: int = 256,
                 ws: str = "demo", ns: str = "App-0",
                 spread_provider=None):
        self.streams = streams
        self.schemas = schemas
        self.num_shards = num_shards
        self.spread = spread
        # per-shard-key overrides; the planner prunes with the SAME
        # provider so ingest and query always agree (SpreadProvider)
        self.spread_provider = spread_provider
        self.batch_lines = batch_lines
        self.ws, self.ns = ws, ns
        self.part_schema = PartitionSchema()
        self._stats_lock = threading.Lock()
        self.lines_ingested = 0
        self.lines_rejected = 0
        # batches dropped while ingest is degraded to read-only (the
        # fire-and-forget TCP edge has no backpressure channel — counted
        # loss beats a crashed producer thread; HTTP ingest gets a 503)
        self.batches_dropped = 0
        gateway = self

        class Handler(socketserver.StreamRequestHandler):
            # per-connection producer thread (ThreadingTCPServer spawn
            # the AST engine cannot see)
            @thread_root("gateway-producer")
            def handle(self):
                builders: Dict[int, RecordBuilder] = {}
                pending = 0
                for raw in self.rfile:
                    line = raw.decode("utf-8", errors="replace").strip()
                    if not line or line.startswith("#"):
                        continue
                    if gateway._route_line(line, builders):
                        pending += 1
                    if pending >= gateway.batch_lines:
                        gateway._publish(builders)
                        pending = 0
                gateway._publish(builders)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    # -- routing -----------------------------------------------------------
    def _route_line(self, line: str, builders: Dict[int, RecordBuilder]
                    ) -> bool:
        """Parse one line, append each resulting sample to its shard's
        builder (GatewayServer.scala:120 shardKeyHash->ingestionShard)."""
        try:
            rec = parse_line(line)
            samples = input_records(rec, self.ws, self.ns)
        except ValueError:
            with self._stats_lock:
                self.lines_rejected += 1
            return False
        for schema_name, labels, ts, values in samples:
            schema = self.schemas.by_name(schema_name)
            pk = PartKey.make(schema, labels)
            if self.spread_provider is not None:
                spread = self.spread_provider.spread_for_labels(
                    labels, self.part_schema.non_metric_shard_key_columns)
            else:
                spread = self.spread
            shard = ingestion_shard(pk.shard_key_hash(self.part_schema),
                                    pk.part_hash(), spread,
                                    self.num_shards)
            b = builders.setdefault(shard, RecordBuilder(self.schemas))
            b.add_sample(schema_name, labels, ts, *values)
        with self._stats_lock:
            self.lines_ingested += 1
        return True

    def _publish(self, builders: Dict[int, RecordBuilder],
                 raise_on_error: bool = False) -> None:
        """Flush per-shard builders into their streams (KafkaContainerSink).

        Write-path out-of-space degrades instead of crashing the
        producer thread: the process flips to ingest-read-only
        (ingest/health.py), and while degraded this edge DROPS batches
        (counted) except for the rate-limited probe write that detects
        recovery. ``raise_on_error=True`` (the HTTP ingest edge) raises
        :class:`~filodb_tpu.ingest.health.IngestReadOnly` instead so
        the caller can answer 503 + Retry-After."""
        health = ingest_health.GLOBAL
        if health.read_only() and not health.should_probe():
            # containers() drains the builders — the batch is lost
            # either way (dropped here, or retried wholesale by the
            # HTTP caller after its 503)
            dropped = sum(len(b.containers()) for b in builders.values())
            if dropped:
                with self._stats_lock:
                    self.batches_dropped += 1
            if raise_on_error:
                raise health.reject()
            return
        wrote = False
        for shard, b in builders.items():
            stream = self.streams.get(shard)
            if stream is None:
                continue
            for cont in b.containers():
                try:
                    stream.append(cont)
                    wrote = True
                except OSError as e:
                    if health.note_write_error(e, "gateway publish"):
                        with self._stats_lock:
                            self.batches_dropped += 1
                        if raise_on_error:
                            raise health.reject() from e
                        return
                    raise
        if wrote:
            health.note_write_ok()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "GatewayServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="gateway-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @property
    def port(self) -> int:
        return self._server.server_address[1]


def send_lines(host: str, port: int, lines: List[str],
               timeout: float = 10.0) -> None:
    """Small client for tests/tools: push influx lines to a gateway."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        payload = ("\n".join(lines) + "\n").encode()
        s.sendall(payload)
