"""Ingest gateway: test-data producers + Influx line protocol
(reference: gateway/GatewayServer.scala, conversion/InfluxProtocolParser.scala,
TestTimeseriesProducer)."""
