"""Synthetic time-series producers (gateway TestTimeseriesProducer
equivalent, gateway/src/main/scala/filodb/timeseries/
TestTimeseriesProducer.scala) — deterministic dev/test data shaped like the
reference's: `heap_usage` gauges, `http_requests_total` counters and
`http_request_latency` histograms across n instances, sharded exactly the
way the reference shards (shard-key hash + spread via ShardMapper)."""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from filodb_tpu.core.record import (PartKey, RecordBuilder, ingestion_shard,
                                    shard_key_hash)
from filodb_tpu.core.schemas import Schemas
from filodb_tpu.memory.histogram import CustomBuckets


class TestTimeseriesProducer:
    """Generates samples into per-shard RecordBuilders."""

    __test__ = False          # named after the reference class, not a test

    def __init__(self, schemas: Schemas, num_shards: int = 4,
                 spread: int = 1, ws: str = "demo", ns: str = "App-0"):
        self.schemas = schemas
        self.num_shards = num_shards
        self.spread = spread
        self.ws, self.ns = ws, ns

    def _labels(self, metric: str, instance: int) -> Dict[str, str]:
        return {"_metric_": metric, "_ws_": self.ws, "_ns_": self.ns,
                "job": "test", "instance": f"instance-{instance}",
                "host": f"h{instance % 4}"}

    def shard_for(self, schema_name: str, labels: Dict[str, str]) -> int:
        from filodb_tpu.core.schemas import PartitionSchema
        schema = self.schemas.by_name(schema_name)
        pk = PartKey.make(schema, labels)
        skh = pk.shard_key_hash(PartitionSchema())
        return ingestion_shard(skh, pk.part_hash(), self.spread,
                               self.num_shards)

    def gauges(self, start_ms: int, n_samples: int, n_instances: int = 4,
               step_ms: int = 10_000, metric: str = "heap_usage"
               ) -> Dict[int, RecordBuilder]:
        """Sinusoid-ish gauges (TestTimeseriesProducer gauge shape)."""
        builders: Dict[int, RecordBuilder] = {}
        for inst in range(n_instances):
            labels = self._labels(metric, inst)
            shard = self.shard_for("gauge", labels)
            b = builders.setdefault(shard, RecordBuilder(self.schemas))
            for i in range(n_samples):
                val = 15.0 + 8.0 * math.sin((i + inst) / 10.0) \
                    + (i % 5) * 0.1
                b.add_sample("gauge", labels, start_ms + i * step_ms, val)
        return builders

    def counters(self, start_ms: int, n_samples: int, n_instances: int = 4,
                 step_ms: int = 10_000,
                 metric: str = "http_requests_total"
                 ) -> Dict[int, RecordBuilder]:
        builders: Dict[int, RecordBuilder] = {}
        for inst in range(n_instances):
            labels = self._labels(metric, inst)
            shard = self.shard_for("prom-counter", labels)
            b = builders.setdefault(shard, RecordBuilder(self.schemas))
            v = 0.0
            for i in range(n_samples):
                v += (inst + 1) * 10.0
                b.add_sample("prom-counter", labels,
                             start_ms + i * step_ms, v)
        return builders

    def histograms(self, start_ms: int, n_samples: int, n_instances: int = 2,
                   step_ms: int = 10_000,
                   metric: str = "http_request_latency",
                   les: Iterable[float] = (2, 4, 8, 16, 32, 64, float("inf"))
                   ) -> Dict[int, RecordBuilder]:
        """Prom-histogram samples (sum, count, hist) with fixed buckets."""
        les_arr = np.asarray(list(les), dtype=np.float64)
        buckets = CustomBuckets(les_arr)
        builders: Dict[int, RecordBuilder] = {}
        rng = np.random.default_rng(42)
        for inst in range(n_instances):
            labels = self._labels(metric, inst)
            shard = self.shard_for("prom-histogram", labels)
            b = builders.setdefault(shard, RecordBuilder(self.schemas))
            cum = np.zeros(les_arr.size)
            total, count = 0.0, 0
            for i in range(n_samples):
                lat = rng.exponential(8.0)
                cum += (les_arr >= lat)
                total += lat
                count += 1
                b.add_sample("prom-histogram", labels,
                             start_ms + i * step_ms,
                             total, float(count), (buckets, cum.copy()))
        return builders


def ingest_builders(store, ref, builders: Dict[int, RecordBuilder]) -> int:
    """Push per-shard builders into a TimeSeriesMemStore; returns rows."""
    n = 0
    for shard, b in builders.items():
        for c in b.containers():
            store.ingest(ref, shard, c)
            n += len(c)
    return n
