"""filodb_tpu: a TPU-native, Prometheus-compatible time-series database framework.

Re-designed from scratch for TPU (JAX/XLA/Pallas/pjit) with the capabilities of
the FiloDB reference (Scala/Akka, see /root/reference):

- ``memory``    : columnar chunk codecs (NibblePack, delta-delta, XOR doubles,
                  histogram 2D-delta) — bit-compatible interchange formats plus
                  device-friendly dense tile layouts.
- ``core``      : record format, schemas, the in-memory time-series store
                  (shards, partitions, write buffers, flush, tag index).
- ``query``     : LogicalPlan -> ExecPlan -> range functions / aggregators with
                  a numpy oracle backend and a JAX/TPU backend.
- ``promql``    : PromQL parser producing LogicalPlans.
- ``parallel``  : shard <-> mesh mapping, scatter-gather over jax.sharding.
- ``store``     : persistent column store + checkpointing.
- ``http``      : Prometheus-compatible HTTP API.
- ``downsample``: batch downsampler driven by the same device kernels.
"""

__version__ = "0.1.0"
