// NibblePack codec — native implementation of the interchange bit format
// (reference: memory/src/main/scala/filodb.memory/format/NibblePack.scala:12,
// spec doc/compression.md "Predictive NibblePacking"; bit-compatible with
// filodb_tpu/memory/nibblepack.py, which is the behavioral oracle).
//
// This is the ⚙ "native layer" SURVEY §2.1 calls for: the per-sample encode
// loops on the ingest/flush hot path run here instead of the Python
// interpreter. Exposed as a plain C ABI for ctypes (no pybind11 in the
// image); all little-endian (TPU hosts are x86/ARM LE).
//
// Build: g++ -O3 -shared -fPIC -o _nibblepack.so nibblepack.cpp
// (done on demand by filodb_tpu/native/__init__.py).

#include <cstdint>
#include <cstring>

namespace {

inline int nlz64(uint64_t x) { return x ? __builtin_clzll(x) : 64; }
inline int ntz64(uint64_t x) { return x ? __builtin_ctzll(x) : 64; }

struct Writer {
    uint8_t* p;
    long pos;
};

// NibblePack.scala:105 pack8 — one 8-word group.
void pack8(const uint64_t* words, Writer& w) {
    uint8_t bitmask = 0;
    for (int i = 0; i < 8; i++)
        if (words[i]) bitmask |= (uint8_t)(1u << i);
    w.p[w.pos++] = bitmask;
    if (!bitmask) return;

    int min_lz = 64, min_tz = 64;
    for (int i = 0; i < 8; i++) {
        uint64_t v = words[i];
        int lz = nlz64(v), tz = ntz64(v);
        if (lz < min_lz) min_lz = lz;
        if (tz < min_tz) min_tz = tz;
    }
    int trailing_nibbles = min_tz / 4;
    int num_nibbles = 16 - min_lz / 4 - trailing_nibbles;
    w.p[w.pos++] =
        (uint8_t)(((num_nibbles - 1) << 4) | trailing_nibbles);

    int trailing_shift = trailing_nibbles * 4;
    int num_bits = num_nibbles * 4;
    uint64_t out_word = 0;
    int bit_cursor = 0;   // always in [0, 63]
    for (int i = 0; i < 8; i++) {
        uint64_t v = words[i];
        if (!v) continue;
        int remaining = 64 - bit_cursor;
        uint64_t shifted = v >> trailing_shift;
        out_word |= shifted << bit_cursor;
        if (remaining <= num_bits) {
            std::memcpy(w.p + w.pos, &out_word, 8);
            w.pos += 8;
            out_word = (remaining < num_bits) ? (shifted >> remaining) : 0;
        }
        bit_cursor = (bit_cursor + num_bits) % 64;
    }
    if (bit_cursor > 0) {
        int nb = (bit_cursor + 7) / 8;
        std::memcpy(w.p + w.pos, &out_word, nb);
        w.pos += nb;
    }
}

// NibblePack.scala:373 unpack8. Returns new pos, or -1 on short input.
inline uint64_t read_word(const uint8_t* buf, long n, long idx) {
    uint64_t v = 0;
    long take = (idx + 8 <= n) ? 8 : (idx < n ? n - idx : 0);
    if (take > 0) std::memcpy(&v, buf + idx, (size_t)take);
    return v;
}

long unpack8(const uint8_t* buf, long n, long pos, uint64_t* out) {
    if (pos >= n) return -1;
    uint8_t bitmask = buf[pos];
    if (!bitmask) {
        for (int i = 0; i < 8; i++) out[i] = 0;
        return pos + 1;
    }
    if (pos + 1 >= n) return -1;
    uint8_t nib = buf[pos + 1];
    int num_bits = ((nib >> 4) + 1) * 4;
    int trailing_zeroes = (nib & 0x0F) * 4;   // <= 60
    long total_bytes =
        2 + (num_bits * __builtin_popcount(bitmask) + 7) / 8;
    uint64_t mask =
        (num_bits >= 64) ? ~0ULL : ((1ULL << num_bits) - 1);
    long buf_index = pos + 2;
    int bit_cursor = 0;
    uint64_t in_word = read_word(buf, n, buf_index);
    buf_index += 8;
    for (int bit = 0; bit < 8; bit++) {
        if (bitmask & (1u << bit)) {
            int remaining = 64 - bit_cursor;
            uint64_t out_word = (in_word >> bit_cursor) & mask;
            if (remaining <= num_bits && (buf_index - pos) < total_bytes) {
                if (buf_index < n) {
                    in_word = read_word(buf, n, buf_index);
                    buf_index += 8;
                    if (remaining < num_bits)
                        out_word |= (in_word << remaining) & mask;
                } else {
                    return -1;
                }
            }
            out[bit] = out_word << trailing_zeroes;
            bit_cursor = (bit_cursor + num_bits) % 64;
        } else {
            out[bit] = 0;
        }
    }
    return pos + total_bytes;
}

}  // namespace

extern "C" {

// Each packer returns bytes written. Caller sizes `out` for the worst
// case: ceil(n/8) groups * 66 bytes (+8 for the doubles header).

long np_pack_non_increasing(const uint64_t* vals, long n, uint8_t* out) {
    Writer w{out, 0};
    uint64_t group[8];
    long i = 0;
    for (; i + 8 <= n; i += 8) {
        std::memcpy(group, vals + i, 64);
        pack8(group, w);
    }
    if (i < n) {
        for (int j = 0; j < 8; j++)
            group[j] = (i + j < n) ? vals[i + j] : 0;
        pack8(group, w);
    }
    return w.pos;
}

// NibblePack.scala:37 packDelta (negative deltas clamp to 0).
long np_pack_delta(const int64_t* vals, long n, uint8_t* out) {
    Writer w{out, 0};
    uint64_t group[8];
    int64_t last = 0;
    int k = 0;
    for (long i = 0; i < n; i++) {
        int64_t v = vals[i];
        group[k] = (v >= last) ? (uint64_t)(v - last) : 0;
        last = v;
        if (++k == 8) { pack8(group, w); k = 0; }
    }
    if (k) {
        for (; k < 8; k++) group[k] = 0;
        pack8(group, w);
    }
    return w.pos;
}

// NibblePack.scala:70 packDoubles: first value raw LE, rest XOR deltas.
long np_pack_doubles(const double* vals, long n, uint8_t* out) {
    if (n <= 0) return -1;
    Writer w{out, 0};
    std::memcpy(w.p, vals, 8);
    w.pos = 8;
    uint64_t group[8];
    uint64_t last;
    std::memcpy(&last, vals, 8);
    int k = 0;
    for (long i = 1; i < n; i++) {
        uint64_t b;
        std::memcpy(&b, vals + i, 8);
        group[k] = b ^ last;
        last = b;
        if (++k == 8) { pack8(group, w); k = 0; }
    }
    if (k) {
        for (; k < 8; k++) group[k] = 0;
        pack8(group, w);
    }
    return w.pos;
}

// Raw u64 words out. Returns new position, or -1 on short input.
long np_unpack_words(const uint8_t* buf, long buflen, long pos, long n,
                     uint64_t* out) {
    uint64_t group[8];
    long left = n;
    uint64_t* o = out;
    while (left > 0) {
        pos = unpack8(buf, buflen, pos, group);
        if (pos < 0) return -1;
        long take = left < 8 ? left : 8;
        std::memcpy(o, group, (size_t)take * 8);
        o += take;
        left -= take;
    }
    return pos;
}

// DeltaSink (NibblePack.scala:205): running sum of deltas.
long np_unpack_delta(const uint8_t* buf, long buflen, long pos, long n,
                     int64_t* out) {
    uint64_t group[8];
    int64_t acc = 0;
    long left = n, oi = 0;
    while (left > 0) {
        pos = unpack8(buf, buflen, pos, group);
        if (pos < 0) return -1;
        long take = left < 8 ? left : 8;
        for (long j = 0; j < take; j++) {
            acc += (int64_t)group[j];
            out[oi++] = acc;
        }
        left -= take;
    }
    return pos;
}

// DoubleXORSink (NibblePack.scala:225/:352): first raw, rest XOR chain.
long np_unpack_double_xor(const uint8_t* buf, long buflen, long pos,
                          long n, double* out) {
    if (n <= 0 || buflen - pos < 8) return -1;
    uint64_t bits;
    std::memcpy(&bits, buf + pos, 8);
    pos += 8;
    std::memcpy(out, &bits, 8);
    uint64_t group[8];
    long left = n - 1, oi = 1;
    while (left > 0) {
        pos = unpack8(buf, buflen, pos, group);
        if (pos < 0) return -1;
        long take = left < 8 ? left : 8;
        for (long j = 0; j < take; j++) {
            bits ^= group[j];
            std::memcpy(out + oi, &bits, 8);
            oi++;
        }
        left -= take;
    }
    return pos;
}

}  // extern "C"
