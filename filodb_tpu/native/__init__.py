"""Native (C++) runtime components, loaded via ctypes.

The reference's `memory/` module is "native code written in Scala" — raw
off-heap pointer work (SURVEY §2.1, format/UnsafeUtils.scala). Here the
host-side hot loops live in real C++ compiled on demand with g++ (the
image has no pybind11; the C ABI + ctypes keeps the binding surface
trivial). Python implementations remain the behavioral oracle and the
fallback when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import tempfile
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "nibblepack.cpp")
_LIB_NAME = f"_nibblepack_{sys.platform}.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build(lib_path: str) -> bool:
    """Compile the codec; atomic rename so concurrent builders are safe."""
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib_path)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load_nibblepack() -> Optional[ctypes.CDLL]:
    """The compiled codec, building it on first use; None when unavailable
    (callers keep the Python path)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        lib_path = os.path.join(_DIR, _LIB_NAME)
        fresh = (os.path.exists(lib_path)
                 and os.path.getmtime(lib_path) >= os.path.getmtime(_SRC))
        # graftlint: disable=lock-blocking-reachable (one-time native build on first use; the lock exists to prevent duplicate concurrent compiles)
        if not fresh and not _build(lib_path):
            return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            return None
        L = ctypes.c_long
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f64p = ctypes.POINTER(ctypes.c_double)
        lib.np_pack_non_increasing.restype = L
        lib.np_pack_non_increasing.argtypes = [u64p, L, u8p]
        lib.np_pack_delta.restype = L
        lib.np_pack_delta.argtypes = [i64p, L, u8p]
        lib.np_pack_doubles.restype = L
        lib.np_pack_doubles.argtypes = [f64p, L, u8p]
        lib.np_unpack_words.restype = L
        lib.np_unpack_words.argtypes = [u8p, L, L, L, u64p]
        lib.np_unpack_delta.restype = L
        lib.np_unpack_delta.argtypes = [u8p, L, L, L, i64p]
        lib.np_unpack_double_xor.restype = L
        lib.np_unpack_double_xor.argtypes = [u8p, L, L, L, f64p]
        _lib = lib
        return _lib
