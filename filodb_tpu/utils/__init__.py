"""Shared utilities: hashing, config, logging, metrics."""
