"""Pure-Python XXHash32, matching the reference's series hashing
(memory/src/main/scala/filodb.memory/BinaryRegion.scala:20-37 — lz4 XXHash32
with seed 0x9747b28c).  Shard routing compatibility depends on these hashes
(coordinator/ShardMapper.scala:122), so results are pinned by tests against
known xxh32 vectors.

Returns *signed* 32-bit ints to mirror JVM ``Int`` semantics, since the
reference's ``combineHash`` (RecordBuilder.scala:638) does Java int overflow
arithmetic.
"""

from __future__ import annotations

_P1 = 2654435761
_P2 = 2246822519
_P3 = 3266489917
_P4 = 668265263
_P5 = 374761393
_M32 = 0xFFFFFFFF

SEED = 0x9747B28C


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def _round(acc: int, lane: int) -> int:
    acc = (acc + lane * _P2) & _M32
    return (_rotl(acc, 13) * _P1) & _M32


def xxhash32(data: bytes, seed: int = SEED) -> int:
    """XXH32 of ``data``; returns signed 32-bit int (Java Int semantics)."""
    n = len(data)
    i = 0
    if n >= 16:
        v1 = (seed + _P1 + _P2) & _M32
        v2 = (seed + _P2) & _M32
        v3 = seed & _M32
        v4 = (seed - _P1) & _M32
        limit = n - 16
        while i <= limit:
            v1 = _round(v1, int.from_bytes(data[i : i + 4], "little"))
            v2 = _round(v2, int.from_bytes(data[i + 4 : i + 8], "little"))
            v3 = _round(v3, int.from_bytes(data[i + 8 : i + 12], "little"))
            v4 = _round(v4, int.from_bytes(data[i + 12 : i + 16], "little"))
            i += 16
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)) & _M32
    else:
        h = (seed + _P5) & _M32
    h = (h + n) & _M32
    while i + 4 <= n:
        h = (h + int.from_bytes(data[i : i + 4], "little") * _P3) & _M32
        h = (_rotl(h, 17) * _P4) & _M32
        i += 4
    while i < n:
        h = (h + data[i] * _P5) & _M32
        h = (_rotl(h, 11) * _P1) & _M32
        i += 1
    h ^= h >> 15
    h = (h * _P2) & _M32
    h ^= h >> 13
    h = (h * _P3) & _M32
    h ^= h >> 16
    return h - (1 << 32) if h >= (1 << 31) else h


def to_signed32(x: int) -> int:
    x &= _M32
    return x - (1 << 32) if x >= (1 << 31) else x
