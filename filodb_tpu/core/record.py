"""Ingest records and partition keys.

TPU-native analogue of BinaryRecord v2
(core/src/main/scala/filodb.core/binaryrecord2/RecordBuilder.scala:34,
RecordSchema.scala:47, RecordContainer.scala).  The reference's format exists
to avoid JVM serialization; here the equivalent "zero-copy to the engine" goal
is met by columnar numpy batches (``RecordContainer`` below), while partition
keys keep a canonical binary form for persistence and index bootstrap.

**Hash compatibility is preserved exactly** — shard routing must agree with
the reference cluster (RecordBuilder.scala:638 combineHash, :667 shardKeyHash;
ShardMapper.scala:122 ingestionShard), pinned by tests.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.schemas import DataSchema, PartitionSchema, Schemas
from filodb_tpu.lint.locks import single_writer
from filodb_tpu.utils.xxhash import to_signed32, xxhash32

_M32 = 0xFFFFFFFF


def combine_hash(h1: int, h2: int) -> int:
    """31*h1 + h2 with Java Int overflow (RecordBuilder.scala:638)."""
    return to_signed32(31 * (h1 & _M32) + (h2 & _M32))


def shard_key_hash(shard_key_values: Sequence[str], metric: str,
                   include_metric: bool = True) -> int:
    """Hash of the shard-key label *values* in key-name order, then the metric
    (RecordBuilder.scala:667-683)."""
    h = 7
    for v in shard_key_values:
        h = combine_hash(h, xxhash32(v.encode()))
    if include_metric:
        h = combine_hash(h, xxhash32(metric.encode()))
    return h


def sort_and_compute_hashes(pairs: Sequence[Tuple[str, str]]) -> Tuple[
        List[Tuple[str, str]], List[int]]:
    """Sort label pairs by key and hash each (RecordBuilder.scala:618)."""
    spairs = sorted(pairs, key=lambda kv: kv[0])
    hashes = [
        combine_hash(xxhash32(k.encode()), xxhash32(v.encode()))
        for k, v in spairs
    ]
    return spairs, hashes


def combine_hash_excluding(sorted_pairs: Sequence[Tuple[str, str]],
                           hashes: Sequence[int],
                           exclude_keys) -> int:
    """(RecordBuilder.scala:648 combineHashExcluding)."""
    h = 7
    for (k, _), kh in zip(sorted_pairs, hashes):
        if k not in exclude_keys:
            h = combine_hash(h, kh)
    return h


def partition_key_hash(labels: Mapping[str, str]) -> int:
    """Full partition hash over ALL labels, used with shardKeyHash to pick the
    ingestion shard (RecordBuilder partKeyHash semantics)."""
    spairs, hashes = sort_and_compute_hashes(list(labels.items()))
    return combine_hash_excluding(spairs, hashes, frozenset())


def ingestion_shard(shard_key_h: int, partition_h: int, spread: int,
                    num_shards: int) -> int:
    """Shard selection (coordinator/ShardMapper.scala:122): lower
    (log2NumShards - spread) bits from the shard-key hash, upper ``spread``
    bits from the partition hash."""
    log2 = num_shards.bit_length() - 1
    if (1 << log2) != num_shards:
        raise ValueError("num_shards must be a power of 2")
    if not 0 <= spread <= log2:
        raise ValueError(f"invalid spread {spread} for {num_shards} shards")
    shard_mask = (1 << (log2 - spread)) - 1
    part_mask = ((1 << log2) - 1) & ~shard_mask
    return (shard_key_h & shard_mask) | (partition_h & part_mask)


def query_shards(shard_key_h: int, spread: int, num_shards: int) -> List[int]:
    """All shards that may hold a shard key (ShardMapper.scala:93)."""
    log2 = num_shards.bit_length() - 1
    shard_mask = (1 << (log2 - spread)) - 1
    base = shard_key_h & shard_mask
    spacing = 1 << (log2 - spread)
    return list(range(base, num_shards, spacing))


# ---------------------------------------------------------------------------
# Partition key
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PartKey:
    """One time series identity: schema + full label set
    (binaryrecord2 partition key; schemaID embedded per Schemas.scala).

    ``labels`` includes the metric label (default ``_metric_``) and shard-key
    labels (``_ws_``, ``_ns_``)."""
    schema_id: int
    labels: Tuple[Tuple[str, str], ...]  # sorted by key

    @staticmethod
    def make(schema: DataSchema, labels: Mapping[str, str]) -> "PartKey":
        return PartKey(schema.schema_id, tuple(sorted(labels.items())))

    @property
    def label_map(self) -> Dict[str, str]:
        return dict(self.labels)

    def metric(self, part_schema: PartitionSchema) -> str:
        return self.label_map.get(part_schema.metric_column, "")

    def shard_key_hash(self, part_schema: PartitionSchema) -> int:
        lm = self.label_map
        values = [lm.get(c, "") for c in part_schema.non_metric_shard_key_columns]
        return shard_key_hash(values, lm.get(part_schema.metric_column, ""))

    def part_hash(self) -> int:
        return partition_key_hash(self.label_map)

    # Canonical binary form — persistence + index bootstrap interchange.
    # Layout: u16 schema_id, u16 numPairs, then per pair (u16 klen, bytes,
    # u16 vlen, bytes), UTF-8.
    def to_bytes(self) -> bytes:
        out = bytearray(struct.pack("<HH", self.schema_id, len(self.labels)))
        for k, v in self.labels:
            kb, vb = k.encode(), v.encode()
            out.extend(struct.pack("<H", len(kb)))
            out.extend(kb)
            out.extend(struct.pack("<H", len(vb)))
            out.extend(vb)
        return bytes(out)

    @staticmethod
    def from_bytes(buf: bytes) -> "PartKey":
        schema_id, npairs = struct.unpack_from("<HH", buf, 0)
        off = 4
        pairs = []
        for _ in range(npairs):
            (klen,) = struct.unpack_from("<H", buf, off)
            off += 2
            k = buf[off : off + klen].decode()
            off += klen
            (vlen,) = struct.unpack_from("<H", buf, off)
            off += 2
            v = buf[off : off + vlen].decode()
            off += vlen
            pairs.append((k, v))
        return PartKey(schema_id, tuple(pairs))


# ---------------------------------------------------------------------------
# Ingest record containers (columnar batches)
# ---------------------------------------------------------------------------

@dataclass
class IngestRecord:
    """One sample: partkey + timestamp + data column values
    (BinaryRecordRowReader equivalent, RecordSchema.scala:625)."""
    part_key: PartKey
    timestamp: int
    values: Tuple  # data column values in schema order (floats / hist arrays)


@dataclass
class RecordContainer:
    """A batch of ingest records for one schema — the unit handed to the
    ingestion pipeline (RecordContainer.scala; Kafka payload unit).

    Columnar: one numpy array per column, plus per-row partkey references;
    this is the "zero-serialization" analogue — arrays flow straight into the
    write-buffer appenders. Same-partition runs are tracked AT ADD TIME
    (builders emit per-series bursts), so the shard ingest loop walks
    O(series) runs instead of O(rows) with per-row PartKey comparisons."""
    schema: DataSchema
    part_keys: List[PartKey] = field(default_factory=list)
    timestamps: List[int] = field(default_factory=list)
    columns: List[List] = field(default_factory=list)  # per data column
    _runs: List = field(default_factory=list)          # [start, end, pk]

    def __post_init__(self):
        if not self.columns:
            self.columns = [[] for _ in self.schema.data_columns]

    def add(self, part_key: PartKey, timestamp: int, *values) -> None:
        if len(values) != len(self.schema.data_columns):
            raise ValueError(
                f"expected {len(self.schema.data_columns)} values, "
                f"got {len(values)}")
        i = len(self.timestamps)
        if self._runs and (self._runs[-1][2] is part_key
                           or self._runs[-1][2] == part_key):
            self._runs[-1][1] = i + 1
        else:
            self._runs.append([i, i + 1, part_key])
        self.part_keys.append(part_key)
        self.timestamps.append(int(timestamp))
        for col, v in zip(self.columns, values):
            col.append(v)

    def arrays(self):
        """Columnar numpy view of the container: (ts int64 array,
        per-column float64 arrays — histogram columns stay per-row
        lists). Cached by row count; run slices of these are zero-copy
        views, so the per-run ingest cost is O(1)."""
        n = len(self.timestamps)
        cached = getattr(self, "_arrays_cache", None)
        if cached is not None and cached[0] == n:
            return cached[1], cached[2]
        ts = np.asarray(self.timestamps, dtype=np.int64)
        cols = []
        from filodb_tpu.core.schemas import ColumnType  # cycle-free late
        for col, vals in zip(self.schema.data_columns, self.columns):
            if col.col_type in (ColumnType.HISTOGRAM, ColumnType.STRING):
                cols.append(vals)
            else:
                cols.append(np.asarray(vals, dtype=np.float64))
        self._arrays_cache = (n, ts, cols)
        return ts, cols

    def runs(self):
        """Consecutive same-partition [start, end, pk] runs. Recomputed
        lazily for containers assembled from raw lists (wire decode)."""
        if not self._runs and self.timestamps:
            runs = []
            pks = self.part_keys
            i, total = 0, len(pks)
            while i < total:
                j = i + 1
                pk = pks[i]
                while j < total and (pks[j] is pk or pks[j] == pk):
                    j += 1
                runs.append([i, j, pk])
                i = j
            self._runs = runs
        return self._runs

    def __len__(self) -> int:
        return len(self.timestamps)

    def rows(self):
        for i in range(len(self.timestamps)):
            yield IngestRecord(
                self.part_keys[i], self.timestamps[i],
                tuple(col[i] for col in self.columns))


@single_writer("a RecordBuilder is constructed, filled, and drained by "
               "ONE producer thread (a gateway handler, a selfmon "
               "tick); instances are never shared across threads")
class RecordBuilder:
    """Builds RecordContainers from label maps + samples, computing shard
    hashes (RecordBuilder.scala:34 public API surface).

    PartKeys are interned per builder: the same series yields the SAME
    object, so downstream run detection and partition-map lookups hit the
    identity fast path instead of re-hashing label tuples per row."""

    def __init__(self, schemas: Schemas):
        self.schemas = schemas
        self._containers: Dict[str, RecordContainer] = {}
        self._pk_intern: Dict[Tuple[int, Tuple], PartKey] = {}

    def add_sample(self, schema_name: str, labels: Mapping[str, str],
                   timestamp: int, *values) -> PartKey:
        schema = self.schemas.by_name(schema_name)
        key = (schema.schema_id, tuple(sorted(labels.items())))
        pk = self._pk_intern.get(key)
        if pk is None:
            pk = PartKey(key[0], key[1])
            self._pk_intern[key] = pk
        cont = self._containers.setdefault(schema_name, RecordContainer(schema))
        cont.add(pk, timestamp, *values)
        return pk

    def containers(self) -> List[RecordContainer]:
        out = [c for c in self._containers.values() if len(c)]
        self._containers = {}
        return out
