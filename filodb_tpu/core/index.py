"""Tag index: label filters -> partition ids.

Replaces the reference's per-shard Apache Lucene index
(core/src/main/scala/filodb.core/memstore/PartKeyLuceneIndex.scala:49,128;
abstract API PartKeyIndex.scala).  Same query surface — Equals / In / Regex /
NotEquals / NotRegex / Prefix filters, label-values facets, start/end-time
range lookups — implemented as in-memory inverted maps per shard.  High-
cardinality scaling (roaring bitmaps / C++ index) is a later optimization;
the API is the stable boundary.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Set

import numpy as np

from filodb_tpu.lint.locks import single_writer

# sentinel for "still ingesting" (PartKeyLuceneIndex endTime semantics)
END_TIME_INGESTING = (1 << 62)


@dataclass(frozen=True)
class ColumnFilter:
    """One label filter (core/query/Filter in the reference)."""
    label: str
    op: str          # eq | neq | in | nin | re | nre | prefix
    value: object    # str for eq/re/prefix, tuple for in

    # constructors
    @staticmethod
    def eq(label: str, value: str) -> "ColumnFilter":
        return ColumnFilter(label, "eq", value)

    @staticmethod
    def neq(label: str, value: str) -> "ColumnFilter":
        return ColumnFilter(label, "neq", value)

    @staticmethod
    def in_(label: str, values: Sequence[str]) -> "ColumnFilter":
        return ColumnFilter(label, "in", tuple(values))

    @staticmethod
    def regex(label: str, pattern: str) -> "ColumnFilter":
        return ColumnFilter(label, "re", pattern)

    @staticmethod
    def not_regex(label: str, pattern: str) -> "ColumnFilter":
        return ColumnFilter(label, "nre", pattern)

    @staticmethod
    def prefix(label: str, pfx: str) -> "ColumnFilter":
        return ColumnFilter(label, "prefix", pfx)


def _full_match(pattern: str, value: str) -> bool:
    return re.fullmatch(pattern, value) is not None


@single_writer("one index per shard, mutated only by the shard's "
               "owning thread (ingest driver / pre-driver bootstrap)")
class TagIndex:
    """Inverted index for one shard: label -> value -> set(part_id), plus
    per-part start/end times (the ``__startTime__``/``__endTime__`` doc values
    of PartKeyLuceneIndex.scala)."""

    def __init__(self):
        self._postings: Dict[str, Dict[str, Set[int]]] = {}
        self._labels: Dict[int, Mapping[str, str]] = {}
        self._start: Dict[int, int] = {}
        self._end: Dict[int, int] = {}
        self._all: Set[int] = set()

    # -- write path -------------------------------------------------------
    def add_part_key(self, part_id: int, labels: Mapping[str, str],
                     start_time: int,
                     end_time: int = END_TIME_INGESTING) -> None:
        self._labels[part_id] = labels
        self._start[part_id] = start_time
        self._end[part_id] = end_time
        self._all.add(part_id)
        for k, v in labels.items():
            self._postings.setdefault(k, {}).setdefault(v, set()).add(part_id)

    def update_end_time(self, part_id: int, end_time: int) -> None:
        if part_id in self._end:
            self._end[part_id] = end_time

    def start_time(self, part_id: int) -> Optional[int]:
        return self._start.get(part_id)

    def end_time(self, part_id: int) -> Optional[int]:
        return self._end.get(part_id)

    def remove_part_keys(self, part_ids: Iterable[int]) -> None:
        for pid in part_ids:
            labels = self._labels.pop(pid, None)
            if labels is None:
                continue
            self._all.discard(pid)
            self._start.pop(pid, None)
            self._end.pop(pid, None)
            for k, v in labels.items():
                vals = self._postings.get(k)
                if vals and v in vals:
                    vals[v].discard(pid)
                    if not vals[v]:
                        del vals[v]

    # -- read path --------------------------------------------------------
    def posting_upper_bound(self, filters: Sequence[ColumnFilter]
                            ) -> Optional[int]:
        """Cheap (O(#filters), no set intersection) upper bound on the
        series an equality-filter set can match: the smallest posting
        list among the eq filters. None when no eq filter names an
        indexed label — the caller falls back to its cardinality-tree
        estimate. This is the QoS cost estimator's tag-index input; it
        must stay cheap enough to run BEFORE admission."""
        best: Optional[int] = None
        for f in filters:
            if getattr(f, "op", "") != "eq":
                continue
            vals = self._postings.get(f.label)
            if vals is None:
                continue
            n = len(vals.get(f.value, ()))
            if best is None or n < best:
                best = n
        return best

    def _ids_for_filter(self, f: ColumnFilter) -> Set[int]:
        vals = self._postings.get(f.label, {})
        if f.op == "eq":
            return set(vals.get(f.value, ()))
        if f.op == "in":
            out: Set[int] = set()
            for v in f.value:
                out |= vals.get(v, set())
            return out
        if f.op == "re":
            # Prometheus fast-path: a plain-string regex is an equals match
            out = set()
            for v, ids in vals.items():
                if _full_match(f.value, v):
                    out |= ids
            return out
        if f.op == "prefix":
            out = set()
            for v, ids in vals.items():
                if v.startswith(f.value):
                    out |= ids
            return out
        if f.op == "neq":
            matched: Set[int] = set(vals.get(f.value, ()))
            return self._all - matched
        if f.op == "nre":
            matched = set()
            for v, ids in vals.items():
                if _full_match(f.value, v):
                    matched |= ids
            return self._all - matched
        raise ValueError(f"unknown filter op {f.op}")

    def part_ids_from_filters(self, filters: Sequence[ColumnFilter],
                              start_time: int, end_time: int) -> List[int]:
        """Series matching all filters whose [start,end] lifetime overlaps the
        query range (partIdsFromFilters, PartKeyLuceneIndex.scala:993ff)."""
        if filters:
            ids: Optional[Set[int]] = None
            for f in filters:
                got = self._ids_for_filter(f)
                ids = got if ids is None else (ids & got)
                if not ids:
                    return []
        else:
            ids = set(self._all)
        out = [
            pid for pid in ids
            if self._start[pid] <= end_time and self._end[pid] >= start_time
        ]
        out.sort()
        return out

    def label_values(self, label: str,
                     filters: Sequence[ColumnFilter] = (),
                     start_time: int = 0,
                     end_time: int = END_TIME_INGESTING) -> List[str]:
        """Distinct values of a label (labelValuesEfficient /
        LabelValues facet path)."""
        if not filters:
            return sorted(self._postings.get(label, {}).keys())
        pids = set(self.part_ids_from_filters(filters, start_time, end_time))
        out = {
            v for v, ids in self._postings.get(label, {}).items()
            if ids & pids
        }
        return sorted(out)

    def label_names(self, filters: Sequence[ColumnFilter] = (),
                    start_time: int = 0,
                    end_time: int = END_TIME_INGESTING) -> List[str]:
        if not filters:
            return sorted(self._postings.keys())
        pids = self.part_ids_from_filters(filters, start_time, end_time)
        names: Set[str] = set()
        for pid in pids:
            names |= set(self._labels[pid].keys())
        return sorted(names)

    def labels_for(self, part_id: int) -> Mapping[str, str]:
        return self._labels[part_id]

    @property
    def num_parts(self) -> int:
        return len(self._all)
