"""Core layer: schemas, record format, the time-series memstore, store APIs.

TPU-native analogue of the reference's ``core/`` module
(core/src/main/scala/filodb.core/*).
"""
