"""Schema registry: column types, data/partition schemas, built-in schemas.

Re-design of the reference's metadata layer
(core/src/main/scala/filodb.core/metadata/Schemas.scala:66,126,370,
metadata/Column.scala, metadata/Dataset.scala:73,143).  Built-in schema
definitions mirror core/src/main/resources/filodb-defaults.conf:121-275.

Each schema gets a 16-bit ``schema_id`` derived from a hash of its column
definitions (Schemas.scala embeds this in partkeys); ids are stable across
processes because the hash input is the canonical schema string.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Optional, Sequence, Tuple

from filodb_tpu.utils.xxhash import xxhash32


class ColumnType(Enum):
    TIMESTAMP = "ts"
    LONG = "long"
    DOUBLE = "double"
    INT = "int"
    STRING = "string"
    MAP = "map"
    BINARY = "binary"
    HISTOGRAM = "hist"


@dataclass(frozen=True)
class Column:
    name: str
    col_type: ColumnType
    # column params (Column.scala / conf column defs like detectDrops=true)
    detect_drops: bool = False   # counter semantics: detect resets
    counter: bool = False        # histogram counter flag
    delta: bool = False          # delta temporality (otel delta)

    @property
    def is_counter_like(self) -> bool:
        return self.detect_drops or self.counter

    def canonical(self) -> str:
        return (f"{self.name}:{self.col_type.value}:"
                f"{int(self.detect_drops)}{int(self.counter)}{int(self.delta)}")


@dataclass(frozen=True)
class DataSchema:
    """Columns of one time series sample (DataSchema, Schemas.scala:66)."""
    name: str
    columns: Tuple[Column, ...]
    value_column: str
    downsamplers: Tuple[str, ...] = ()
    downsample_period_marker: str = "time(0)"
    downsample_schema: Optional[str] = None

    @property
    def schema_id(self) -> int:
        """16-bit schema hash embedded in partkeys (Schemas.scala:370)."""
        canon = self.name + "|" + "|".join(c.canonical() for c in self.columns)
        return xxhash32(canon.encode()) & 0xFFFF

    @property
    def timestamp_column(self) -> Column:
        return self.columns[0]

    @property
    def data_columns(self) -> Tuple[Column, ...]:
        return self.columns[1:]

    def column(self, name: str) -> Column:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)

    def value_column_index(self) -> int:
        for i, c in enumerate(self.columns):
            if c.name == self.value_column:
                return i
        raise KeyError(self.value_column)


@dataclass(frozen=True)
class PartitionSchema:
    """Partition-key schema: which labels form the shard key
    (PartitionSchema, Schemas.scala:126; defaults filodb-defaults.conf:95-100).
    """
    shard_key_columns: Tuple[str, ...] = ("_ws_", "_ns_", "_metric_")
    metric_column: str = "_metric_"

    @property
    def non_metric_shard_key_columns(self) -> Tuple[str, ...]:
        return tuple(c for c in self.shard_key_columns if c != self.metric_column)


def _col(spec: str) -> Column:
    """Parse "name:type[:opts]" column spec (conf format,
    filodb-defaults.conf:125)."""
    parts = spec.split(":")
    name, ctype = parts[0], ColumnType(parts[1])
    opts = {}
    if len(parts) > 2:
        raw = parts[2].strip("{}")
        for kv in raw.split(","):
            if kv:
                k, v = kv.split("=")
                opts[k.strip()] = v.strip() == "true"
    return Column(
        name, ctype,
        detect_drops=opts.get("detectDrops", False),
        counter=opts.get("counter", False),
        delta=opts.get("delta", False),
    )


def _schema(name, col_specs, value_column, downsamplers=(), marker="time(0)",
            ds_schema=None) -> DataSchema:
    return DataSchema(
        name=name,
        columns=tuple(_col(s) for s in col_specs),
        value_column=value_column,
        downsamplers=tuple(downsamplers),
        downsample_period_marker=marker,
        downsample_schema=ds_schema,
    )


# Built-in schemas — filodb-defaults.conf:121-275 verbatim semantics.
BUILTIN_SCHEMAS: Dict[str, DataSchema] = {s.name: s for s in [
    _schema("gauge", ["timestamp:ts", "value:double:detectDrops=false"],
            "value",
            ["tTime(0)", "dMin(1)", "dMax(1)", "dSum(1)", "dCount(1)", "dAvg(1)"],
            "time(0)", "ds-gauge"),
    _schema("untyped", ["timestamp:ts", "number:double"], "number"),
    _schema("prom-counter", ["timestamp:ts", "count:double:detectDrops=true"],
            "count", ["tTime(0)", "dLast(1)"], "counter(1)", "prom-counter"),
    _schema("delta-counter",
            ["timestamp:ts", "count:double:{detectDrops=false,delta=true}"],
            "count", ["tTime(0)", "dSum(1)"], "time(0)", "delta-counter"),
    _schema("prom-histogram",
            ["timestamp:ts", "sum:double:detectDrops=true",
             "count:double:detectDrops=true", "h:hist:counter=true"],
            "h", ["tTime(0)", "dLast(1)", "dLast(2)", "hLast(3)"],
            "counter(2)", "prom-histogram"),
    _schema("delta-histogram",
            ["timestamp:ts", "sum:double:{detectDrops=false,delta=true}",
             "count:double:{detectDrops=false,delta=true}",
             "h:hist:{counter=false,delta=true}"],
            "h", ["tTime(0)", "dSum(1)", "dSum(2)", "hSum(3)"],
            "time(0)", "delta-histogram"),
    _schema("otel-cumulative-histogram",
            ["timestamp:ts", "sum:double:detectDrops=true",
             "count:double:detectDrops=true", "h:hist:counter=true",
             "min:double:detectDrops=true", "max:double:detectDrops=true"],
            "h",
            ["tTime(0)", "dLast(1)", "dLast(2)", "hLast(3)", "dMin(4)", "dMax(5)"],
            "counter(2)", "otel-cumulative-histogram"),
    _schema("otel-delta-histogram",
            ["timestamp:ts", "sum:double:{detectDrops=false,delta=true}",
             "count:double:{detectDrops=false,delta=true}",
             "h:hist:{counter=false,delta=true}",
             "min:double:{detectDrops=false,delta=true}",
             "max:double:{detectDrops=false,delta=true}"],
            "h",
            ["tTime(0)", "dSum(1)", "dSum(2)", "hSum(3)", "dMin(4)", "dMax(5)"],
            "time(0)", "otel-delta-histogram"),
    _schema("preagg-gauge",
            ["timestamp:ts", "count:double:detectDrops=false",
             "min:double:detectDrops=false", "sum:double:detectDrops=false",
             "max:double:detectDrops=false"],
            "sum",
            ["tTime(0)", "dSum(1)", "dMin(2)", "dSum(3)", "dMax(4)"],
            "time(0)", "preagg-gauge"),
    _schema("preagg-delta-counter",
            ["timestamp:ts", "count:double:{detectDrops=false,delta=true}",
             "min:double:detectDrops=false",
             "sum:double:{detectDrops=false,delta=true}",
             "max:double:detectDrops=false"],
            "sum",
            ["tTime(0)", "dSum(1)", "dMin(2)", "dSum(3)", "dMax(4)"],
            "time(0)", "preagg-delta-counter"),
    _schema("preagg-delta-histogram",
            ["timestamp:ts", "sum:double:{detectDrops=false,delta=true}",
             "count:double:{detectDrops=false,delta=true}",
             "tscount:double:{detectDrops=false,delta=true}",
             "h:hist:{counter=false,delta=true}"],
            "h",
            ["tTime(0)", "dSum(1)", "dSum(2)", "dSum(3)", "hSum(4)"],
            "time(0)", "preagg-delta-histogram"),
    _schema("preagg-otel-delta-histogram",
            ["timestamp:ts", "sum:double:{detectDrops=false,delta=true}",
             "count:double:{detectDrops=false,delta=true}",
             "tscount:double:{detectDrops=false,delta=true}",
             "h:hist:{counter=false,delta=true}",
             "min:double:{detectDrops=false,delta=true}",
             "max:double:{detectDrops=false,delta=true}"],
            "h",
            ["tTime(0)", "dSum(1)", "dSum(2)", "dSum(3)", "hSum(4)", "dMin(5)",
             "dMax(6)"],
            "time(0)", "preagg-otel-delta-histogram"),
    _schema("ds-gauge",
            ["timestamp:ts", "min:double", "max:double", "sum:double",
             "count:double", "avg:double"],
            "avg"),
]}


@dataclass
class Schemas:
    """Registry of schemas by name and by 16-bit id (Schemas.scala:370)."""
    part: PartitionSchema = field(default_factory=PartitionSchema)
    schemas: Dict[str, DataSchema] = field(
        default_factory=lambda: dict(BUILTIN_SCHEMAS))

    def __post_init__(self):
        self._by_id = {s.schema_id: s for s in self.schemas.values()}
        if len(self._by_id) != len(self.schemas):
            raise ValueError("schema id (hash) conflict — rename a schema")

    def by_name(self, name: str) -> DataSchema:
        return self.schemas[name]

    def by_id(self, schema_id: int) -> DataSchema:
        return self._by_id[schema_id]

    def __contains__(self, name: str) -> bool:
        return name in self.schemas


DEFAULT_SCHEMAS = Schemas()


@dataclass(frozen=True)
class DatasetRef:
    """Dataset identifier (core/DatasetRef)."""
    dataset: str
    database: Optional[str] = None

    def __str__(self) -> str:
        return f"{self.database}.{self.dataset}" if self.database else self.dataset


@dataclass(frozen=True)
class DatasetOptions:
    """Per-dataset options (metadata/Dataset.scala:143)."""
    shard_key_columns: Tuple[str, ...] = ("_ws_", "_ns_", "_metric_")
    metric_column: str = "_metric_"
    max_chunks_size: int = 400
    flush_interval_ms: int = 3_600_000
