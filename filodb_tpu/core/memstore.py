"""In-memory time-series store: shards, partitions, write buffers, chunks.

TPU-native re-design of the reference's memstore
(core/src/main/scala/filodb.core/memstore/TimeSeriesShard.scala:258,
TimeSeriesPartition.scala:64, TimeSeriesMemStore.scala:26,
WriteBufferPool.scala:34, store/ChunkSetInfo.scala:32).

Key departures from the JVM design, chosen for the TPU execution model:

- No off-heap Unsafe pointers: write buffers are plain Python/numpy appenders;
  encoded chunks are immutable ``bytes`` (the interchange format from
  filodb_tpu.memory.vectors).  The reference's ChunkMap spin-locks and
  EvictionLock exist to let queries iterate shared mutable off-heap memory
  safely; here queries only ever see **immutable published chunk lists** plus
  a snapshot of the in-progress buffer tail, so the whole lock apparatus is
  replaced by snapshot semantics (SURVEY.md §7 "immutable-snapshot design").

- Flush groups (TimeSeriesShard.scala:1253 createFlushTasks): partitions hash
  into ``num_groups`` subgroups; flushing a group encodes that group's write
  buffers into chunks and records a checkpoint offset, exactly like the
  reference's interleaved flush/ingest protocol, minus the actor machinery.

- Queries hitting recent data merge the encoded chunks with the current
  write-buffer snapshot (the reference reads write buffers through the same
  BinaryVector API; here the tail is just small host arrays appended to the
  decoded chunk arrays).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.index import (END_TIME_INGESTING, ColumnFilter, TagIndex)
from filodb_tpu.core.record import PartKey, RecordContainer
from filodb_tpu.lint.caches import cache_registry, event_source, publishes
from filodb_tpu.lint.locks import guarded_by, single_writer
from filodb_tpu.core.schemas import (ColumnType, DataSchema, DatasetRef,
                                     Schemas)
from filodb_tpu.memory import histogram as bh
from filodb_tpu.memory import vectors as bv

DEFAULT_MAX_CHUNK_ROWS = 400  # store config max-chunks-size (IngestionConfig)


def chunk_id(start_ts: int, seq: int) -> int:
    """chunkID = startTime << 12 | seq (core/store/package.scala chunkID)."""
    return (start_ts << 12) | (seq & 0xFFF)


@dataclass
class ChunkSetInfo:
    """Per-chunk metadata (store/ChunkSetInfo.scala:32 — 32-byte metadata:
    id, numRows, startTime, endTime + per-column vector ptrs)."""
    id: int
    num_rows: int
    start_ts: int
    end_ts: int
    vectors: Tuple[bytes, ...]  # column 0 = timestamps

    def decode_column(self, i: int):
        return bv.decode(self.vectors[i]) if i == 0 or not _is_hist(
            self.vectors[i]) else bh.decode_histograms(self.vectors[i])


def _is_hist(buf: bytes) -> bool:
    return buf[:1] in (bytes([bh.K_HIST_2D]), bytes([bh.K_HIST_SECT]))


# the caches are shared by concurrent HTTP query threads; the chunk list
# itself is append-only and read via snapshots, so only the caches (and
# the publish step in switch_buffers) ride the lock.
# Cache inventory: both caches validate against the chunk-set length at
# read time (decoded prefix extends to len(chunks), a merge entry is
# keyed by (n_chunks, tail_len)) — graftlint requires the read hooks to
# keep consulting the chunk-set event source.
@cache_registry("partition-decode", keyed=("column",),
                validated_by={"chunk-set": ("read_full",
                                            "hist_drop_rows")})
@cache_registry("partition-merge", keyed=("column",),
                validated_by={"chunk-set": ("read_full",)})
@guarded_by("_cache_lock", "_decode_cache", "_merge_cache")
class TimeSeriesPartition:
    """One time series in one shard (memstore/TimeSeriesPartition.scala:64).

    Write path: ``ingest`` appends to the current write buffer; when the
    buffer reaches ``max_chunk_rows`` (or on flush-group flush) the buffer is
    encoded to an immutable chunk (``encodeOneChunkset`` :248 equivalent) and
    published to ``chunks``."""

    __slots__ = ("part_id", "part_key", "schema", "chunks", "_ts_buf",
                 "_col_bufs", "_buf_rows", "_hist_scheme",
                 "max_chunk_rows", "_chunk_seq",
                 "ingested", "ooo_dropped", "_decode_cache", "_merge_cache",
                 "persisted_chunks", "odp_pending", "_cache_lock",
                 "card_active", "on_encode")

    def __init__(self, part_id: int, part_key: PartKey, schema: DataSchema,
                 max_chunk_rows: int = DEFAULT_MAX_CHUNK_ROWS):
        self.part_id = part_id
        self.part_key = part_key
        self.schema = schema
        self.chunks: List[ChunkSetInfo] = []
        # write buffers are SEGMENT lists: each ingest run appends one
        # numpy array slice (no per-row Python element churn); histogram
        # columns keep per-row [nb] arrays. Row count tracked separately.
        self._ts_buf: List[np.ndarray] = []
        self._col_bufs: List[List] = [[] for _ in schema.data_columns]
        self._buf_rows = 0
        self._hist_scheme = None
        self.max_chunk_rows = max_chunk_rows
        self._chunk_seq = 0
        self.ingested = 0
        self.ooo_dropped = 0
        # col_index -> [n_chunks_decoded, ts_parts, val_parts, concat pair]
        self._decode_cache: Dict[int, list] = {}
        # col_index -> (n_chunks, tail_len, ts, vals): last chunks+tail
        # merge, reused until either side changes (per-scrape, not per-query)
        self._merge_cache: Dict[int, Tuple] = {}
        self.persisted_chunks = 0   # prefix of `chunks` already in the store
        self.odp_pending = False    # True: chunks live in the ColumnStore
        self.card_active = True     # counted as active in the tracker
        self.on_encode = None       # chunk-encoded hook (flush downsample)
        # guards _decode_cache/_merge_cache population: concurrent HTTP
        # query threads share these caches (the chunk list itself is only
        # appended to, and readers work off a snapshot length)
        self._cache_lock = threading.Lock()

    # -- write path -------------------------------------------------------
    def ingest(self, timestamp: int, values: Sequence) -> bool:
        """Append one row.  Out-of-order / duplicate timestamps within the
        partition are dropped (TimeSeriesPartition.scala ingest OOO rules).
        Returns True if ingested."""
        last = self.last_timestamp
        if last is not None and timestamp <= last:
            self.ooo_dropped += 1
            return False
        self._ts_buf.append(np.asarray([int(timestamp)], dtype=np.int64))
        for buf, col, v in zip(self._col_bufs, self.schema.data_columns, values):
            if col.col_type == ColumnType.HISTOGRAM:
                scheme, counts = v
                if self._hist_scheme is None:
                    self._hist_scheme = scheme
                buf.append(np.asarray(counts, dtype=np.int64))
            elif col.col_type == ColumnType.STRING:
                buf.append("" if v is None else str(v))
            else:
                buf.append(np.asarray([v], dtype=np.float64))
        self._buf_rows += 1
        self.ingested += 1
        if self._buf_rows >= self.max_chunk_rows:
            self.switch_buffers()
        return True

    def ingest_batch(self, timestamps: Sequence[int],
                     col_values: Sequence[Sequence]) -> int:
        """Append a run of rows for this partition in one shot.

        Fast path: a strictly-increasing run starting after the current
        last timestamp lands as whole numpy SEGMENTS in the write
        buffers — O(1) Python work per run, no per-row element churn
        (the batched analogue of the reference's per-row appender adds).
        Anything else falls back to the per-row path so OOO-drop
        semantics stay identical. Returns rows ingested."""
        n_in = len(timestamps)
        if n_in == 0:
            return 0
        if n_in == 1:
            return 1 if self.ingest(timestamps[0], [c[0] for c
                                                    in col_values]) else 0
        ts = np.asarray(timestamps, dtype=np.int64)
        last = self.last_timestamp
        sorted_run = bool(np.all(np.diff(ts) > 0)) and \
            (last is None or int(ts[0]) > last)
        if not sorted_run:
            n = 0
            for i in range(n_in):
                if self.ingest(timestamps[i],
                               [c[i] for c in col_values]):
                    n += 1
            return n
        hist_cols = [i for i, c in enumerate(self.schema.data_columns)
                     if c.col_type == ColumnType.HISTOGRAM]
        str_cols = [i for i, c in enumerate(self.schema.data_columns)
                    if c.col_type == ColumnType.STRING]
        col_arrays = [None if ci in hist_cols or ci in str_cols
                      else np.asarray(col_values[ci], dtype=np.float64)
                      for ci in range(len(self._col_bufs))]
        pos = 0
        while pos < n_in:
            room = self.max_chunk_rows - self._buf_rows
            take = min(room, n_in - pos)
            # copy: a view would pin the container's WHOLE column array
            # in memory for as long as any segment sits in the buffer
            self._ts_buf.append(np.array(ts[pos:pos + take]))
            for ci, buf in enumerate(self._col_bufs):
                if ci in hist_cols:
                    vals = col_values[ci]
                    for k in range(pos, pos + take):
                        scheme, counts = vals[k]
                        if self._hist_scheme is None:
                            self._hist_scheme = scheme
                        buf.append(np.asarray(counts, dtype=np.int64))
                elif ci in str_cols:
                    vals = col_values[ci]
                    for k in range(pos, pos + take):
                        v = vals[k]
                        buf.append("" if v is None else str(v))
                else:
                    buf.append(np.array(col_arrays[ci][pos:pos + take]))
            self._buf_rows += take
            pos += take
            if self._buf_rows >= self.max_chunk_rows:
                self.switch_buffers()
        self.ingested += n_in
        return n_in

    @property
    def last_timestamp(self) -> Optional[int]:
        if self._buf_rows:
            return int(self._ts_buf[-1][-1])
        if self.chunks:
            return self.chunks[-1].end_ts
        return None

    @property
    def earliest_timestamp(self) -> Optional[int]:
        if self.chunks:
            return self.chunks[0].start_ts
        return int(self._ts_buf[0][0]) if self._buf_rows else None

    @publishes("chunk-set")
    def switch_buffers(self) -> Optional[ChunkSetInfo]:
        """Encode the current write buffer into an immutable chunk
        (TimeSeriesPartition.scala:229 switchBuffers / :248 encodeOneChunkset).
        """
        if not self._buf_rows:
            return None
        ts = np.concatenate(self._ts_buf)
        vecs: List[bytes] = [bv.encode_longs(ts)]
        for buf, col in zip(self._col_bufs, self.schema.data_columns):
            if col.col_type == ColumnType.HISTOGRAM:
                rows = np.stack(buf) if buf else np.zeros((0, 0), np.int64)
                vecs.append(bh.encode_histograms(
                    self._hist_scheme, rows, counter=col.counter))
            elif col.col_type == ColumnType.STRING:
                vecs.append(bv.encode_strings(buf))
            else:
                vecs.append(bv.encode_doubles(
                    np.concatenate(buf) if buf
                    else np.zeros(0, dtype=np.float64),
                    counter=col.detect_drops))
        info = ChunkSetInfo(
            id=chunk_id(int(ts[0]), self._chunk_seq),
            num_rows=ts.size,
            start_ts=int(ts[0]),
            end_ts=int(ts[-1]),
            vectors=tuple(vecs),
        )
        self._chunk_seq += 1
        # publish atomically w.r.t. readers: a reader must never see the new
        # chunk AND the old buffer tail (double count) or neither (drop)
        with self._cache_lock:
            self.chunks.append(info)
            self._ts_buf = []
            self._col_bufs = [[] for _ in self.schema.data_columns]
            self._buf_rows = 0
        if self.on_encode is not None:
            # flush-time downsample emission rides every encode, including
            # buffer-full encodes during ingest (ShardDownsampler.scala:40)
            self.on_encode(self.part_key, self.schema, info)
        return info

    # -- read path --------------------------------------------------------
    def buffer_snapshot(self):
        """Snapshot of the un-encoded tail: (ts array, per-column tails —
        float64 arrays for plain columns, per-row lists for histograms).

        Ingest appends the timestamp segment first, then each column
        segment, so the longest consistent prefix across all buffers is a
        valid row set even when a writer thread is mid-append."""
        ts_segs = list(self._ts_buf)
        ts = (np.concatenate(ts_segs) if ts_segs
              else np.zeros(0, dtype=np.int64))
        snaps, counts = [], []
        for buf, col in zip(self._col_bufs, self.schema.data_columns):
            b = list(buf)
            if col.col_type in (ColumnType.HISTOGRAM, ColumnType.STRING):
                snaps.append(b)
                counts.append(len(b))
            else:
                arr = (np.concatenate(b) if b
                       else np.zeros(0, dtype=np.float64))
                snaps.append(arr)
                counts.append(arr.size)
        n = min([ts.size] + counts) if counts else ts.size
        return ts[:n], [c[:n] for c in snaps]

    def _decoded_chunk_arrays(self, col_index: int
                              ) -> Tuple[np.ndarray, np.ndarray]:
        """Decoded concatenation of all PUBLISHED chunks for one column,
        cached incrementally: only chunks appended since the last call are
        decoded. This is the host mirror of the device tile store — decode
        cost is paid once per chunk, not once per query."""
        col = self.schema.columns[col_index]
        with self._cache_lock:
            return self._decoded_chunk_arrays_locked(col_index, col)

    @event_source("chunk-set")
    def _decoded_chunk_arrays_locked(self, col_index: int, col):
        """Body of _decoded_chunk_arrays; caller holds ``_cache_lock``.

        Entry layout: [next_chunk, ts_parts, val_parts, concat,
        drop_rows, rows_so_far, prev_last_row]. The last three exist for
        histogram columns only: drop_rows accumulates GLOBAL reset row
        indices from each chunk's sectioned drop table (legacy unsectioned
        chunks are rescanned once at decode), plus cross-chunk boundary
        resets — so query-time counter correction never rescans buckets."""
        entry = self._decode_cache.get(col_index)
        if entry is None:
            entry = [0, [], [], None, [], 0, None]
            self._decode_cache[col_index] = entry
        n = len(self.chunks)
        if entry[0] < n:
            for c in self.chunks[entry[0]:n]:
                entry[1].append(bv.decode_longs(c.vectors[0]))
                if col.col_type == ColumnType.HISTOGRAM:
                    _, _, vals, drops = bh.decode_histograms_full(
                        c.vectors[col_index])
                    if drops is None:           # legacy K_HIST_2D chunk
                        drops = bh.detect_drop_rows(vals)
                    off, prev = entry[5], entry[6]
                    if (prev is not None and vals.shape[0]
                            and (vals[0] < prev).any()):
                        entry[4].append(np.array([off], dtype=np.int64))
                    if drops.size:
                        entry[4].append(drops + off)
                    entry[5] = off + vals.shape[0]
                    if vals.shape[0]:
                        entry[6] = vals[-1]
                    entry[2].append(vals)
                elif col.col_type == ColumnType.STRING:
                    vals = bv.decode_strings(c.vectors[col_index])
                    entry[2].append(vals)
                else:
                    vals = bv.decode_doubles(c.vectors[col_index])
                    entry[2].append(vals)
            entry[0] = n
            entry[3] = None
        if entry[3] is None:
            if entry[1]:
                cat = (np.concatenate(entry[1]),
                       np.concatenate(entry[2], axis=0))
                # collapse parts into the concatenation (no 2x residency);
                # future chunks append after it
                entry[1] = [cat[0]]
                entry[2] = [cat[1]]
            else:
                col_empty = (np.zeros((0, 0))
                             if col.col_type == ColumnType.HISTOGRAM
                             else np.zeros(0, dtype=object)
                             if col.col_type == ColumnType.STRING
                             else np.zeros(0))
                cat = (np.zeros(0, dtype=np.int64), col_empty)
            # cache-backed arrays are shared with query results: freeze them
            for a in cat:
                a.setflags(write=False)
            entry[3] = cat
        return entry[0], entry[3]

    def read_full(self, col_index: int
                  ) -> Tuple[np.ndarray, np.ndarray, int]:
        """All samples of one data column: published chunks (cached decode)
        + current write-buffer tail. Returns (ts, vals, chunk_len) where
        chunk_len is the length of the chunk-backed (immutable) prefix —
        downstream device caches key on it (num_chunks pins its content)."""
        col = self.schema.columns[col_index]
        # one lock acquisition covers decode AND the tail snapshot: a
        # switch_buffers publishing the tail as a chunk between the two
        # would otherwise double-count (chunk seen + tail still seen) or
        # drop (neither seen) those rows
        with self._cache_lock:
            n_chunks, (cts, cvals) = \
                self._decoded_chunk_arrays_locked(col_index, col)
            buf_ts, buf_cols = self.buffer_snapshot()
            # merge-cache bookkeeping stays under the same acquisition:
            # a concurrent reader's pop must never race this thread's
            # get/set on the shared dict (graftlint lock-guarded-access)
            if not buf_ts.size:
                self._merge_cache.pop(col_index, None)
                cached = None
            else:
                cached = self._merge_cache.get(col_index)
        if not buf_ts.size:
            return cts, cvals, cts.size
        if cached is not None and cached[0] == n_chunks \
                and cached[1] == buf_ts.size:
            return cached[2], cached[3], cts.size
        if col.col_type == ColumnType.HISTOGRAM:
            rows = buf_cols[col_index - 1]
            tail = (np.stack(rows).astype(np.float64) if rows
                    else np.zeros((0, cvals.shape[1]
                                   if cvals.ndim == 2 else 0)))
            if cvals.ndim == 2 and tail.ndim == 2 \
                    and cvals.shape[1] != tail.shape[1] and cvals.size == 0:
                cvals = np.zeros((0, tail.shape[1]))
        elif col.col_type == ColumnType.STRING:
            tail = np.asarray(buf_cols[col_index - 1], dtype=object)
        else:
            tail = np.asarray(buf_cols[col_index - 1], dtype=np.float64)
        mts = np.concatenate([cts, buf_ts])
        mvals = np.concatenate([cvals, tail], axis=0)
        mts.setflags(write=False)
        mvals.setflags(write=False)
        with self._cache_lock:
            self._merge_cache[col_index] = (n_chunks, buf_ts.size,
                                            mts, mvals)
        return mts, mvals, cts.size

    def hist_drop_rows(self, col_index: int) -> np.ndarray:
        """Global reset row indices over this histogram column's full
        (chunks + buffer tail) row sequence, from the sectioned drop
        tables — readers hand these to hist_counter_correction instead of
        rescanning (SectDelta's read-side payoff)."""
        with self._cache_lock:
            _, _ = self._decoded_chunk_arrays_locked(
                col_index, self.schema.columns[col_index])
            entry = self._decode_cache[col_index]
            chunk_drops = (np.concatenate(entry[4]) if entry[4]
                           else np.zeros(0, dtype=np.int64))
            off, prev = entry[5], entry[6]
            buf_ts, buf_cols = self.buffer_snapshot()
        if not buf_ts.size:
            return chunk_drops
        rows = buf_cols[col_index - 1]
        tail = np.stack(rows).astype(np.float64) if rows else \
            np.zeros((0, 0))
        parts = [chunk_drops]
        if prev is not None and tail.shape[0] and tail.shape[1] \
                and (tail[0] < prev).any():
            parts.append(np.array([off], dtype=np.int64))
        tail_drops = bh.detect_drop_rows(tail)
        if tail_drops.size:
            parts.append(tail_drops + off)
        return np.concatenate(parts)

    def cache_bytes(self) -> int:
        """Bytes held by this partition's decode + merge caches (the
        ``filodb_decode_cache_bytes`` gauge input)."""
        with self._cache_lock:
            return self._cache_bytes_locked()

    def _cache_bytes_locked(self) -> int:
        n = 0
        for entry in self._decode_cache.values():
            for part in entry[1]:
                n += int(part.nbytes)
            for part in entry[2]:
                n += int(getattr(part, "nbytes", 0))
        for cached in self._merge_cache.values():
            n += int(cached[2].nbytes) + int(getattr(cached[3],
                                                     "nbytes", 0))
        return n

    def release_caches(self) -> int:
        """Drop the decoded-chunk and merge caches when every published
        chunk sits in the flushed/persisted prefix — those decodes are
        pure duplicates of immutable chunk bytes (re-decodable on the
        next read), so under memory pressure they are the first thing to
        give back. Partitions with unflushed chunks keep their caches
        (they are the hot, actively-queried head). Returns bytes freed."""
        with self._cache_lock:
            if self.persisted_chunks < len(self.chunks):
                return 0
            n = self._cache_bytes_locked()
            self._decode_cache.clear()
            self._merge_cache.clear()
            return n

    def read_range(self, start_ts: int, end_ts: int, col_index: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """All samples with start_ts <= t <= end_ts for one data column.
        Returns (timestamps int64, values f64 or [n, nb] f64 for histograms).

        Merges immutable chunks with the current write-buffer snapshot — the
        equivalent of the reference's RawDataRangeVector iteration over
        ChunkMap + appenders (TimeSeriesPartition readers)."""
        ts_all, val_all, _ = self.read_full(col_index)
        lo = int(np.searchsorted(ts_all, start_ts, side="left"))
        hi = int(np.searchsorted(ts_all, end_ts, side="right"))
        return ts_all[lo:hi], val_all[lo:hi]

    @property
    def num_chunks(self) -> int:
        return len(self.chunks)


@single_writer("per-shard counters: mutated only by the shard's owning "
               "thread (ingest driver, or bootstrap strictly before it)")
@dataclass
class ShardStats:
    """Kamon-equivalent gauges (TimeSeriesShardStats, TimeSeriesShard.scala:41).
    """
    rows_ingested: int = 0
    rows_skipped: int = 0
    out_of_order_dropped: int = 0
    num_series: int = 0
    chunks_encoded: int = 0
    encoded_bytes: int = 0
    flushes_done: int = 0
    partitions_evicted: int = 0
    chunks_persisted: int = 0
    partitions_paged_in: int = 0    # ODP page-ins (ChunkSourceStats)
    partitions_bootstrapped: int = 0
    quota_dropped_series: int = 0   # new series rejected by cardinality


@single_writer("shard state is mutated only by the shard's single "
               "writer (the per-shard ingest thread; adopt/crash "
               "bootstrap runs strictly before the driver starts — the "
               "membership protocol pins the handoff happens-before); "
               "query threads read immutable snapshots, ODP page-in "
               "rides _odp_lock")
class TimeSeriesShard:
    """One shard: partKey -> partition map + tag index + flush groups
    (memstore/TimeSeriesShard.scala:258)."""

    def __init__(self, ref: DatasetRef, schemas: Schemas, shard_num: int,
                 num_groups: int = 8,
                 max_chunk_rows: int = DEFAULT_MAX_CHUNK_ROWS,
                 max_series: int = 1_000_000,
                 column_store: Optional[object] = None,
                 card_tracker: Optional[object] = None,
                 flush_downsampler: Optional[object] = None):
        self.ref = ref
        self.schemas = schemas
        self.shard_num = shard_num
        self.num_groups = num_groups
        self.max_chunk_rows = max_chunk_rows
        self.max_series = max_series  # cardinality quota (ratelimit/)
        # per-(ws,ns,metric) quota tree (ratelimit/CardinalityTracker)
        self.card_tracker = card_tracker
        # flush-time downsample emission (ShardDownsampler.scala:40)
        self.flush_downsampler = flush_downsampler
        self.column_store = column_store  # ChunkSink/RawChunkSource boundary
        self.partitions: Dict[int, TimeSeriesPartition] = {}
        self._by_part_key: Dict[bytes, int] = {}
        self._next_part_id = 0
        self.index = TagIndex()
        self.stats = ShardStats()
        # per-group ingestion checkpoint offsets (CheckpointTable semantics)
        self.checkpoints: Dict[int, int] = {}
        self._resident = 0      # running resident-sample count
        # settled-time lower bound (ms); -1 until the first row lands.
        # This is the MIN over per-partition last timestamps (ODP shells
        # contribute their persisted end time): the per-partition OOO
        # guard drops rows <= its own last, so no partition already in
        # the min-set can ever ingest at/below this watermark — steps
        # at/below it are settled, steps above it may still fill in
        # (a lagging series sits below faster ones and pins the min).
        # The results cache uses it as the freshness horizon; a
        # REGRESSION (new shard object replaying, adoption) signals
        # cached results built against this shard must be invalidated.
        self.ingest_watermark_ms = -1
        # monotone count of backfill events: a partition ENTERING the
        # min-set (new series, re-created series, shell without a
        # persisted end) whose first accepted row lands at/below the
        # watermark. Such rows dirty already-settled steps without
        # moving the watermark (the entrant's LAST may sit above it),
        # so the results cache invalidates on any epoch change.
        self.ingest_backfill_epoch = 0
        # storage-integrity state: how many corrupt records the durable
        # tier quarantined for this shard, and whether that loss tripped
        # the integrity-max-quarantined-records knob (the shard then
        # degrades to read-only — serving silently-partial data is the
        # one thing the integrity rail must never do). Written by the
        # single ingest thread, read racily by HTTP health threads,
        # same idiom as the watermark above.
        self.integrity_quarantined_records = 0
        self.integrity_read_only = False
        # serializes ODP page-ins (queries arrive from concurrent HTTP
        # threads; page-in rebinds part.chunks — everything else on the
        # read path sees immutable snapshots and needs no lock)
        self._odp_lock = threading.Lock()

    def update_integrity(self, stream_quarantined: int,
                         max_allowed: int) -> bool:
        """Refresh the shard's quarantine count (WAL + ColumnStore) and
        degrade to read-only when it exceeds ``max_allowed``. Returns
        the read-only state. Called from the ingest thread after reads
        and BEFORE applying a batch, so no records land after the knob
        trips."""
        total = int(stream_quarantined)
        cs = self.column_store
        if cs is not None and hasattr(cs, "quarantined_records"):
            total += cs.quarantined_records(self.ref.dataset,
                                            self.shard_num)
        self.integrity_quarantined_records = total
        if total > max_allowed and not self.integrity_read_only:
            self.integrity_read_only = True
            from filodb_tpu.obs import events as obs_events
            from filodb_tpu.obs import metrics as obs_metrics
            obs_metrics.GLOBAL_REGISTRY.gauge(
                "filodb_shard_integrity_read_only",
                "1 while the shard is degraded to read-only because "
                "quarantined-record loss exceeded the integrity knob"
            ).set(1.0, dataset=self.ref.dataset,
                  shard=str(self.shard_num))
            obs_events.emit("integrity-read-only",
                            dataset=self.ref.dataset, shard=self.shard_num,
                            quarantined=total, max_allowed=max_allowed)
        return self.integrity_read_only

    # -- ingest path ------------------------------------------------------
    def get_or_create_partition(self, part_key: PartKey, first_ts: int,
                                active: bool = True
                                ) -> Optional[TimeSeriesPartition]:
        """(TimeSeriesShard.scala:960 getOrAddPartitionForIngestion).
        ``active=False`` registers a recovered/bootstrapped shell that is
        counted in cardinality totals but not as actively ingesting."""
        kb = part_key.to_bytes()
        pid = self._by_part_key.get(kb)
        if pid is not None:
            return self.partitions[pid]
        if len(self.partitions) >= self.max_series:
            # shard-wide cap breach: drop new series
            self.stats.quota_dropped_series += 1
            return None
        if self.card_tracker is not None:
            from filodb_tpu.core.cardinality import QuotaReachedException
            try:
                self.card_tracker.modify_count(
                    self.card_tracker.prefix_of(part_key.label_map), 1,
                    1 if active else 0)
            except QuotaReachedException:
                # per-prefix quota breach: drop new series + stat
                # (QuotaExceededProtocol)
                self.stats.quota_dropped_series += 1
                return None
        schema = self.schemas.by_id(part_key.schema_id)
        pid = self._next_part_id
        self._next_part_id += 1
        part = TimeSeriesPartition(pid, part_key, schema, self.max_chunk_rows)
        part.card_active = active
        if self.flush_downsampler is not None:
            part.on_encode = self.flush_downsampler.on_chunk
        self.partitions[pid] = part
        self._by_part_key[kb] = pid
        self.index.add_part_key(pid, part_key.label_map, first_ts)
        self.stats.num_series = len(self.partitions)
        return part

    # the watermark/backfill-epoch mutation publishers: pull events —
    # the results cache re-reads them via its @event_source functions
    # on every lookup rather than being pushed to
    @publishes("watermark")
    @publishes("backfill-epoch")
    def ingest(self, container: RecordContainer, offset: int = -1) -> int:
        """Ingest one record container (TimeSeriesShard.scala:871).
        Returns number of rows ingested.

        Rows are processed in consecutive same-partition runs (builders
        emit per-series bursts), so the per-partition hot path is one
        batched buffer extension instead of a per-row Python loop."""
        n = 0
        tss, cols = container.arrays()
        wm_recompute = False
        for i, j, pk in container.runs():
            part = self.get_or_create_partition(pk, tss[i])
            if part is None:
                self.stats.rows_skipped += j - i
                continue
            if not part.card_active:
                # resumed ingest into a recovered/evicted shell
                part.card_active = True
                if self.card_tracker is not None:
                    self.card_tracker.modify_count(
                        self.card_tracker.prefix_of(pk.label_map), 0, 1)
            if part.odp_pending:
                # only page in when the run could overlap persisted history
                # (replay — the OOO guard then sees it); normal continuation
                # needs just the index end time, so restart recovery does
                # not trigger a full-retention read storm
                endt = self.index.end_time(part.part_id)
                if endt is not None and endt != END_TIME_INGESTING \
                        and min(tss[i:j]) <= endt:
                    # min of the whole run, not just the first row: an
                    # unsorted replay run may lead with a fresh row while
                    # later rows still overlap persisted history
                    self._ensure_loaded(part)
            prev_last = part.last_timestamp
            got = part.ingest_batch(tss[i:j], [c[i:j] for c in cols])
            if got:
                n += got
                self._resident += got
                last = part.last_timestamp
                if last is not None:
                    self.index.update_end_time(part.part_id, last)
                    if prev_last is None:
                        # partition enters the min-set: its last joins
                        # the min directly; a first row at/below the
                        # watermark is a BACKFILL into settled time
                        # (the run min, not the last — an entrant
                        # spanning the watermark still dirties the
                        # steps its early rows land on)
                        if self.ingest_watermark_ms >= 0:
                            if int(tss[i:j].min()) \
                                    <= self.ingest_watermark_ms:
                                self.ingest_backfill_epoch += 1
                            if last < self.ingest_watermark_ms:
                                self.ingest_watermark_ms = int(last)
                        else:
                            # first contribution ever (or only shells
                            # so far): fold in everything once
                            wm_recompute = True
                    elif prev_last <= self.ingest_watermark_ms:
                        # the min-set's laggard advanced: the min may
                        # rise — recompute once per container
                        wm_recompute = True
            self.stats.out_of_order_dropped += (j - i) - got
        if wm_recompute:
            self.ingest_watermark_ms = self._compute_watermark()
        self.stats.rows_ingested += n
        if offset >= 0:
            # conservative: record offset against all groups on explicit flush
            self._last_offset = offset
        return n

    def group_of(self, part_id: int) -> int:
        return part_id % self.num_groups

    def flush_group(self, group: int, offset: int = -1) -> int:
        """Encode write buffers of one flush group, persist new chunks +
        partkeys + the group checkpoint (TimeSeriesShard.scala:1341
        doFlushSteps: encode → ColumnStore.write → index/partkey write →
        writeCheckpoint).  Returns chunks written."""
        n = 0
        touched: List[TimeSeriesPartition] = []
        for pid, part in self.partitions.items():
            if pid % self.num_groups != group:
                continue
            info = part.switch_buffers()
            if info is not None:
                n += 1
                self.stats.chunks_encoded += 1
                self.stats.encoded_bytes += sum(len(v) for v in info.vectors)
            if self.column_store is not None \
                    and part.num_chunks > part.persisted_chunks:
                touched.append(part)
        if touched:
            from filodb_tpu.store import PartKeyEntry
            entries = []
            for part in touched:
                new = part.chunks[part.persisted_chunks:]
                self.column_store.write_chunks(
                    self.ref.dataset, self.shard_num,
                    part.part_key.to_bytes(), new)
                part.persisted_chunks = part.num_chunks
                self.stats.chunks_persisted += len(new)
                entries.append(PartKeyEntry(
                    part.part_key.to_bytes(),
                    self.index.start_time(part.part_id)
                    or part.earliest_timestamp or 0,
                    part.last_timestamp or 0))
            self.column_store.write_part_keys(self.ref.dataset,
                                              self.shard_num, entries)
        self.stats.flushes_done += 1
        if self.flush_downsampler is not None:
            # persist pending ds records (also covers chunks encoded by
            # buffer-full switches during ingest since the last flush)
            self.flush_downsampler.flush()
        if offset >= 0:
            self.checkpoints[group] = offset
            if self.column_store is not None:
                self.column_store.write_checkpoint(
                    self.ref.dataset, self.shard_num, group, offset)
        return n

    def flush_all(self, offset: int = -1) -> int:
        return sum(self.flush_group(g, offset) for g in range(self.num_groups))

    def recovery_watermark(self) -> int:
        """min checkpoint over groups — replay start offset
        (IngestionActor.scala:297 doRecovery)."""
        if len(self.checkpoints) < self.num_groups:
            return -1
        return min(self.checkpoints.values())

    def _compute_watermark(self) -> int:
        """Exact settled-time bound: min over per-partition last
        timestamps. Evicted/bootstrapped ODP shells (in-memory chunks
        gone, ``last_timestamp`` None) contribute their persisted index
        end time — the page-in + OOO path guarantees a shell never
        re-ingests at/below it. Partitions that never ingested
        constrain nothing. O(partitions); runs on the ingest thread
        only when the min-set's laggard advanced (or membership
        changed), never per row."""
        lo = None
        for pid, p in self.partitions.items():
            t = p.last_timestamp
            if t is None and p.odp_pending:
                t = self.index.end_time(pid)
                if t == END_TIME_INGESTING:
                    t = None
            if t is not None and (lo is None or t < lo):
                lo = int(t)
        return -1 if lo is None else lo

    # -- persistence / recovery -------------------------------------------
    @publishes("watermark")
    def bootstrap_from_store(self) -> int:
        """Rebuild the tag index + partition shells from persisted partkeys
        and load checkpoint offsets (IndexBootstrapper.scala:43; recovery
        watermark read IngestionActor.scala:174). Chunk data stays in the
        store until a query or ingest pages it in (ODP)."""
        if self.column_store is None:
            return 0
        n = 0
        for e in self.column_store.scan_part_keys(self.ref.dataset,
                                                  self.shard_num):
            pk = PartKey.from_bytes(e.part_key)
            part = self.get_or_create_partition(pk, e.start_ts,
                                                active=False)
            if part is None:
                continue
            part.odp_pending = True
            self.index.update_end_time(part.part_id, e.end_ts)
            n += 1
        self.checkpoints = dict(self.column_store.read_checkpoints(
            self.ref.dataset, self.shard_num))
        self.stats.partitions_bootstrapped += n
        # shells joined the min-set via their persisted end times
        self.ingest_watermark_ms = self._compute_watermark()
        return n

    def _ensure_loaded(self, part: TimeSeriesPartition) -> None:
        """ODP read-through: page this partition's chunks back from the
        ColumnStore (OnDemandPagingShard.scala:26 /
        DemandPagedChunkStore.scala:34 — granularity here is the whole
        partition; chunks are append-only so the merge is a sorted concat)."""
        with self._odp_lock:
            if not part.odp_pending or self.column_store is None:
                part.odp_pending = False
                return
            loaded = self.column_store.read_chunks(
                self.ref.dataset, self.shard_num, part.part_key.to_bytes())
            # skip chunks already in memory (a shell that ingested + flushed
            # before page-in has persisted chunks present on both sides)
            have = {c.id for c in part.chunks}
            infos = [ChunkSetInfo(c.chunk_id, c.num_rows, c.start_ts,
                                  c.end_ts, c.vectors)
                     for c in loaded if c.chunk_id not in have]
            # prepending invalidates the decoded-prefix caches; swap the
            # list and clear them under the partition's cache lock so a
            # concurrent reader can't repopulate against the old prefix
            with part._cache_lock:
                part.chunks = infos + part.chunks
                part.persisted_chunks += len(infos)
                part._chunk_seq = max(part._chunk_seq, len(part.chunks))
                part._decode_cache.clear()
                part._merge_cache.clear()
            self._resident += sum(c.num_rows for c in infos)
            # bootstrapped shells never saw an ingest row: learn the bucket
            # scheme from the paged-in chunk header
            if infos and part._hist_scheme is None:
                for ci, col in enumerate(part.schema.columns):
                    if col.col_type == ColumnType.HISTOGRAM:
                        part._hist_scheme = bh.hist_scheme_of(
                            infos[0].vectors[ci])
                        break
            part.odp_pending = False
            self.stats.partitions_paged_in += 1

    # -- read path --------------------------------------------------------
    def lookup_partitions(self, filters: Sequence[ColumnFilter],
                          start_ts: int, end_ts: int
                          ) -> List[TimeSeriesPartition]:
        """(memstore lookupPartitions via the tag index; pages in evicted
        partitions read-through like OnDemandPagingShard)."""
        pids = self.index.part_ids_from_filters(filters, start_ts, end_ts)
        out = []
        for p in pids:
            part = self.partitions[p]
            if part.odp_pending:
                self._ensure_loaded(part)
            out.append(part)
        return out

    # -- eviction ---------------------------------------------------------
    def resident_samples(self) -> int:
        """Samples held in memory (encoded chunks + write buffers); ODP
        shells count 0 (their data lives in the ColumnStore). O(1):
        maintained by ingest/eviction/page-in, so the per-flush headroom
        check doesn't rescan every partition's chunk list."""
        return self._resident

    def recount_resident(self) -> int:
        """Full rescan (tests / forensic cross-check of the counter)."""
        n = 0
        for p in self.partitions.values():
            n += sum(c.num_rows for c in p.chunks) + p._buf_rows
        return n

    def decode_cache_bytes(self) -> int:
        """Total bytes in per-partition decode/merge caches (the
        ``filodb_decode_cache_bytes`` gauge — previously this memory was
        unbounded and invisible)."""
        return sum(p.cache_bytes() for p in list(self.partitions.values()))

    def trim_decode_caches(self, max_bytes: int) -> int:
        """Memory-bound the host decode/merge caches: when their total
        exceeds ``max_bytes``, release the caches of least-recently-
        written partitions whose chunks are all flushed/persisted (pure
        duplicates of immutable chunk bytes) until under budget. Runs on
        the ingest driver's flush path. Returns bytes freed."""
        if max_bytes <= 0:
            return 0
        total = self.decode_cache_bytes()
        if total <= max_bytes:
            return 0
        freed = 0
        parts = sorted(list(self.partitions.values()),
                       key=lambda p: p.last_timestamp or 0)
        for p in parts:
            if total - freed <= max_bytes:
                break
            freed += p.release_caches()
        return freed

    def ensure_headroom(self, max_samples: int,
                        headroom_pct: int = 25) -> int:
        """Memory-pressure eviction: when resident samples exceed the
        budget, evict the least-recently-written partitions until
        ``headroom_pct`` percent of the budget is free again
        (the reference's headroom task + PartitionEvictionPolicy
        watermark, TimeSeriesShard ensureFreeSpace /
        ensure-block-memory-headroom-percent). Requires a ColumnStore
        (eviction turns partitions into ODP shells) or drops series.
        Returns partitions evicted."""
        if max_samples <= 0:
            return 0
        cur = self.resident_samples()
        if cur <= max_samples:
            return 0
        target = max_samples * (100 - headroom_pct) // 100
        parts = sorted(
            ((p.last_timestamp, p) for p in self.partitions.values()
             if p.last_timestamp is not None and p.chunks
             and not p._buf_rows and not p.odp_pending),
            key=lambda x: x[0])
        freed = 0
        cutoff = None
        for last_ts, p in parts:
            if cur - freed <= target:
                break
            freed += sum(c.num_rows for c in p.chunks)
            cutoff = last_ts + 1
        if cutoff is None:
            return 0
        return self.evict_partitions(cutoff_ts=cutoff)

    @publishes("watermark")
    def evict_partitions(self, cutoff_ts: int) -> int:
        """Evict series whose data ended before cutoff
        (PartitionEvictionPolicy / EvictablePartIdQueueSet equivalents).

        With a ColumnStore the partition becomes an ODP shell: unpersisted
        chunks are written out first, memory is released, the index entry
        stays so queries can page the data back. Without one, the series is
        dropped entirely (memory-only deployments)."""
        evict = [
            pid for pid, p in self.partitions.items()
            if (p.last_timestamp is not None and p.last_timestamp < cutoff_ts
                and not p._buf_rows
                # shells that re-accumulated chunks (resumed ingest after
                # an earlier eviction) are evictable again; empty shells
                # have nothing to release
                and (p.chunks or not p.odp_pending))
        ]
        if self.column_store is not None:
            from filodb_tpu.store import PartKeyEntry
            entries = []
            # hold the ODP lock for the persist+clear: a concurrent
            # _ensure_loaded page-in snapshotting chunks mid-eviction
            # could otherwise clear odp_pending with the just-evicted
            # chunks missing — silent permanent data loss until restart
            with self._odp_lock:
                for pid in evict:
                    part = self.partitions[pid]
                    new = part.chunks[part.persisted_chunks:]
                    if new:
                        self.column_store.write_chunks(
                            self.ref.dataset, self.shard_num,
                            part.part_key.to_bytes(), new)
                        self.stats.chunks_persisted += len(new)
                    entries.append(PartKeyEntry(
                        part.part_key.to_bytes(),
                        self.index.start_time(pid)
                        or part.earliest_timestamp or 0,
                        part.last_timestamp or 0))
                    self._resident -= sum(c.num_rows for c in part.chunks)
                    with part._cache_lock:
                        # flag BEFORE clearing: a concurrent lookup must
                        # either see the data or see the page-in flag,
                        # never an empty unflagged partition
                        part.odp_pending = True
                        part.chunks = []
                        part.persisted_chunks = 0
                        part._decode_cache.clear()
                        part._merge_cache.clear()
            if entries:
                self.column_store.write_part_keys(
                    self.ref.dataset, self.shard_num, entries)
            for pid in evict:       # ODP shells: still counted, inactive
                part = self.partitions[pid]
                if part.card_active:
                    part.card_active = False
                    if self.card_tracker is not None:
                        self.card_tracker.modify_count(
                            self.card_tracker.prefix_of(
                                part.part_key.label_map), 0, -1)
        else:
            for pid in evict:
                part = self.partitions.pop(pid)
                self._resident -= sum(c.num_rows for c in part.chunks) \
                    + part._buf_rows
                self._by_part_key.pop(part.part_key.to_bytes(), None)
                if self.card_tracker is not None:
                    self.card_tracker.modify_count(
                        self.card_tracker.prefix_of(part.part_key.label_map),
                        -1, -1 if part.card_active else 0)
            self.index.remove_part_keys(evict)
            self.stats.num_series = len(self.partitions)
        self.stats.partitions_evicted += len(evict)
        if evict:
            # ODP shells swap a live last for an equal persisted end
            # (min unchanged); dropped series LEAVE the min-set and the
            # min may rise — recompute either way (eviction is rare)
            self.ingest_watermark_ms = self._compute_watermark()
        return len(evict)


class TimeSeriesMemStore:
    """Top-level store: dataset -> shards (memstore/TimeSeriesMemStore.scala:26).
    """

    def __init__(self, schemas: Optional[Schemas] = None,
                 column_store: Optional[object] = None):
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        self.schemas = schemas or DEFAULT_SCHEMAS
        self.column_store = column_store
        self._shards: Dict[DatasetRef, Dict[int, TimeSeriesShard]] = {}
        # the shard MAP (not the shards) is mutated from concurrent
        # adopt/release workers during elastic membership; reads stay
        # lock-free GIL-atomic lookups
        self._shards_lock = threading.Lock()

    def setup(self, ref: DatasetRef, shard_num: int, num_groups: int = 8,
              max_chunk_rows: int = DEFAULT_MAX_CHUNK_ROWS,
              bootstrap: bool = False,
              card_tracker: Optional[object] = None,
              flush_downsampler: Optional[object] = None
              ) -> TimeSeriesShard:
        """Create one shard; with ``bootstrap`` (and a column store) the tag
        index + checkpoints are recovered from persistence
        (TimeSeriesMemStore.scala setup + IndexBootstrapper on startup)."""
        shard = TimeSeriesShard(ref, self.schemas, shard_num, num_groups,
                                max_chunk_rows,
                                column_store=self.column_store,
                                card_tracker=card_tracker,
                                flush_downsampler=flush_downsampler)
        with self._shards_lock:
            shards = self._shards.setdefault(ref, {})
            if shard_num in shards:
                raise ValueError(
                    f"shard {shard_num} already set up for {ref}")
            shards[shard_num] = shard
        if bootstrap:
            shard.bootstrap_from_store()
        return shard

    def get_shard(self, ref: DatasetRef, shard_num: int) -> TimeSeriesShard:
        return self._shards[ref][shard_num]

    def remove_shard(self, ref: DatasetRef, shard_num: int) -> None:
        """Release a shard (elastic recovery hand-back: the adopter drops
        its copy when the original owner returns — ShardManager.scala
        stopShards semantics)."""
        with self._shards_lock:
            self._shards.get(ref, {}).pop(shard_num, None)

    def shards(self, ref: DatasetRef) -> List[TimeSeriesShard]:
        return [s for _, s in sorted(self._shards.get(ref, {}).items())]

    def ingest(self, ref: DatasetRef, shard_num: int,
               container: RecordContainer, offset: int = -1) -> int:
        return self.get_shard(ref, shard_num).ingest(container, offset)

    def flush_all(self, ref: DatasetRef) -> int:
        return sum(s.flush_all() for s in self.shards(ref))

    def lookup_partitions(self, ref: DatasetRef, shard_num: int,
                          filters: Sequence[ColumnFilter],
                          start_ts: int, end_ts: int):
        return self.get_shard(ref, shard_num).lookup_partitions(
            filters, start_ts, end_ts)
