"""Per-tenant cardinality metering publisher.

The reference runs TenantIngestionMetering
(coordinator/src/main/scala/filodb.coordinator/TenantIngestionMetering.scala):
a periodic task issuing TsCardinalities against every dataset and
publishing the per-(_ws_, _ns_) series counts as metrics, so operators
chart tenant growth without querying the cardinality API. Same shape
here: a daemon thread snapshots the shard cardinality trackers at a
fixed interval into gauges the /metrics exposition serves."""

from __future__ import annotations

import threading
from typing import Dict, Mapping, Tuple


class TenantMetering:
    """Periodic depth-2 (workspace, namespace) cardinality snapshots."""

    def __init__(self, trackers: Mapping[int, object],
                 interval_s: float = 60.0, depth: int = 2):
        self.trackers = trackers          # shard -> CardinalityTracker
        self.interval_s = interval_s
        self.depth = depth
        # (ws, ns) -> (ts_count, active_ts_count); swapped atomically
        self.latest: Dict[Tuple[str, ...], Tuple[int, int]] = {}
        self.snapshots = 0
        self._stop = threading.Event()
        self._thread = None

    def snapshot_once(self) -> None:
        agg: Dict[Tuple[str, ...], Tuple[int, int]] = {}
        for tracker in list(self.trackers.values()):
            for rec in tracker.scan((), self.depth):
                if len(rec.prefix) != self.depth:
                    continue
                t, a = agg.get(rec.prefix, (0, 0))
                agg[rec.prefix] = (t + rec.ts_count,
                                   a + rec.active_ts_count)
        self.latest = agg                 # atomic rebind for readers
        self.snapshots += 1

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.snapshot_once()
            except Exception:
                pass                      # keep the metering loop alive

    def start(self) -> "TenantMetering":
        self.snapshot_once()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tenant-metering")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
