"""Per-tenant cardinality metering publisher.

The reference runs TenantIngestionMetering
(coordinator/src/main/scala/filodb.coordinator/TenantIngestionMetering.scala):
a periodic task issuing TsCardinalities against every dataset and
publishing the per-(_ws_, _ns_) series counts as metrics, so operators
chart tenant growth without querying the cardinality API. Same shape
here: a daemon thread snapshots the shard cardinality trackers at a
fixed interval into gauges the /metrics exposition serves."""

from __future__ import annotations

import threading
import time
from typing import Dict, Mapping, Optional, Tuple

from filodb_tpu.lint.threads import thread_root


class TenantMetering:
    """Periodic depth-2 (workspace, namespace) cardinality snapshots.

    Daemon-thread lifecycle contract (the reference's
    TenantIngestionMetering runs on the coordinator scheduler and dies
    with it): ``start()`` takes an eager first snapshot and spawns the
    loop; ``stop()`` is idempotent, joins the thread, and after it
    returns ``alive`` is False — the standalone server calls it on
    shutdown so no metering thread outlives the process teardown.
    ``last_snapshot_age_s`` is exported in /metrics so a stalled or
    dead loop shows as a growing age instead of silently-stale
    gauges."""

    def __init__(self, trackers: Mapping[int, object],
                 interval_s: float = 60.0, depth: int = 2):
        self.trackers = trackers          # shard -> CardinalityTracker
        self.interval_s = float(interval_s)
        self.depth = depth
        # (ws, ns) -> (ts_count, active_ts_count); swapped atomically
        self.latest: Dict[Tuple[str, ...], Tuple[int, int]] = {}
        self.snapshots = 0
        self.last_snapshot_t: Optional[float] = None   # monotonic
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def alive(self) -> bool:
        """True while the snapshot thread is running (False before
        start and after a completed stop/join)."""
        return self._thread is not None and self._thread.is_alive()

    @property
    def last_snapshot_age_s(self) -> Optional[float]:
        """Seconds since the last completed snapshot (None before the
        first one) — the loop-liveness gauge."""
        if self.last_snapshot_t is None:
            return None
        return time.monotonic() - self.last_snapshot_t

    def count_for(self, prefix: Tuple[str, ...]) -> Optional[int]:
        """Series count for a (ws[, ns]) prefix from the latest
        snapshot, or None when the prefix has never appeared. The QoS
        cost estimator reads this to price REMOTE shard groups (local
        cardinality trackers only know local shards; the metering
        snapshot is the node's aggregated per-tenant view)."""
        latest = self.latest                    # atomic snapshot ref
        if not latest:
            return None
        total = 0
        found = False
        for pfx, (t, _a) in latest.items():
            if pfx[:len(prefix)] == tuple(prefix):
                total += t
                found = True
        return total if found else None

    def snapshot_once(self) -> None:
        agg: Dict[Tuple[str, ...], Tuple[int, int]] = {}
        for tracker in list(self.trackers.values()):
            for rec in tracker.scan((), self.depth):
                if len(rec.prefix) != self.depth:
                    continue
                t, a = agg.get(rec.prefix, (0, 0))
                agg[rec.prefix] = (t + rec.ts_count,
                                   a + rec.active_ts_count)
        self.latest = agg                 # atomic rebind for readers
        self.snapshots += 1
        self.last_snapshot_t = time.monotonic()

    @thread_root("tenant-metering")
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.snapshot_once()
            except Exception:
                pass                      # keep the metering loop alive

    def start(self) -> "TenantMetering":
        self.snapshot_once()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tenant-metering")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stop + JOIN the snapshot thread (idempotent; safe to call
        before start)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            if not t.is_alive():
                self._thread = None
