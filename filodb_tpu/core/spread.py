"""Spread provider: per-shard-key fan-out overrides.

(core/SpreadProvider.scala + filodb-defaults.conf:319 — a system
default-spread plus per-application overrides keyed by shard-key values;
doc/sharding.md "Spread": hot shard keys get a larger spread so one
tenant's series fan across 2^spread shards.)

The SAME provider instance must drive both the ingest edge (gateway
shard routing) and the query planner (shard pruning) — a mismatch
silently prunes to the wrong shards. `FiloServer` builds one from config
and hands it to both, which replaces the previous "these two ints MUST
match" comment-level contract.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence


class SpreadProvider:
    """default spread + overrides keyed by comma-joined non-metric
    shard-key values (e.g. "demo,App-0")."""

    def __init__(self, default_spread: int = 1,
                 overrides: Optional[Mapping[str, int]] = None):
        self.default_spread = int(default_spread)
        self.overrides: Dict[str, int] = {
            k: int(v) for k, v in (overrides or {}).items()}

    @staticmethod
    def _key(shard_key_values: Sequence[str]) -> str:
        return ",".join(shard_key_values)

    def spread_for(self, shard_key_values: Sequence[str]) -> int:
        return self.overrides.get(self._key(shard_key_values),
                                  self.default_spread)

    def spread_for_labels(self, labels: Mapping[str, str],
                          shard_key_columns: Sequence[str]) -> int:
        return self.spread_for([labels.get(c, "")
                                for c in shard_key_columns])
