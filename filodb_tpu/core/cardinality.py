"""Cardinality control: per-(workspace, namespace, metric) series counting
with quota enforcement at series creation.

Re-design of the reference's ratelimit subsystem
(core/memstore/ratelimit/CardinalityTracker.scala:38 — a prefix-tree of
counts with per-node quotas; RocksDbCardinalityStore.scala:70 backs it with
RocksDB for crash-safe, memory-bounded storage; CardinalityManager.scala:14
periodically rebuilds from the Lucene index; quota config
filodb-defaults.conf:277-318). Here the tree is in-process dicts — counts
are re-derived from persisted partkeys on bootstrap, which is the
reference's own recovery story, so durable storage adds nothing at this
scale.

Prefix levels mirror the reference: () → (ws,) → (ws, ns) →
(ws, ns, metric). A new series increments all four levels; a quota breach
at ANY level rejects the series (QuotaReachedException →
QuotaExceededProtocol: the shard drops the series and counts it). Counts
rebuild naturally on restart: bootstrap re-registers every recovered
series through the same admission path (the reference instead rebuilds
from Lucene periodically, CardinalityManager.scala:14).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from filodb_tpu.lint.locks import single_writer

SHARD_KEY_LABELS = ("_ws_", "_ns_", "_metric_")
MAX_DEPTH = len(SHARD_KEY_LABELS)


class QuotaReachedException(Exception):
    def __init__(self, prefix: Tuple[str, ...], quota: int):
        super().__init__(f"cardinality quota {quota} reached at "
                         f"prefix {prefix}")
        self.prefix = prefix
        self.quota = quota


@dataclass
class CardinalityRecord:
    """(ratelimit/CardinalityRecord — one node of the tree.)"""
    prefix: Tuple[str, ...]
    ts_count: int = 0           # series under this prefix
    active_ts_count: int = 0    # actively ingesting series
    children_count: int = 0     # direct children
    quota: int = 0              # 0 = unlimited

    def to_json(self) -> Dict:
        return {"prefix": list(self.prefix), "tsCount": self.ts_count,
                "activeTsCount": self.active_ts_count,
                "childrenCount": self.children_count,
                "childrenQuota": self.quota}


@single_writer("prefix-tree nodes belong to one shard's tracker "
               "(see CardinalityTracker)")
@dataclass
class _Node:
    ts_count: int = 0
    active: int = 0
    quota: int = 0
    children: Dict[str, "_Node"] = field(default_factory=dict)


@single_writer("one tracker per shard: quota setup runs before the "
               "shard serves, counts mutate only on the shard's owning "
               "thread; metering reads are racy-by-design snapshots")
class CardinalityTracker:
    """Prefix tree of series counts with quota enforcement
    (CardinalityTracker.scala:38)."""

    def __init__(self, default_quotas: Sequence[int] = (0, 0, 0, 0)):
        # default quota per depth (0..3); 0 = unlimited
        self.default_quotas = tuple(default_quotas) + (0,) * (
            MAX_DEPTH + 1 - len(default_quotas))
        self.root = _Node(quota=self.default_quotas[0])

    # -- quota config (QuotaSource) ---------------------------------------
    def set_quota(self, prefix: Sequence[str], quota: int) -> None:
        node = self.root
        for depth, p in enumerate(prefix):
            node = node.children.setdefault(
                p, _Node(quota=self.default_quotas[
                    min(depth + 1, MAX_DEPTH)]))
        node.quota = quota

    @staticmethod
    def prefix_of(labels: Mapping[str, str]) -> Tuple[str, ...]:
        return tuple(labels.get(l, "") for l in SHARD_KEY_LABELS)

    # -- counting (modifyCount) -------------------------------------------
    def modify_count(self, prefix: Sequence[str], delta: int,
                     active_delta: int = 0) -> None:
        """Walk the prefix path adjusting counts; on a positive delta,
        raise QuotaReachedException if any level would exceed its quota —
        in that case NOTHING is modified and no tree nodes are created
        (a rejected high-cardinality flood must not grow the tree)."""
        # pass 1: existing nodes only — quota checks before any mutation
        existing: List[_Node] = [self.root]
        node = self.root
        missing_from = None
        for depth, p in enumerate(prefix[:MAX_DEPTH]):
            child = node.children.get(p) if node is not None else None
            if child is None:
                if missing_from is None:
                    missing_from = depth
                node = None
                continue
            existing.append(child)
            node = child
        if delta > 0:
            for n in existing:
                if n.quota and n.ts_count + delta > n.quota:
                    raise QuotaReachedException(tuple(prefix), n.quota)
            if missing_from is not None:
                # nodes to be created get depth defaults; reject if the
                # default itself cannot admit the delta
                for depth in range(missing_from, min(len(prefix),
                                                     MAX_DEPTH)):
                    dq = self.default_quotas[depth + 1]
                    if dq and delta > dq:
                        raise QuotaReachedException(tuple(prefix), dq)
        # pass 2: create + mutate
        path: List[_Node] = [self.root]
        node = self.root
        for depth, p in enumerate(prefix[:MAX_DEPTH]):
            child = node.children.get(p)
            if child is None:
                child = _Node(quota=self.default_quotas[depth + 1])
                node.children[p] = child
            path.append(child)
            node = child
        for n in path:
            n.ts_count += delta
            n.active += active_delta
            if n.ts_count < 0:
                n.ts_count = 0
            if n.active < 0:
                n.active = 0

    # -- scans (TsCardinalities / topkCardLocal) --------------------------
    def _node_at(self, prefix: Sequence[str]) -> Optional[_Node]:
        node = self.root
        for p in prefix:
            node = node.children.get(p)
            if node is None:
                return None
        return node

    def series_count(self, prefix: Sequence[str]) -> Optional[int]:
        """Series count under ``prefix`` (O(depth) — the QoS cost
        estimator's cardinality input), or None when the prefix has
        never been seen. An empty prefix answers the shard total."""
        node = self._node_at(prefix)
        if node is None:
            return None
        return node.ts_count

    def scan(self, prefix: Sequence[str], depth: int
             ) -> List[CardinalityRecord]:
        """Records at ``depth`` under ``prefix`` (TsCardinalities plan:
        shard_key_prefix + num_groups)."""
        base = self._node_at(prefix)
        if base is None:
            return []
        out: List[CardinalityRecord] = []

        def rec(node: _Node, path: Tuple[str, ...]):
            if len(path) == depth:
                out.append(CardinalityRecord(
                    path, node.ts_count, node.active,
                    len(node.children), node.quota))
                return
            for name, child in node.children.items():
                rec(child, path + (name,))

        rec(base, tuple(prefix))
        return out

    def top_k(self, prefix: Sequence[str], k: int
              ) -> List[CardinalityRecord]:
        """Heaviest direct children of a prefix (CLI topkcardlocal)."""
        node = self._node_at(prefix)
        if node is None:
            return []
        items = sorted(node.children.items(),
                       key=lambda kv: -kv[1].ts_count)[:k]
        return [CardinalityRecord(tuple(prefix) + (name,), c.ts_count,
                                  c.active, len(c.children), c.quota)
                for name, c in items]


def merge_records(per_shard: Sequence[Sequence[CardinalityRecord]]
                  ) -> List[CardinalityRecord]:
    """Sum same-prefix records across shards (TsCardReduceExec)."""
    acc: Dict[Tuple[str, ...], CardinalityRecord] = {}
    for records in per_shard:
        for r in records:
            got = acc.get(r.prefix)
            if got is None:
                acc[r.prefix] = CardinalityRecord(
                    r.prefix, r.ts_count, r.active_ts_count,
                    r.children_count, r.quota)
            else:
                got.ts_count += r.ts_count
                got.active_ts_count += r.active_ts_count
                # children are NAME sets, not disjoint across shards: the
                # max is a lower bound on distinct children (scan one
                # level deeper for exact names)
                got.children_count = max(got.children_count,
                                         r.children_count)
    return sorted(acc.values(), key=lambda r: -r.ts_count)
