"""Degraded-mode execution primitives for the distributed query path:
bounded retries, per-peer circuit breakers, and deadline budgets.

The reference stack gets these from Akka (remote dispatch timeouts,
DeathWatch-driven circuit breaking in ActorPlanDispatcher +
queryActorsCircuitBreaker config, filodb-defaults.conf) and from the
Prometheus-federation ecosystem's partial-response semantics (Thanos
`partial_response_strategy`, M3 fanout warnings). This module is the
TPU build's equivalent, threaded through RemoteShardGroup /
GrpcShardGroup leaf dispatch and PromQlRemoteExec / GrpcRemoteExec
pushdown:

  * ``RetryPolicy`` — bounded retries with exponential backoff and full
    jitter, deadline-aware (never sleeps past the budget).
  * ``CircuitBreaker`` — opens after N consecutive transport failures
    and stops dialing the peer entirely; a half-open probe after
    ``reset_timeout_s`` lets ONE call through, and its outcome closes or
    re-opens the breaker. Keyed per peer address in a
    ``BreakerRegistry`` owned by the server (breaker state must outlive
    a single query).
  * ``Deadline`` — a remaining-time budget created at the HTTP/gRPC
    entry point and threaded down the exec tree, so every remote hop
    uses ``min(flat_timeout, remaining)`` instead of a flat 60s, and
    exhausted budgets fail fast with a clean QueryError.

Error taxonomy: ``TransportError`` (peer unreachable / RPC transport
failure — retryable, counts against the breaker) vs a plain
``QueryError`` from the peer (application-level — NOT retryable: the
peer answered; retrying would repeat the same error)."""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.query.model import QueryError


class TransportError(QueryError):
    """The peer could not be reached or the transport failed mid-call.
    Retryable; consecutive occurrences trip the peer's circuit breaker."""


class BreakerOpenError(QueryError):
    """The peer's circuit breaker is open: the call was not attempted."""


class DeadlineExceeded(QueryError):
    """The query's deadline budget ran out."""


class Deadline:
    """Monotonic remaining-time budget for one query.

    Created once at the entry point; every remote call clips its flat
    timeout to ``remaining()`` and checks ``expired`` before dialing, so
    a query never outlives its budget no matter how many hops retry."""

    def __init__(self, budget_s: float, clock: Callable[[], float]
                 = time.monotonic):
        self._clock = clock
        self.budget_s = float(budget_s)
        self._t_end = clock() + float(budget_s)

    @classmethod
    def after(cls, budget_s: float, clock: Callable[[], float]
              = time.monotonic) -> "Deadline":
        return cls(budget_s, clock)

    def remaining(self) -> float:
        return self._t_end - self._clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "query") -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"deadline of {self.budget_s:g}s exceeded during {what}")

    def clip(self, timeout_s: float) -> float:
        """Flat per-hop timeout clipped to the remaining budget; raises
        when the budget is already gone (never dial with <= 0)."""
        rem = self.remaining()
        if rem <= 0:
            raise DeadlineExceeded(
                f"deadline of {self.budget_s:g}s exceeded before "
                f"remote call")
        return min(float(timeout_s), rem)


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retries with exponential backoff + full jitter
    (the AWS-style decorrelated backoff; Akka's RestartFlow analogue).
    ``max_attempts`` counts the first try: 3 = 1 call + 2 retries."""
    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5       # fraction of the delay randomized away

    def delay_s(self, attempt: int, rng: Callable[[], float]
                = random.random) -> float:
        """Backoff before retry #``attempt`` (1-based)."""
        d = min(self.max_delay_s,
                self.base_delay_s * self.multiplier ** (attempt - 1))
        return d * (1.0 - self.jitter * rng())


@guarded_by("_lock", "_state", "_failures", "_opened_at")
class CircuitBreaker:
    """Per-peer transport circuit breaker (CLOSED -> OPEN -> HALF_OPEN).

    CLOSED: calls flow; ``failure_threshold`` CONSECUTIVE transport
    failures open it. OPEN: ``allow()`` is False (no dials) until
    ``reset_timeout_s`` elapses, then exactly one caller wins the
    half-open probe slot. HALF_OPEN: the probe's success closes the
    breaker, its failure re-opens it for another full timeout."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 3,
                 reset_timeout_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """True when a call may be attempted now. In OPEN state, the
        first caller past the reset timeout claims the half-open probe;
        others keep getting False until the probe resolves."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at >= self.reset_timeout_s:
                    self._state = self.HALF_OPEN
                    return True
                return False
            return False            # HALF_OPEN: probe already in flight

    def record_success(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._state = self.OPEN
                self._opened_at = self._clock()
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._state = self.OPEN
                self._opened_at = self._clock()


@guarded_by("_lock", "_breakers", "_retry_stats")
class BreakerRegistry:
    """Address-keyed breaker map. One registry per server process (the
    HTTP server owns it), shared across queries so breaker state
    persists; a module-level default serves directly-constructed
    exec nodes/tests."""

    def __init__(self, failure_threshold: int = 3,
                 reset_timeout_s: float = 5.0):
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        # per-peer call-policy counters surfaced in /metrics:
        # attempts (dials tried), retries (re-dials after transport
        # failure), exhaustions (gave up with retries spent), rejections
        # (not dialed: breaker open)
        self._retry_stats: Dict[str, Dict[str, int]] = {}

    def get(self, key: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(key)
            if b is None:
                b = CircuitBreaker(self.failure_threshold,
                                   self.reset_timeout_s)
                self._breakers[key] = b
            return b

    def record(self, key: str, counter: str, n: int = 1) -> None:
        with self._lock:
            st = self._retry_stats.setdefault(
                key, {"attempts": 0, "retries": 0, "exhaustions": 0,
                      "rejections": 0})
            st[counter] = st.get(counter, 0) + n

    def metrics_snapshot(self) -> Dict[str, Dict[str, object]]:
        """Per-peer view for the /metrics exposition: breaker state +
        retry counters. Breaker state reads take each breaker's own
        lock AFTER the registry lock is released (fixed order, no
        nesting)."""
        with self._lock:
            breakers = dict(self._breakers)
            stats = {k: dict(v) for k, v in self._retry_stats.items()}
        out: Dict[str, Dict[str, object]] = {}
        for key in set(breakers) | set(stats):
            entry: Dict[str, object] = dict(stats.get(key, {}))
            b = breakers.get(key)
            if b is not None:
                entry["state"] = b.state
            out[key] = entry
        return out

    def reset(self) -> None:
        with self._lock:
            self._breakers.clear()
            self._retry_stats.clear()


DEFAULT_BREAKERS = BreakerRegistry()


@dataclass
class PeerResilience:
    """The per-server bundle threaded planner -> exec nodes: retry
    policy + the breaker registry remote calls consult."""
    retry: RetryPolicy
    breakers: BreakerRegistry

    @classmethod
    def default(cls) -> "PeerResilience":
        return cls(retry=RetryPolicy(), breakers=DEFAULT_BREAKERS)


def resilient_call(do_call: Callable[[float], object], *,
                   key: str, node_id: str,
                   timeout_s: float,
                   retry: Optional[RetryPolicy] = None,
                   breakers: Optional[BreakerRegistry] = None,
                   deadline: Optional[Deadline] = None,
                   sleep: Callable[[float], None] = time.sleep):
    """Run one remote hop under the full policy stack.

    ``do_call(timeout_s)`` performs the dial with the given per-attempt
    timeout and raises TransportError on transport failure. Breaker-open
    peers are not dialed at all; transport failures are retried within
    the deadline budget; peer application errors pass straight through
    (the peer answered — retrying repeats the same error)."""
    retry = retry or RetryPolicy()
    registry = breakers or DEFAULT_BREAKERS
    breaker = registry.get(key)
    if not breaker.allow():
        registry.record(key, "rejections")
        # tracing: a rejected dial is a point event on the trace — the
        # call never happened, so there is no duration to record
        obs_trace.event("breaker-rejected", peer=node_id, key=key)
        raise BreakerOpenError(
            f"peer {node_id} ({key}) circuit breaker is open")
    attempt = 0
    while True:
        attempt += 1
        registry.record(key, "attempts")
        if deadline is not None:
            deadline.check(f"call to peer {node_id}")
        t = deadline.clip(timeout_s) if deadline is not None \
            else float(timeout_s)
        try:
            # each attempt is its own span: a retried call shows up in
            # the trace as SIBLING spans, the failed ones tagged with
            # the transport error (span __exit__ records it)
            with obs_trace.span("peer-attempt", peer=node_id,
                                attempt=attempt, retry=attempt > 1):
                out = do_call(t)
        except TransportError:
            breaker.record_failure()
            if attempt >= retry.max_attempts or not breaker.allow():
                registry.record(key, "exhaustions")
                raise
            d = retry.delay_s(attempt)
            if deadline is not None:
                rem = deadline.remaining()
                if rem <= 0:
                    registry.record(key, "exhaustions")
                    raise
                d = min(d, max(rem - 1e-3, 0.0))
            registry.record(key, "retries")
            if d > 0:
                sleep(d)
            continue
        except QueryError:
            # the peer ANSWERED (transport is healthy): an application
            # error must not keep a half-open breaker stuck open, and
            # is never retried — the same call repeats the same error
            breaker.record_success()
            raise
        breaker.record_success()
        return out
