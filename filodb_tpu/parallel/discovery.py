"""Cluster seed discovery: how a node finds its peers at startup.

The reference boots its Akka cluster through pluggable seed discovery
(akka-bootstrapper/src/main/scala/filodb/akkabootstrapper/
AkkaBootstrapper.scala:31 — whitelist, DNS-SRV, and Consul strategies
selected by config). Same surface here, producing the {node_id: url}
peer map the standalone server and FailureDetector consume:

  * ``explicit-list`` — the static map from config (ExplicitList mode).
  * ``dns-srv``       — resolve an SRV name to host:port targets
                        (SrvSeedDiscovery): ordinals follow the sorted
                        target list so every node derives the SAME ids.
  * ``consul``        — query a Consul catalog service endpoint
                        (ConsulSeedDiscovery) over its HTTP API.

Resolvers/fetchers are injectable (tests and air-gapped environments);
the defaults use dnspython when present and urllib for Consul.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# one SRV/consul target: (host, port)
Target = Tuple[str, int]


def _default_srv_resolver(name: str) -> List[Target]:
    try:
        import dns.resolver  # type: ignore
    except ImportError as e:        # pragma: no cover - env dependent
        raise RuntimeError(
            "dns-srv discovery needs the dnspython package or an "
            "injected resolver") from e
    out = []
    for r in dns.resolver.resolve(name, "SRV"):   # pragma: no cover
        out.append((str(r.target).rstrip("."), int(r.port)))
    return out


def _default_consul_fetcher(url: str) -> List[dict]:
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _peer_map(targets: Sequence[Target], scheme: str) -> Dict[str, str]:
    """Deterministic node ids: every node sorts the same target list, so
    ordinals agree cluster-wide without a coordinator (the property the
    reference gets from sorted seed addresses)."""
    ordered = sorted(set(targets))
    return {f"node{i}": f"{scheme}://{host}:{port}"
            for i, (host, port) in enumerate(ordered)}


def discover_peers(config: dict,
                   srv_resolver: Optional[Callable] = None,
                   consul_fetcher: Optional[Callable] = None
                   ) -> Dict[str, str]:
    """Resolve the peer map for a discovery config:

      {"mode": "explicit-list", "peers": {...}}
      {"mode": "dns-srv", "srv-name": "_filodb._tcp.ns.svc"}
      {"mode": "consul", "url": "http://consul:8500", "service": "filodb"}
    """
    mode = (config or {}).get("mode", "explicit-list")
    scheme = (config or {}).get("scheme", "http")
    if mode == "explicit-list":
        return dict((config or {}).get("peers") or {})
    if mode == "dns-srv":
        name = config["srv-name"]
        resolver = srv_resolver or _default_srv_resolver
        return _peer_map(resolver(name), scheme)
    if mode == "consul":
        base = config["url"].rstrip("/")
        service = config["service"]
        fetcher = consul_fetcher or _default_consul_fetcher
        rows = fetcher(f"{base}/v1/catalog/service/{service}")
        targets = [(row.get("ServiceAddress") or row.get("Address"),
                    int(row["ServicePort"])) for row in rows]
        return _peer_map(targets, scheme)
    raise ValueError(f"unknown discovery mode {mode!r}")
