"""Shard → node/device mapping with the reference's shard state FSM.

Mirrors coordinator/ShardMapper.scala:26 (shard→ActorRef array, updateFromEvent
:204, ingestionShard :122, queryShards :93) and ShardStatus.scala's state
machine (Unassigned/Assigned/Active/Recovery/Down/Error/Stopped) — but a
"node" here is a host/device slot in the mesh, not an Akka actor.

The hash math itself (xxh32 shard-key hash, combineHash, spread bit split)
lives in filodb_tpu.core.record (ingestion_shard / query_shards) and is
bit-compatible with RecordBuilder.scala:638-683 so sharding interoperates
with reference deployments.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from filodb_tpu.core.record import ingestion_shard, query_shards
from filodb_tpu.lint.caches import publishes
from filodb_tpu.lint.locks import guarded_by


class ShardStatus(enum.Enum):
    """Shard lifecycle states (ShardStatus.scala)."""
    UNASSIGNED = "unassigned"
    ASSIGNED = "assigned"           # node picked, ingestion not started
    ACTIVE = "active"               # ingesting + queryable
    RECOVERY = "recovery"           # replaying from checkpoint (has progress)
    ERROR = "error"
    DOWN = "down"
    STOPPED = "stopped"

    @property
    def queryable(self) -> bool:
        return self in (ShardStatus.ACTIVE, ShardStatus.RECOVERY)


@dataclass
class ShardState:
    status: ShardStatus = ShardStatus.UNASSIGNED
    node: Optional[str] = None      # node/coordinator identifier
    progress_pct: int = 0           # recovery progress (ShardStatus.scala)


@dataclass
class ShardEvent:
    """Published on state transitions (ShardStatus.scala sealed trait)."""
    shard: int
    status: ShardStatus
    node: Optional[str] = None
    progress_pct: int = 0


@guarded_by("_lock", "_epoch")
class ShardMapper:
    """numShards-entry shard→node table + status FSM (ShardMapper.scala:26)."""

    def __init__(self, num_shards: int):
        if num_shards <= 0 or (num_shards & (num_shards - 1)) != 0:
            raise ValueError("num_shards must be a power of 2")
        self.num_shards = num_shards
        self._states: List[ShardState] = [ShardState()
                                          for _ in range(num_shards)]
        self._subscribers: List = []
        # monotone topology epoch: bumped on every OWNERSHIP change
        # (shard -> node edge rewired), not on status-only transitions.
        # Carried in the health body and peer responses so stale-routing
        # detection and the plan/results caches key off one counter
        # (ShardMapper.scala versioning analogue).
        self._epoch = 0
        # serializes FSM transitions: update() is called concurrently
        # from the failure-detector poll thread, per-shard ingestion
        # driver threads, membership handoff workers, and HTTP admin
        # threads — an unlocked `_epoch += 1` loses bumps under that
        # interleaving, and a lost bump means two different topologies
        # share an epoch (the plan/results caches would keep serving
        # extents across an ownership rewire). Found by graftlint's
        # thread-unguarded-shared-state inference.
        self._lock = threading.Lock()

    @property
    def topology_epoch(self) -> int:
        with self._lock:
            return self._epoch

    # -- hash-based routing (ShardMapper.scala:93-150) ---------------------
    def ingestion_shard(self, shard_key_hash: int, part_hash: int,
                        spread: int) -> int:
        return ingestion_shard(shard_key_hash, part_hash, spread,
                               self.num_shards)

    def query_shards(self, shard_key_hash: int, spread: int) -> List[int]:
        return query_shards(shard_key_hash, spread, self.num_shards)

    # -- assignment / FSM (updateFromEvent :204) ---------------------------
    def subscribe(self, callback) -> None:
        self._subscribers.append(callback)

    def _publish(self, ev: ShardEvent) -> None:
        for cb in self._subscribers:
            cb(ev)

    # the ONE topology-epoch mutation publisher: every ownership rewire
    # funnels through here (membership handoff, crash reassignment, bus
    # convergence, admin transfer). graftlint's cache-invalidation-
    # completeness rule requires this function to reach every
    # registered cache's topology hook through the subscription chain.
    @publishes("topology-epoch")
    def update(self, shard: int, status: ShardStatus,
               node: Optional[str] = None, progress_pct: int = 0) -> None:
        # the transition (multi-field ShardState write + epoch bump) is
        # atomic under _lock; _publish runs OUTSIDE it — subscribers
        # take their own locks (plan/results-cache invalidation) and
        # must not nest under the mapper's
        with self._lock:
            st = self._states[shard]
            prev_node = st.node
            st.status = status
            if node is not None:
                st.node = node
            if status in (ShardStatus.UNASSIGNED, ShardStatus.STOPPED):
                st.node = None
            if st.node != prev_node:
                self._epoch += 1        # ownership edge rewired
            st.progress_pct = progress_pct
            ev = ShardEvent(shard, status, st.node, progress_pct)
        self._publish(ev)

    def assign(self, shard: int, node: str) -> None:
        self.update(shard, ShardStatus.ASSIGNED, node)

    def activate(self, shard: int) -> None:
        self.update(shard, ShardStatus.ACTIVE)

    def status(self, shard: int) -> ShardStatus:
        return self._states[shard].status

    def node_of(self, shard: int) -> Optional[str]:
        return self._states[shard].node

    def shards_for_node(self, node: str) -> List[int]:
        return [i for i, s in enumerate(self._states) if s.node == node]

    def active_shards(self, shards: Optional[Sequence[int]] = None
                      ) -> List[int]:
        it = shards if shards is not None else range(self.num_shards)
        return [s for s in it if self._states[s].status.queryable]

    def all_queryable(self) -> bool:
        return all(s.status.queryable for s in self._states)

    def unassigned_shards(self) -> List[int]:
        return [i for i, s in enumerate(self._states)
                if s.status is ShardStatus.UNASSIGNED]


def assign_shards_evenly(mapper: ShardMapper, nodes: Sequence[str]) -> None:
    """DefaultShardAssignmentStrategy (ShardAssignmentStrategy.scala:188):
    spread shards as evenly as possible across nodes."""
    if not nodes:
        return
    per = -(-mapper.num_shards // len(nodes))
    for i in range(mapper.num_shards):
        mapper.assign(i, nodes[min(i // per, len(nodes) - 1)])


def shards_for_ordinal(ordinal: int, num_nodes: int, num_shards: int
                       ) -> List[int]:
    """Deterministic k8s-statefulset-ordinal → shards mapping
    (v2 FiloDbClusterDiscovery.scala:50 / K8sStatefulSetShardAssignmentStrategy
    ShardAssignmentStrategy.scala:53)."""
    if not (0 <= ordinal < num_nodes):
        raise ValueError("ordinal out of range")
    per = -(-num_shards // num_nodes)
    lo = ordinal * per
    return list(range(lo, min(lo + per, num_shards)))
