"""Distribution layer: shard→device mapping and mesh scatter-gather.

Replaces the reference's Akka cluster + scatter-gather query trees
(coordinator/ShardMapper.scala, DistConcatExec / ReduceAggregateExec) with a
jax.sharding.Mesh: shards ride the mesh 'shard' (data) axis, output query
steps ride the 'time' (sequence) axis, and the cross-shard aggregation tree
is an XLA collective (psum/pmax) over ICI instead of actor messages.
"""

from filodb_tpu.parallel.shardmapper import ShardMapper, ShardStatus
from filodb_tpu.parallel.mesh import MeshExecutor, pack_sharded
from filodb_tpu.parallel.resilience import (BreakerOpenError,
                                            BreakerRegistry, CircuitBreaker,
                                            Deadline, DeadlineExceeded,
                                            PeerResilience, RetryPolicy,
                                            TransportError)

__all__ = ["ShardMapper", "ShardStatus", "MeshExecutor", "pack_sharded",
           "RetryPolicy", "CircuitBreaker", "BreakerRegistry", "Deadline",
           "PeerResilience", "TransportError", "BreakerOpenError",
           "DeadlineExceeded"]
