"""Mesh scatter-gather execution of windowed range functions + aggregation.

This is the TPU-native replacement for the reference's distributed query tree
(coordinator/queryplanner/SingleClusterPlanner.scala:253 materialize →
per-shard MultiSchemaPartitionsExec leaves dispatched over Akka, gathered by
DistConcatExec / ReduceAggregateExec, AggrOverRangeVectors.scala:98,193
map-reduce):

  * shards ride the mesh **'shard' axis** (horizontal data partitioning —
    one shard's series tile lives on one device slice);
  * output query steps ride the **'time' axis** (sequence/context
    parallelism: each device slice computes a contiguous slice of the
    output step grid — windows only need that device's local series tile,
    which is replicated along 'time');
  * the cross-shard aggregation tree is `psum`/`pmax`/`pmin` over ICI —
    the collective IS ReduceAggregateExec;
  * grouped (`by (...)`) aggregation is a one-hot [S,G] matmul against the
    [S,T] result tile — an MXU op — followed by the same psum.

Wire format between host and device is dense padded tiles from
`pack_sharded` (CSR-ragged series → [shard, S_pad, N_pad]).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

# jax moved shard_map to the top level (and renamed check_rep -> check_vma)
# after 0.4.x; accept either so the mesh executor runs on both
if hasattr(jax, "shard_map"):
    _shard_map_raw = jax.shard_map
else:                                                   # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def _shard_map_raw(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)


def resolve_spec(mesh: "Mesh", spec):
    """Expand positional PartitionSpec indices against ``mesh``.

    Library-level shard_map code (tilestore/shardstore evaluators) names
    mesh axes POSITIONALLY — ``P(0)`` is the first mesh axis, ``P(1)``
    the second, a single ``-1`` the tuple of all axes not otherwise
    mentioned (dropped when empty) — so the evaluator bodies stay
    agnostic to users' axis naming conventions. Newer jax resolves these
    natively; this resolver implements the same semantics on every
    version this repo supports. Out-of-range indices and a repeated
    ``-1`` raise ValueError, mirroring the native behavior."""
    if spec is None:
        return spec
    names = tuple(mesh.axis_names)
    entries = tuple(spec)

    def subaxes(e):
        return tuple(e) if isinstance(e, (tuple, list)) else (e,)

    if not any(isinstance(x, int) for e in entries for x in subaxes(e)):
        return spec

    def name_of(i: int) -> str:
        if not -len(names) <= i < len(names):
            raise ValueError(
                f"positional PartitionSpec index {i} out of range for "
                f"mesh axes {names}")
        return names[i]

    mentioned = set()
    for e in entries:
        for x in subaxes(e):
            if isinstance(x, str):
                mentioned.add(x)
            elif isinstance(x, int) and x != -1:
                mentioned.add(name_of(x))
    neg = sum(1 for e in entries for x in subaxes(e)
              if isinstance(x, int) and x == -1)
    if neg > 1:
        raise ValueError("at most one -1 may appear in a PartitionSpec")
    remaining = tuple(n for n in names if n not in mentioned)
    out = []
    for e in entries:
        if isinstance(e, int):
            if e == -1:
                out.append(remaining if remaining else None)
            else:
                out.append(name_of(e))
        elif isinstance(e, (tuple, list)):
            sub = []
            for x in e:
                if isinstance(x, int):
                    sub.extend(remaining if x == -1 else (name_of(x),))
                else:
                    sub.append(x)
            out.append(tuple(sub))
        else:
            out.append(e)
    return P(*out)


def _resolve_spec_tree(mesh, specs):
    """resolve_spec over a specs pytree (tuples/lists/dicts of P/None)."""
    if specs is None or isinstance(specs, P):
        return resolve_spec(mesh, specs)
    if isinstance(specs, (tuple, list)):
        return tuple(_resolve_spec_tree(mesh, s) for s in specs)
    if isinstance(specs, dict):
        return {k: _resolve_spec_tree(mesh, v) for k, v in specs.items()}
    return specs


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """shard_map with positional-PartitionSpec resolution: mesh-agnostic
    library specs (P(0), P(None, 1), P(-1)) expand against the call's
    mesh before lowering."""
    return _shard_map_raw(f, mesh=mesh,
                          in_specs=_resolve_spec_tree(mesh, in_specs),
                          out_specs=_resolve_spec_tree(mesh, out_specs),
                          check_vma=check_vma)

from filodb_tpu.lint.caches import cache_registry
from filodb_tpu.lint.contracts import kernel_contract
from filodb_tpu.lint.numerics import order_insensitive
from filodb_tpu.query.model import RangeParams, RawSeries
from filodb_tpu.query.tpu import (_GATHER_FUNCS, _TS_PAD, TpuBackend,
                                  _window_endpoint, _window_gather,
                                  _next_pow2, clean_rows)

# Aggregations executable as mesh collectives (AggrOverRangeVectors
# RowAggregator map/reduce protocol, aggregator/RowAggregator.scala:28).
MESH_AGGS = frozenset({"sum", "count", "avg", "min", "max", "group"})


def make_mesh(n_shard_groups: Optional[int] = None,
              time_parallel: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ('shard', 'time') mesh over the available devices.

    n_shard_groups × time_parallel must equal the device count; by default
    all devices go on the shard axis (pure scatter-gather, like the
    reference's one-node-per-shard-group layout)."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    n = devs.size
    if n_shard_groups is None:
        n_shard_groups = n // time_parallel
    if n_shard_groups * time_parallel != n:
        raise ValueError(f"{n_shard_groups}x{time_parallel} != {n} devices")
    return Mesh(devs.reshape(n_shard_groups, time_parallel),
                ("shard", "time"))


def pack_sharded(series_by_shard: Sequence[Sequence[RawSeries]],
                 drop_nan: bool = True,
                 s_pad: Optional[int] = None,
                 n_pad: Optional[int] = None,
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[List[Dict[str, str]]]]:
    """Pack per-shard ragged series into [G, S, N] tiles (G = shard groups).

    Equalizes series-count and sample-count across shards by padding
    (pow2-bucketized so XLA reuses compiled kernels). Padding series have
    len 0 and _TS_PAD timestamps so every kernel treats them as empty."""
    G = len(series_by_shard)
    maxlen, maxs = 1, 1
    cleaned: List[List[Tuple[np.ndarray, np.ndarray]]] = []
    keys: List[List[Dict[str, str]]] = []
    for group in series_by_shard:
        row, ml = clean_rows(group, drop_nan)
        cleaned.append(row)
        keys.append([dict(s.labels) for s in group])
        maxlen = max(maxlen, ml)
        maxs = max(maxs, len(row))
    S = s_pad or _next_pow2(maxs, 1)
    N = n_pad or _next_pow2(maxlen)
    ts_pad = np.full((G, S, N), _TS_PAD, dtype=np.int64)
    vals_pad = np.zeros((G, S, N), dtype=np.float64)
    lens = np.zeros((G, S), dtype=np.int32)
    for g, row in enumerate(cleaned):
        for i, (ts, vals) in enumerate(row):
            n = ts.size
            ts_pad[g, i, :n] = ts
            vals_pad[g, i, :n] = vals
            lens[g, i] = n
    return ts_pad, vals_pad, lens, keys


def _grouped_reduce_check():
    """Abstract check under a minimal 1-device ('shard','time') mesh:
    shard_map traces on CPU, nothing executes."""
    devs = np.asarray(jax.devices()[:1]).reshape(1, 1)
    mesh = Mesh(devs, ("shard", "time"))
    S, T, G = 8, 16, 4
    f = _shard_map(
        lambda loc, g: _grouped_reduce(loc, g, G, "sum"),
        mesh=mesh, in_specs=(P("shard", None), P("shard")),
        out_specs=P(), check_vma=False)
    out = jax.eval_shape(f, jax.ShapeDtypeStruct((S, T), jnp.float64),
                         jax.ShapeDtypeStruct((S,), jnp.int32))
    if tuple(out.shape) != (G, T) or str(out.dtype) != "float64":
        return f"grouped reduce {out.shape}/{out.dtype} != ({G},{T}) f64"
    return None


@order_insensitive(
    "grouped-reduce-psum", tolerance=1e-12,
    reason="the sum/avg family psums f64 per-device partial "
           "aggregates whose grouping follows the shard-axis device "
           "count; each per-device partial is a one-hot matmul of at "
           "most S/n_dev f64 terms, so regrouping moves the result by "
           "at most a few f64 ulps — certified at 1/2/4/8 virtual "
           "devices. min/max ride pmin/pmax (order-free) and counts "
           "are integers in f64 (exact below 2**53)")
@kernel_contract(
    "mesh_grouped_reduce", kind="shard_map",
    check=_grouped_reduce_check,
    notes="per-device one-hot [S,G] matmul / segment min-max, then "
          "psum/pmin/pmax over the 'shard' axis — ReduceAggregateExec "
          "as a collective; requires a ('shard','time') mesh context")
def _grouped_reduce(local: jnp.ndarray, gids: jnp.ndarray, num_groups: int,
                    agg: str) -> jnp.ndarray:
    """[S,T] per-series windowed results + [S] group ids → [G,T] partial
    aggregate for this device, then collective over 'shard'.

    Sum-family runs as a one-hot [S,G] matmul (MXU); min/max as segment
    reductions; NaN (stale/empty) entries contribute nothing. Mean is
    sum/count reduced separately (AvgRowAggregator keeps (mean, count)
    pairs — same math, batched).

    Padding rows carry the sentinel gid -1: their one-hot row is all-zero
    and their entries are masked out, so functions that map empty rows to
    non-NaN values (absent_over_time -> 1.0) cannot contaminate group 0,
    while a REAL series with zero samples still aggregates normally."""
    valid = (gids >= 0)[:, None]                       # [S, 1]
    ok = ~jnp.isnan(local) & valid
    local = jnp.where(valid, local, jnp.nan)
    gids = jnp.where(valid[:, 0], gids, 0)
    onehot = ((gids[:, None] == jnp.arange(num_groups)[None, :])
              & valid).astype(local.dtype)             # [S, G]
    cnt = onehot.T @ ok.astype(local.dtype)            # [G, T]
    cnt = jax.lax.psum(cnt, "shard")
    if agg == "count":
        return jnp.where(cnt > 0, cnt, jnp.nan)
    if agg == "group":
        return jnp.where(cnt > 0, 1.0, jnp.nan)
    if agg in ("sum", "avg"):
        s = jax.lax.psum(onehot.T @ jnp.where(ok, local, 0.0), "shard")
        if agg == "avg":
            s = s / cnt
        return jnp.where(cnt > 0, s, jnp.nan)
    if agg in ("min", "max"):
        big = jnp.inf if agg == "min" else -jnp.inf
        masked = jnp.where(ok, local, big)              # [S, T]
        segf = jax.ops.segment_min if agg == "min" else jax.ops.segment_max
        red = segf(masked, gids, num_segments=num_groups)  # [G, T]
        red = (jax.lax.pmin if agg == "min" else jax.lax.pmax)(red, "shard")
        return jnp.where(cnt > 0, red, jnp.nan)
    raise ValueError(f"unhandled mesh agg {agg}")


# cache inventory: the cached_property executables (_step/_step_topk)
# close over ONE mesh instance and specialize per static kernel shape —
# world-independent by construction; a topology change builds a new
# executor, never mutates this one
@cache_registry("mesh-executable", keyed=("mesh", "kernel-shape"))
class MeshExecutor:
    """Distributed query step executor over a ('shard','time') mesh.

    The single entry point `window_aggregate` fuses the reference's whole
    per-query pipeline below the planner — SelectRawPartitions (already
    packed) → PeriodicSamplesMapper → AggregateMapReduce → ReduceAggregate
    — into one pjit'd program with collectives."""

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        # first-sight keys for compile-event telemetry (one executor is
        # driven by one query engine call at a time; races would only
        # double-count a build event)
        self._exec_seen: set = set()

    @functools.cached_property
    def _step(self):
        mesh = self.mesh

        @functools.partial(
            jax.jit,
            static_argnames=("func", "agg", "num_groups", "nsteps_local",
                             "w_bound"))
        def run(func, agg, num_groups, nsteps_local, w_bound, ts, vals,
                lens, gids, w0s, w0e, step, scalar):
            @functools.partial(
                _shard_map, mesh=mesh,
                in_specs=(P("shard", None, None), P("shard", None, None),
                          P("shard", None), P("shard", None),
                          P(), P(), P(), P()),
                out_specs=P(None, "time"))
            def inner(ts, vals, lens, gids, w0s, w0e, step, sc):
                # local tiles arrive [G_local, S, N]; collapse shard groups
                gl, S, N = ts.shape
                ts2, vals2 = ts.reshape(gl * S, N), vals.reshape(gl * S, N)
                lens2, gids2 = lens.reshape(-1), gids.reshape(-1)
                # this device's slice of the step grid (sequence parallel)
                t_off = jax.lax.axis_index("time").astype(
                    jnp.int64) * nsteps_local * step
                if func in _GATHER_FUNCS:
                    local = _window_gather(func, w_bound, ts2, vals2, lens2,
                                           w0s + t_off, w0e + t_off, step,
                                           nsteps_local, sc)   # [S_l, T_l]
                else:
                    local = _window_endpoint(func, ts2, vals2, lens2,
                                             w0s + t_off, w0e + t_off, step,
                                             nsteps_local, sc)
                return _grouped_reduce(local, gids2, num_groups,
                                       agg)                    # [G, T_l]
            return inner(ts, vals, lens, gids,
                         jnp.asarray(w0s, jnp.int64),
                         jnp.asarray(w0e, jnp.int64),
                         jnp.asarray(step, jnp.int64),
                         jnp.asarray(scalar, dtype=jnp.float64))
        return run


    def _prepare_inputs(self, series_by_shard, params, func, window_ms,
                        group_ids_by_shard, offset_ms):
        """Shared packing/padding prologue for the windowed mesh entry
        points: [G,S,N] tiles, padded gid table, step-grid scalars and the
        static per-window sample bound."""
        n_shard = self.mesh.shape["shard"]
        n_time = self.mesh.shape["time"]
        if len(series_by_shard) % n_shard:
            raise ValueError("shard groups must divide mesh shard axis")
        ts, vals, lens, _ = pack_sharded(series_by_shard,
                                         drop_nan=(func != "last_sample"))
        G, S, _ = ts.shape
        gids = np.full((G, S), -1, dtype=np.int32)   # -1 marks padding rows
        for g, row in enumerate(group_ids_by_shard):
            gids[g, :len(row)] = row
        steps = params.steps
        T = steps.size
        T_pad = -(-T // n_time) * n_time
        step = np.int64(params.step_ms if T > 1 else 1)
        w0e = np.int64(steps[0] - offset_ms)
        w0s = np.int64(w0e - window_ms)
        w_bound = 0
        if func in _GATHER_FUNCS:
            all_series = [s for row in series_by_shard for s in row]
            w_bound = TpuBackend._window_sample_bound(
                all_series, window_ms, ts.shape[2])
        return (ts, vals, lens, gids, T, T_pad // n_time, step, w0s, w0e,
                w_bound, S)

    @functools.cached_property
    def _step_topk(self):
        mesh = self.mesh

        @functools.partial(
            jax.jit,
            static_argnames=("func", "num_groups", "k", "bottom",
                            "nsteps_local", "w_bound"))
        def run(func, num_groups, k, bottom, nsteps_local, w_bound, ts,
                vals, lens, gids, w0s, w0e, step, scalar):
            @functools.partial(
                _shard_map, mesh=mesh,
                in_specs=(P("shard", None, None), P("shard", None, None),
                          P("shard", None), P("shard", None),
                          P(), P(), P(), P()),
                out_specs=(P(None, "time", None), P(None, "time", None)),
                # outputs ARE shard-replicated (derived from an all_gather
                # over 'shard') but the static checker can't prove it
                check_vma=False)
            def inner(ts, vals, lens, gids, w0s, w0e, step, sc):
                gl, S, N = ts.shape
                ts2, vals2 = ts.reshape(gl * S, N), vals.reshape(gl * S, N)
                lens2, gids2 = lens.reshape(-1), gids.reshape(-1)
                t_off = jax.lax.axis_index("time").astype(
                    jnp.int64) * nsteps_local * step
                if func in _GATHER_FUNCS:
                    local = _window_gather(func, w_bound, ts2, vals2, lens2,
                                           w0s + t_off, w0e + t_off, step,
                                           nsteps_local, sc)
                else:
                    local = _window_endpoint(func, ts2, vals2, lens2,
                                             w0s + t_off, w0e + t_off, step,
                                             nsteps_local, sc)
                # per-group per-step local top-k, then a cross-shard
                # all_gather + re-top-k — the TopBottomK reduce tree as a
                # collective (aggregator TopBottomKRowAggregator)
                sign = -1.0 if bottom else 1.0
                score = jnp.where(jnp.isnan(local), -jnp.inf, sign * local)
                score = jnp.where((gids2 >= 0)[:, None], score, -jnp.inf)
                dev = jax.lax.axis_index("shard").astype(jnp.int32)
                row_ids = dev * (gl * S) + jnp.arange(gl * S,
                                                      dtype=jnp.int32)
                ong = gids2[None, :] == jnp.arange(num_groups)[:, None]
                sc_g = jnp.where(ong[:, :, None], score[None, :, :],
                                 -jnp.inf)              # [G, S_l, T_l]
                sc_t = jnp.transpose(sc_g, (0, 2, 1))   # [G, T_l, S_l]
                kk = min(k, sc_t.shape[-1])
                top_v, top_i = jax.lax.top_k(sc_t, kk)
                top_ids = row_ids[top_i]
                if kk < k:
                    pad = sc_t.shape[:2] + (k - kk,)
                    top_v = jnp.concatenate(
                        [top_v, jnp.full(pad, -jnp.inf)], -1)
                    top_ids = jnp.concatenate(
                        [top_ids, jnp.full(pad, -1, jnp.int32)], -1)
                all_v = jax.lax.all_gather(top_v, "shard")
                all_ids = jax.lax.all_gather(top_ids, "shard")
                n_sh = all_v.shape[0]
                cat_v = jnp.transpose(all_v, (1, 2, 0, 3)).reshape(
                    num_groups, -1, n_sh * k)
                cat_i = jnp.transpose(all_ids, (1, 2, 0, 3)).reshape(
                    num_groups, -1, n_sh * k)
                fin_v, slot = jax.lax.top_k(cat_v, k)   # [G, T_l, k]
                fin_ids = jnp.take_along_axis(cat_i, slot, axis=-1)
                ok = jnp.isfinite(fin_v)
                return (jnp.where(ok, sign * fin_v, jnp.nan),
                        jnp.where(ok, fin_ids, -1))
            return inner(ts, vals, lens, gids,
                         jnp.asarray(w0s, jnp.int64),
                         jnp.asarray(w0e, jnp.int64),
                         jnp.asarray(step, jnp.int64),
                         jnp.asarray(scalar, dtype=jnp.float64))
        return run

    def window_topk(self,
                    series_by_shard: Sequence[Sequence[RawSeries]],
                    params: RangeParams,
                    function: str,
                    window_ms: int,
                    k: int,
                    bottom: bool,
                    group_ids_by_shard: Sequence[Sequence[int]],
                    num_groups: int,
                    func_args: Sequence[float] = (),
                    offset_ms: int = 0):
        """topk/bottomk over the mesh. Returns (values [G, T, k],
        row_ids [G, T, k], S_pad) — row_id // S_pad is the shard group,
        row_id % S_pad the series index within it (-1 = empty slot)."""
        func = function or "last_sample"
        if params.steps.size == 0:
            return (np.empty((num_groups, 0, k)),
                    np.full((num_groups, 0, k), -1, np.int32), 1)
        (ts, vals, lens, gids, T, t_local, step, w0s, w0e, w_bound,
         S) = self._prepare_inputs(series_by_shard, params, func,
                                   window_ms, group_ids_by_shard,
                                   offset_ms)
        sc = float(func_args[0]) if func_args else 0.0
        self._note_exec(
            ("topk", func, int(k), bool(bottom), t_local,
             tuple(ts.shape), self.ndev),
            probe=self._cost_probe(self._step_topk,
                                   (func, num_groups, int(k),
                                    bool(bottom), t_local, w_bound),
                                   (ts, vals, lens, gids),
                                   (w0s, w0e, step, sc)))
        out_v, out_i = self._step_topk(
            func, num_groups, int(k), bool(bottom), t_local,
            w_bound, ts, vals, lens, gids, w0s, w0e, step, sc)
        return np.asarray(out_v)[:, :T], np.asarray(out_i)[:, :T], S

    def window_aggregate(self,
                         series_by_shard: Sequence[Sequence[RawSeries]],
                         params: RangeParams,
                         function: str,
                         window_ms: int,
                         agg: str,
                         group_ids_by_shard: Sequence[Sequence[int]],
                         num_groups: int,
                         func_args: Sequence[float] = (),
                         offset_ms: int = 0) -> np.ndarray:
        """Returns the [num_groups, T] aggregated grid."""
        if agg not in MESH_AGGS:
            raise ValueError(f"agg {agg} not mesh-executable")
        func = function or "last_sample"
        if params.steps.size == 0:
            return np.empty((num_groups, 0), dtype=np.float64)
        (ts, vals, lens, gids, T, t_local, step, w0s, w0e, w_bound,
         _) = self._prepare_inputs(series_by_shard, params, func,
                                   window_ms, group_ids_by_shard,
                                   offset_ms)
        sc = float(func_args[0]) if func_args else 0.0
        self._note_exec(
            ("agg", func, agg, t_local, tuple(ts.shape), self.ndev),
            probe=self._cost_probe(self._step,
                                   (func, agg, num_groups, t_local,
                                    w_bound),
                                   (ts, vals, lens, gids),
                                   (w0s, w0e, step, sc)))
        out = self._step(func, agg, num_groups,
                         t_local, w_bound, ts, vals, lens, gids,
                         w0s, w0e, step, sc)
        return np.asarray(out)[:, :T]

    @property
    def ndev(self) -> int:
        """Device count of the executor's mesh — the per-(kernel,
        device-count) attribution atom every mesh executable key
        carries, so /metrics and &explain=analyze show 1/2/4/8-device
        compiles of the same kernel as distinct executables."""
        return int(self.mesh.devices.size)

    @staticmethod
    def _cost_probe(jitted, statics, arrays, scalars):
        """() -> Compiled lazy cost probe over the abstract signature
        (the tilestore AOT pattern, deferred: the first
        &explain=analyze touching the executable pays the compile,
        serving dispatches never do). Closes over ShapeDtypeStructs,
        never the tiles themselves."""
        abstract = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                         for a in (np.asarray(x) for x in arrays))
        consts = tuple(np.asarray(s) for s in scalars)

        def probe():
            return jitted.lower(*statics, *abstract, *consts).compile()
        return probe

    def _note_exec(self, key, probe=None) -> None:
        """Compile/dispatch telemetry for the mesh-executable cache
        (obs/devprof.py): per (kernel, static shape, device count) key
        — first sight is the shard_map trace + pjit compile (and
        registers the lazy cost probe for XLA cost_analysis capture),
        later dispatches reuse the jit cache. Feeds the
        filodb_executable_* families and the &explain=analyze
        executable attribution."""
        from filodb_tpu.obs import devprof
        first = key not in self._exec_seen
        if first:
            self._exec_seen.add(key)
        devprof.note_dispatch("mesh", key, first,
                              probe=probe if first else None)
