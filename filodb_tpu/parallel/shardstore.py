"""Device-resident sharded tile serving: the multi-chip query hot path.

The scatter-gather mesh executor (parallel/mesh.py) re-packs and
re-ships every query's series to the devices — fine for a dry run,
hopeless as a serving path (the pack dominates at production shapes).
This module makes the SHARDED tile store the thing queries dispatch
from: the aligned tile store's slot-major channels
(query/tilestore.py AlignedTiles) are placed ONCE across the
('shard', 'time') mesh — series ride the shard axis, each device holds
its S/n_shard slice of every [N, S] channel resident in HBM — and the
slot-major counter evaluator plus the grid-batched evaluator families
lower through ``shard_map``:

  * per-series windowed evaluation (``_eval_counter_fast`` /
    ``_eval_core`` — the SAME traceable bodies the single-device
    dispatch compiles, so member (t, s) of the sharded output is
    bit-for-bit the single-device value): output step-grid slices ride
    the time axis, series slices the shard axis;
  * grouped aggregation keeps the one-hot [S, G] matmul + ``psum``
    collective of the scatter-gather path (mesh._grouped_reduce) but
    feeds it from the resident tiles;
  * PartitionSpecs are POSITIONAL (mesh.resolve_spec): ``P(None, 0)``
    = replicated slots x first-mesh-axis series, ``P(1, 0)`` = steps on
    the second axis x series on the first — the evaluator code never
    names an axis, so it runs unchanged on any user mesh shape;
  * cross-flush tile refreshes are ZERO-COPY in HBM: the slot channels
    are capacity-padded and a flush appends its new slot columns via a
    ``donate_argnums`` jit (``_append_step``) — the donated buffers are
    reused in place, no re-placement, no second copy of a multi-GB
    store during rebuild.

Escape hatches: tiles must be dense (every slot valid) with the tile
span in int32 ms — exactly the fast-family eligibility of the
single-device dispatcher — and a query whose grid leaves the int32
range (or whose tiles never qualified) falls back to the single-device
tilestore path unchanged.
"""

from __future__ import annotations

import functools
import threading
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from filodb_tpu.lint.caches import cache_registry
from filodb_tpu.lint.capacity import (capacity, drop_resident,
                                      ensure_residency_collector,
                                      record_resident)
from filodb_tpu.lint.contracts import kernel_contract
from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.numerics import order_insensitive, precision
from filodb_tpu.parallel.mesh import (_grouped_reduce, _shard_map, make_mesh,
                                      resolve_spec)

# cache inventory (graftlint): the sharded-evaluator dispatch table
# memoizes compiled shard_map programs keyed purely on (kernel family,
# func, step shape, mesh shape) — a pure function of the request shape
# and device topology, immune to every world event by construction
__cache_registry__ = {
    "shardstore-executables": {"keyed": ("kernel", "func", "shape-bucket",
                                         "mesh-shape")},
}

_SHARD_EVAL_JIT: Dict[Tuple, object] = {}


def _jit_lookup(key: Tuple, build, cost_args=None):
    """Dispatch-table lookup through the tilestore's profiled builder:
    miss-side builds compile AOT with XLA cost_analysis capture
    (obs/devprof.py), so every sharded executable shows up in
    filodb_executable_* and &explain=analyze keyed by (kernel,
    device-count)."""
    from filodb_tpu.query import tilestore as tst
    return tst._jit_lookup(_SHARD_EVAL_JIT, key, build,
                           site="mesh-tiles", cost_args=cost_args)


# ---------------------------------------------------------------------------
# Donated refresh step
# ---------------------------------------------------------------------------

@precision(
    "append-carry-exact", bits=53, rel_ulps=0,
    reason="the donated append extends the counter-corrected channel "
           "in exact f64: absent counter resets in the appended block "
           "the carry and cumsum terms are all zero, so the refreshed "
           "channel is BITWISE the from-scratch rebuild (certified); "
           "with resets the carry value itself is still exact, only "
           "the add order differs from a rebuild")
@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _append_step(tsr, v, cv, new_tsr, new_v, n_filled):
    """Zero-copy slot append: write a flush's new slot columns into the
    capacity-padded channels IN PLACE (the donated buffers are reused
    by XLA — no second copy of the store in HBM during a refresh).

    The counter-corrected channel extends exactly like a full rebuild:
    the correction carry at the append point is read off the resident
    buffers (``cv[n-1] - v[n-1]``), the previous-sample chain starts at
    the last resident row, and drops accumulate through the appended
    block — so rate/increase over the refreshed store match a
    from-scratch rebuild (bit-for-bit when the appended span carries no
    counter resets; the carry is the same value either way)."""
    prev0 = jax.lax.dynamic_slice_in_dim(v, n_filled - 1, 1, axis=0)
    corr0 = jax.lax.dynamic_slice_in_dim(cv, n_filled - 1, 1, axis=0) - prev0
    prevs = jnp.concatenate([prev0, new_v[:-1]], axis=0)
    drop = new_v < prevs
    new_cv = new_v + jnp.cumsum(jnp.where(drop, prevs, 0.0), axis=0) + corr0
    tsr = jax.lax.dynamic_update_slice_in_dim(tsr, new_tsr, n_filled, axis=0)
    v = jax.lax.dynamic_update_slice_in_dim(v, new_v, n_filled, axis=0)
    cv = jax.lax.dynamic_update_slice_in_dim(cv, new_cv, n_filled, axis=0)
    return tsr, v, cv


# ---------------------------------------------------------------------------
# Sharded evaluator programs (compiled per (func, grid shape, mesh shape))
# ---------------------------------------------------------------------------

def _sharded_counter_check():
    """Abstract check under a minimal 1x1 ('shard','time') mesh: the
    shard_map body traces on CPU, nothing executes."""
    from filodb_tpu.query.tilestore import _eval_counter_fast  # noqa: F401
    devs = np.asarray(jax.devices()[:1]).reshape(1, 1)
    mesh = Mesh(devs, ("shard", "time"))
    fn = _build_counter_eval(mesh, "rate", 16, batch=0)
    out = jax.eval_shape(
        fn, jax.ShapeDtypeStruct((64, 8), jnp.int32),
        jax.ShapeDtypeStruct((64, 8), jnp.float64),
        np.int64(64), np.int64(0), np.int64(10_000),
        np.int64(100_000), np.int64(400_000), np.int64(60_000))
    if tuple(out.shape) != (16, 8) or str(out.dtype) != "float32":
        return f"sharded counter eval {out.shape}/{out.dtype} != (16,8) f32"
    return None


@kernel_contract(
    "sharded_counter_eval", kind="shard_map",
    check=_sharded_counter_check,
    rel_time_bits=31, span_guard="ShardedTiles.query_fits",
    notes="slot-major counter fast path lowered over the ('shard','time')"
          " mesh from device-resident sharded tiles; positional "
          "PartitionSpecs, per-device step-grid slices via axis_index; "
          "bit-for-bit the single-device _eval_counter_fast values")
def _build_counter_eval(mesh: Mesh, func: str, nsteps_local: int,
                        batch: int):
    """One jitted sharded program: [N, S] resident channels ->
    [T, S] (batch == 0) or [B, T, S] (batch == B) windowed counter
    grids. ``batch`` members vmap over the grid scalars exactly like
    the single-device evaluate_counters_t_batch family."""
    from filodb_tpu.query.tilestore import _eval_counter_fast

    t_axis = mesh.axis_names[1]

    def counter_body(tsr, vv, n, base, dt, w0s, w0e, step):
        # this device's slice of the output step grid rides the time
        # axis (sequence parallel): offset the window scalars
        t_off = (jax.lax.axis_index(t_axis).astype(jnp.int64)
                 * nsteps_local * step)
        arrs = {"tsr": tsr, "ff_v": vv}
        ev = functools.partial(_eval_counter_fast, func, nsteps_local,
                               arrs, n, base, dt)
        if batch:
            return jax.vmap(lambda a, b: ev(a + t_off, b + t_off,
                                            step))(w0s, w0e)
        return ev(w0s + t_off, w0e + t_off, step)

    if batch:
        @jax.jit
        def run_b(tsr, vv, n, base, dt, w0s, w0e, step):
            inner = _shard_map(
                counter_body, mesh=mesh,
                in_specs=(P(None, 0), P(None, 0), P(), P(), P(),
                          P(None), P(None), P()),
                out_specs=P(None, 1, 0))
            return inner(tsr, vv, n, base, dt, w0s, w0e, step)
        return run_b

    @jax.jit
    def run(tsr, vv, n, base, dt, w0s, w0e, step):
        inner = _shard_map(
            counter_body, mesh=mesh,
            in_specs=(P(None, 0), P(None, 0), P(), P(), P(),
                      P(), P(), P()),
            out_specs=P(1, 0))
        return inner(tsr, vv, n, base, dt, w0s, w0e, step)
    return run


def _build_aligned_eval(mesh: Mesh, func: str, nsteps_local: int,
                        batch: int, arr_keys: Tuple[Tuple[str, int], ...]):
    """Sharded program for the non-counter aligned families: the SAME
    _eval_core body as the single-device dispatch, series on the shard
    axis, output steps on the time axis -> [S, T] f64 (or [B, S, T]).
    ``arr_keys`` is the channel-set signature ((name, ndim), ...)."""
    from filodb_tpu.query.tilestore import _eval_core

    t_axis = mesh.axis_names[1]
    arr_specs = {k: (P(0) if nd == 1 else P(0, None))
                 for k, nd in arr_keys}

    def aligned_body(arrs, n, base, dt, w0s, w0e, step):
        t_off = (jax.lax.axis_index(t_axis).astype(jnp.int64)
                 * nsteps_local * step)
        ev = functools.partial(_eval_core, func, nsteps_local, arrs,
                               n, base, dt)
        if batch:
            return jax.vmap(lambda a, b: ev(a + t_off, b + t_off,
                                            step))(w0s, w0e)
        return ev(w0s + t_off, w0e + t_off, step)

    if batch:
        @jax.jit
        def run_b(arrs, n, base, dt, w0s, w0e, step):
            inner = _shard_map(
                aligned_body, mesh=mesh,
                in_specs=(arr_specs, P(), P(), P(),
                          P(None), P(None), P()),
                out_specs=P(None, 0, 1))
            return inner(arrs, n, base, dt, w0s, w0e, step)
        return run_b

    @jax.jit
    def run(arrs, n, base, dt, w0s, w0e, step):
        inner = _shard_map(
            aligned_body, mesh=mesh,
            in_specs=(arr_specs, P(), P(), P(), P(), P(), P()),
            out_specs=P(0, 1))
        return inner(arrs, n, base, dt, w0s, w0e, step)
    return run


@order_insensitive(
    "grouped-pair-psum", tolerance=1e-12,
    reason="sums and counts are f64 per-device one-hot matmul "
           "partials psummed over the shard axis; regrouping across "
           "device counts moves the sums by at most a few f64 ulps "
           "(counts are exact integers in f64) — certified at "
           "1/2/4/8 virtual devices")
def _build_grouped_pair_eval(mesh: Mesh, func: str, nsteps_local: int,
                             num_groups: int):
    """The fused-groupsum contract from resident tiles: per-device
    windowed counter evaluation + one-hot matmul, psum over the shard
    axis -> (sums [T, G], counts [T, G]) f64 — sums meaningful where
    counts > 0, exactly the Pallas group-sum kernel's return shape."""
    from filodb_tpu.query.tilestore import _eval_counter_fast

    s_axis = mesh.axis_names[0]
    t_axis = mesh.axis_names[1]

    def grouped_pair_body(tsr, vv, gids, n, base, dt, w0s, w0e, step):
        t_off = (jax.lax.axis_index(t_axis).astype(jnp.int64)
                 * nsteps_local * step)
        arrs = {"tsr": tsr, "ff_v": vv}
        local = _eval_counter_fast(func, nsteps_local, arrs, n, base,
                                   dt, w0s + t_off, w0e + t_off, step)
        valid = (gids >= 0)
        ok = ~jnp.isnan(local) & valid[None, :]
        onehot = ((gids[:, None] == jnp.arange(num_groups)[None, :])
                  & valid[:, None]).astype(jnp.float64)      # [S_l, G]
        sums = jnp.where(ok, local, 0.0).astype(jnp.float64) @ onehot
        cnts = ok.astype(jnp.float64) @ onehot               # [T_l, G]
        return (jax.lax.psum(sums, s_axis), jax.lax.psum(cnts, s_axis))

    @jax.jit
    def run(tsr, vv, gids, n, base, dt, w0s, w0e, step):
        inner = _shard_map(
            grouped_pair_body, mesh=mesh,
            in_specs=(P(None, 0), P(None, 0), P(0), P(), P(), P(), P(),
                      P(), P()),
            out_specs=(P(1, None), P(1, None)))
        return inner(tsr, vv, gids, n, base, dt, w0s, w0e, step)
    return run


def _build_grouped_eval(mesh: Mesh, func: str, nsteps_local: int,
                        num_groups: int, agg: str):
    """Grouped counter aggregation from resident tiles: per-device
    windowed evaluation, then the one-hot [S, G] matmul + psum
    collective (mesh._grouped_reduce — ReduceAggregateExec as a
    collective) -> [G, T]."""
    from filodb_tpu.query.tilestore import _eval_counter_fast

    t_axis = mesh.axis_names[1]

    @functools.partial(jax.jit, static_argnames=("agg",))
    def run(agg, tsr, vv, gids, n, base, dt, w0s, w0e, step):
        def grouped_body(tsr, vv, gids, n, base, dt, w0s, w0e, step):
            t_off = (jax.lax.axis_index(t_axis).astype(jnp.int64)
                     * nsteps_local * step)
            arrs = {"tsr": tsr, "ff_v": vv}
            local = _eval_counter_fast(func, nsteps_local, arrs, n,
                                       base, dt, w0s + t_off,
                                       w0e + t_off, step)
            return _grouped_reduce(local.T.astype(jnp.float64), gids,
                                   num_groups, agg)
        inner = _shard_map(
            grouped_body, mesh=mesh,
            in_specs=(P(None, 0), P(None, 0), P(0), P(), P(), P(), P(),
                      P(), P()),
            out_specs=P(None, 1))
        return inner(tsr, vv, gids, n, base, dt, w0s, w0e, step)
    return run


# ---------------------------------------------------------------------------
# The resident store
# ---------------------------------------------------------------------------

def _next_pow2(n: int, lo: int = 8) -> int:
    p = lo
    while p < n:
        p <<= 1
    return p


@capacity(
    "shardstore-resident-channels", bytes_per_sample=20.0, sharded=True,
    reason="the resident store keeps three [cap, S_pad] slot-major "
           "channels — int32 relative timestamps (4 B) + raw f64 "
           "values (8 B) + counter-corrected f64 values (8 B) = 20 B "
           "per PADDED slot (pow2 slot capacity, shard-aligned series "
           "pad); the non-counter _aligned placements are transient "
           "per-family row sets cleared on every refresh")
class ShardedTiles:
    """One aligned-tile cohort resident across the mesh: capacity-padded
    [cap, S_pad] slot-major channels (int32 relative timestamps, raw
    values, counter-corrected values), series sharded over the first
    mesh axis. Immutable except through :meth:`append_slots` (the
    donated refresh)."""

    def __init__(self, mesh: Mesh, tiles) -> None:
        self.mesh = mesh
        self.base_ms = int(tiles.base_ms)
        self.dt_ms = int(tiles.dt_ms)
        self.keys = list(tiles.keys)
        S = len(self.keys)
        N = int(tiles.num_slots)
        n_shard = int(mesh.shape[mesh.axis_names[0]])
        self.n_time = int(mesh.shape[mesh.axis_names[1]])
        self.S = S
        self.S_pad = -(-S // n_shard) * n_shard
        self.cap = _next_pow2(N, 64)
        self.n_filled = N
        col = NamedSharding(mesh, resolve_spec(mesh, P(None, 0)))
        self._col_sharding = col

        def place(host_nx_s, dtype):
            buf = np.zeros((self.cap, self.S_pad), dtype=dtype)
            buf[:N, :S] = host_nx_s
            return jax.device_put(buf, col)

        ts = np.asarray(tiles.ts, dtype=np.float64)             # [S, N]
        self._tsr = place((ts - self.base_ms).T.astype(np.int32), np.int32)
        v = np.asarray(tiles.channel("v"), dtype=np.float64)
        self._v = place(v.T, np.float64)
        cv = np.asarray(tiles.channel("cv"), dtype=np.float64)
        self._cv = place(cv.T, np.float64)
        # non-counter aligned channel placements, per function family
        self._aligned: Dict[Tuple, Dict[str, jnp.ndarray]] = {}
        # runtime residency accounting: live device bytes under the
        # filodb_device_memory_bytes{family,shard} gauge, dropped when
        # the store is collected
        ensure_residency_collector()
        self._res_key = ("shardstore-resident-channels", str(n_shard),
                         id(self))
        weakref.finalize(self, drop_resident, *self._res_key)
        self._record_residency()

    def _record_residency(self) -> None:
        nbytes = int(self._tsr.nbytes + self._v.nbytes + self._cv.nbytes)
        nbytes += sum(int(a.nbytes) for placed in self._aligned.values()
                      for a in placed.values())
        record_resident(*self._res_key, nbytes)

    # -- eligibility -------------------------------------------------------

    @staticmethod
    def tiles_eligible(tiles) -> bool:
        """Build-time gate, mirroring the single-device fast-family
        guard: dense tiles whose whole span fits int32 ms."""
        from filodb_tpu.query.tilestore import _SENT_HI
        return (tiles is not None and tiles._dense
                and len(tiles.keys) > 0
                and tiles.num_slots * tiles.dt_ms + tiles.dt_ms < _SENT_HI)

    def query_fits(self, steps: np.ndarray, window_ms: int,
                   offset_ms: int) -> bool:
        """Per-query span guard: the grid must sit in int32 ms relative
        to the tile base (the dispatcher's fits_i32 condition) — wider
        grids take the single-device exact-f64 path."""
        from filodb_tpu.query.tilestore import _SENT_HI, _SENT_LO
        if steps.size == 0:
            return False
        w0s = int(steps[0] - offset_ms) - window_ms
        return (_SENT_LO < w0s - self.base_ms
                and int(steps[-1] - offset_ms) - self.base_ms < _SENT_HI)

    def _grid(self, steps: np.ndarray, window_ms: int, offset_ms: int):
        nsteps = steps.size
        T_pad = -(-nsteps // self.n_time) * self.n_time
        w0e = np.int64(steps[0] - offset_ms)
        w0s = np.int64(w0e - window_ms)
        step = np.int64(steps[1] - steps[0]) if nsteps > 1 else np.int64(1)
        return T_pad // self.n_time, w0s, w0e, step

    def _mesh_key(self) -> Tuple:
        return (int(self.mesh.shape[self.mesh.axis_names[0]]),
                self.n_time, int(self.mesh.devices.size))

    # -- evaluation --------------------------------------------------------

    def eval_counters(self, func: str, steps: np.ndarray, window_ms: int,
                      offset_ms: int = 0) -> jnp.ndarray:
        """rate/increase/delta from the resident store -> device
        [T, S] f32 (callers slice/transpose; values bit-for-bit the
        single-device fast-path's)."""
        t_local, w0s, w0e, step = self._grid(steps, window_ms, offset_ms)
        vv = self._cv if func in ("rate", "increase") else self._v
        args = (self._tsr, vv, np.int64(self.n_filled),
                np.int64(self.base_ms), np.int64(self.dt_ms), w0s, w0e,
                step)
        key = ("mesh-fast", func, t_local, self._mesh_key())
        fn = _jit_lookup(key, lambda: _build_counter_eval(
            self.mesh, func, t_local, batch=0), cost_args=args)
        return fn(*args)[:steps.size, :self.S]

    def eval_counters_batch(self, func: str, nsteps: int, step: int,
                            w0s_list: Sequence[int],
                            w0e_list: Sequence[int]) -> jnp.ndarray:
        """One sharded dispatch computing B counter grids -> device
        [B_pad, T, S] (callers slice [:len(w0s_list)]) — the
        mesh-shaped micro-batch."""
        from filodb_tpu.query.tilestore import _pad_pow2
        w0s_v = jnp.asarray(_pad_pow2(list(w0s_list)))
        w0e_v = jnp.asarray(_pad_pow2(list(w0e_list)))
        b_pad = int(w0s_v.shape[0])
        T_pad = -(-nsteps // self.n_time) * self.n_time
        t_local = T_pad // self.n_time
        vv = self._cv if func in ("rate", "increase") else self._v
        args = (self._tsr, vv, np.int64(self.n_filled),
                np.int64(self.base_ms), np.int64(self.dt_ms), w0s_v,
                w0e_v, np.int64(step))
        key = ("mesh-fast-b", func, t_local, b_pad, self._mesh_key())
        fn = _jit_lookup(key, lambda: _build_counter_eval(
            self.mesh, func, t_local, batch=b_pad), cost_args=args)
        return fn(*args)[:, :nsteps, :self.S]

    def _aligned_arrs(self, tiles, func: str) -> Dict[str, jnp.ndarray]:
        """Sharded placement of the row-major channel set ``func``
        needs (query/tilestore._tiles_arrays), cached per channel-set
        signature."""
        from filodb_tpu.query.tilestore import _tiles_arrays
        arrs = _tiles_arrays(tiles, func)
        key = tuple(sorted(arrs))
        placed = self._aligned.get(key)
        if placed is None:
            row = NamedSharding(self.mesh, resolve_spec(self.mesh, P(0)))
            row2 = NamedSharding(self.mesh,
                                 resolve_spec(self.mesh, P(0, None)))
            placed = {}
            for k, a in arrs.items():
                h = np.asarray(a)
                pad = self.S_pad - h.shape[0]
                if pad:
                    h = np.concatenate(
                        [h, np.zeros((pad,) + h.shape[1:], h.dtype)])
                placed[k] = jax.device_put(h, row if h.ndim == 1 else row2)
            self._aligned[key] = placed
            self._record_residency()
        return placed

    def eval_aligned(self, tiles, func: str, steps: np.ndarray,
                     window_ms: int, offset_ms: int = 0) -> jnp.ndarray:
        """Non-counter aligned families from sharded channels ->
        device [S, T] f64, bit-for-bit the single-device _eval_core."""
        t_local, w0s, w0e, step = self._grid(steps, window_ms, offset_ms)
        arrs = self._aligned_arrs(tiles, func)
        sig = tuple(sorted((k, v.ndim) for k, v in arrs.items()))
        args = (arrs, np.int64(self.n_filled), np.int64(self.base_ms),
                np.int64(self.dt_ms), w0s, w0e, step)
        key = ("mesh-aligned", func, t_local, sig, self._mesh_key())
        fn = _jit_lookup(key, lambda: _build_aligned_eval(
            self.mesh, func, t_local, 0, sig), cost_args=args)
        return fn(*args)[:self.S, :steps.size]

    def eval_aligned_batch(self, tiles, func: str, nsteps: int, step: int,
                           w0s_list: Sequence[int],
                           w0e_list: Sequence[int]) -> jnp.ndarray:
        from filodb_tpu.query.tilestore import _pad_pow2
        w0s_v = jnp.asarray(_pad_pow2(list(w0s_list)))
        w0e_v = jnp.asarray(_pad_pow2(list(w0e_list)))
        b_pad = int(w0s_v.shape[0])
        T_pad = -(-nsteps // self.n_time) * self.n_time
        t_local = T_pad // self.n_time
        arrs = self._aligned_arrs(tiles, func)
        sig = tuple(sorted((k, v.ndim) for k, v in arrs.items()))
        args = (arrs, np.int64(self.n_filled), np.int64(self.base_ms),
                np.int64(self.dt_ms), w0s_v, w0e_v, np.int64(step))
        key = ("mesh-aligned-b", func, t_local, b_pad, sig,
               self._mesh_key())
        fn = _jit_lookup(key, lambda: _build_aligned_eval(
            self.mesh, func, t_local, b_pad, sig), cost_args=args)
        return fn(*args)[:, :self.S, :nsteps]

    def eval_grouped(self, func: str, steps: np.ndarray, window_ms: int,
                     gids: np.ndarray, num_groups: int, agg: str = "sum",
                     offset_ms: int = 0) -> np.ndarray:
        """sum/count/avg/min/max by (g) of rate/increase/delta straight
        off the resident store: one-hot matmul + psum over the shard
        axis -> [G, T] numpy."""
        t_local, w0s, w0e, step = self._grid(steps, window_ms, offset_ms)
        g = np.full(self.S_pad, -1, dtype=np.int32)   # -1 = padding rows
        g[:self.S] = np.asarray(gids, dtype=np.int32)
        row = NamedSharding(self.mesh, resolve_spec(self.mesh, P(0)))
        vv = self._cv if func in ("rate", "increase") else self._v
        args = (self._tsr, vv, jax.device_put(g, row),
                np.int64(self.n_filled), np.int64(self.base_ms),
                np.int64(self.dt_ms), w0s, w0e, step)
        args = (agg,) + args
        key = ("mesh-grouped", func, agg, t_local, num_groups,
               self._mesh_key())
        fn = _jit_lookup(key, lambda: _build_grouped_eval(
            self.mesh, func, t_local, num_groups, agg), cost_args=args)
        return np.asarray(fn(*args))[:, :steps.size]

    def eval_grouped_pair(self, func: str, steps: np.ndarray,
                          window_ms: int, gids: np.ndarray,
                          num_groups: int, offset_ms: int = 0
                          ) -> Tuple[np.ndarray, np.ndarray]:
        """Fused `sum by (g)` contract off the resident store ->
        (sums [T, G], counts [T, G]) numpy, matching the Pallas
        group-sum kernel's return shape (TpuBackend.fused_groupsum)."""
        t_local, w0s, w0e, step = self._grid(steps, window_ms, offset_ms)
        g = np.full(self.S_pad, -1, dtype=np.int32)
        g[:self.S] = np.asarray(gids, dtype=np.int32)
        row = NamedSharding(self.mesh, resolve_spec(self.mesh, P(0)))
        vv = self._cv if func in ("rate", "increase") else self._v
        args = (self._tsr, vv, jax.device_put(g, row),
                np.int64(self.n_filled), np.int64(self.base_ms),
                np.int64(self.dt_ms), w0s, w0e, step)
        key = ("mesh-grouped-pair", func, t_local, num_groups,
               self._mesh_key())
        fn = _jit_lookup(key, lambda: _build_grouped_pair_eval(
            self.mesh, func, t_local, num_groups), cost_args=args)
        sums, cnts = fn(*args)
        T = steps.size
        return np.asarray(sums)[:T], np.asarray(cnts)[:T]

    # -- the donated refresh ----------------------------------------------

    def append_slots(self, tiles_new) -> bool:
        """Cross-flush refresh: when ``tiles_new`` extends this store's
        series set by appended slots (same cohort, same cadence, grown
        prefix), write the new slot columns in place through the
        donated :func:`_append_step` and serve the fresh world with
        ZERO buffer copies. Returns False when incompatible — the
        caller re-places from scratch."""
        if not self.tiles_eligible(tiles_new):
            return False
        if (int(tiles_new.base_ms) != self.base_ms
                or int(tiles_new.dt_ms) != self.dt_ms
                or list(tiles_new.keys) != self.keys):
            return False
        n_new = int(tiles_new.num_slots)
        if n_new <= self.n_filled:
            return n_new == self.n_filled    # nothing to append
        k = n_new - self.n_filled
        # pow2-bucketed append width: repeat-pad the tail row so the
        # compiled append program is reused across flush cadences (the
        # padded rows land beyond n_filled and are never read — the
        # next append overwrites them)
        k_pad = _next_pow2(k, 8)
        if self.n_filled + k_pad > self.cap:
            return False                     # out of capacity: re-place
        ts = np.asarray(tiles_new.ts, dtype=np.float64)[:, self.n_filled:]
        v = np.asarray(tiles_new.channel("v"),
                       dtype=np.float64)[:, self.n_filled:]
        new_tsr = np.zeros((k_pad, self.S_pad), np.int32)
        new_v = np.zeros((k_pad, self.S_pad), np.float64)
        new_tsr[:k, :self.S] = (ts - self.base_ms).T.astype(np.int32)
        new_v[:k, :self.S] = v.T
        new_tsr[k:] = new_tsr[k - 1:k]
        new_v[k:] = new_v[k - 1:k]
        col = self._col_sharding
        self._tsr, self._v, self._cv = _append_step(
            self._tsr, self._v, self._cv,
            jax.device_put(new_tsr, col), jax.device_put(new_v, col),
            np.int64(self.n_filled))
        self.n_filled = n_new
        self._aligned.clear()   # row-major placements are per-snapshot
        self._record_residency()
        return True


# ---------------------------------------------------------------------------
# Placement cache (the evaluator the backend holds)
# ---------------------------------------------------------------------------

# cache inventory: placements key on tile-snapshot IDENTITY (an
# AlignedTiles instance is an immutable snapshot; a weakref finalizer
# drops the placement the moment its tiles die, so a recycled id can
# never serve stale channels)
@cache_registry("sharded-tile-placement", keyed=("tiles-identity",))
@guarded_by("_lock", "_placed")
class ShardedTileEvaluator:
    """The serving-path facade TpuBackend holds: lazily places eligible
    aligned-tile cohorts across the mesh, serves the sharded evaluator
    families from them, and rides cross-flush rebuilds through the
    donated append."""

    MAX_PLACEMENTS = 8

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self._lock = threading.Lock()
        # id(tiles) -> (weakref to tiles, ShardedTiles)
        self._placed: Dict[int, Tuple[object, ShardedTiles]] = {}
        self.placements = 0          # observability: builds
        self.donated_refreshes = 0   # observability: zero-copy appends

    @property
    def ndev(self) -> int:
        return int(self.mesh.devices.size)

    def place(self, tiles) -> Optional[ShardedTiles]:
        """The resident placement for ``tiles`` (built on first sight),
        or None when the tiles don't qualify."""
        if tiles is None or not ShardedTiles.tiles_eligible(tiles):
            return None
        key = id(tiles)
        with self._lock:
            got = self._placed.get(key)
            if got is not None:
                return got[1]
        placed = ShardedTiles(self.mesh, tiles)

        def _drop(_ref, *, _self=self, _key=key):
            with _self._lock:
                _self._placed.pop(_key, None)

        ref = weakref.ref(tiles, _drop)
        with self._lock:
            while len(self._placed) >= self.MAX_PLACEMENTS:
                self._placed.pop(next(iter(self._placed)))
            self._placed[key] = (ref, placed)
            self.placements += 1
        return placed

    def refresh(self, old_tiles, new_tiles) -> bool:
        """Cross-flush hand-over: move the old tiles' placement onto
        the freshly-built tiles via the donated append when compatible
        (zero-copy in HBM); otherwise drop it (the next query
        re-places). Returns True when the donated path served."""
        with self._lock:
            got = self._placed.pop(id(old_tiles), None)
        if got is None or new_tiles is None:
            return False
        placed = got[1]
        if not placed.append_slots(new_tiles):
            return False

        key = id(new_tiles)

        def _drop(_ref, *, _self=self, _key=key):
            with _self._lock:
                _self._placed.pop(_key, None)

        ref = weakref.ref(new_tiles, _drop)
        with self._lock:
            self._placed[key] = (ref, placed)
            self.donated_refreshes += 1
        return True

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"placements": self.placements,
                    "resident": len(self._placed),
                    "donated_refreshes": self.donated_refreshes,
                    "devices": self.ndev}
