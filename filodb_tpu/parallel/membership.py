"""Elastic membership: planned shard handoff, rolling-restart drain,
and rejoin hand-back.

The crash path (FailureDetector + quorum + ``reassign_dead_shards``)
treats every topology change as a node death: survivors adopt after the
grace window and the returning node gets a hard cutover. This module is
the PLANNED path — FiloDB's ShardManager/ShardAssignmentStrategy moving
shards on node join/leave as a first-class operation (coordinator/
ShardManager.scala:28 assignShardsToNodes; ShardAssignmentStrategy
.scala:188) — built make-before-break per shard on the existing
ordinal/FSM machinery:

Drain (``POST /admin/drain``) hands each locally-served shard to a
designated successor:

  1. **stop the local writer** — the shard's ingestion driver stops and
     flushes through the normal flush path (checkpoints + ColumnStore
     persist), so at most ONE node ever consumes/flushes a shard's
     stream (the per-shard single-writer invariant the chaos suite
     pins);
  2. **adopt request** — the successor is told to adopt over
     ``POST /admin/adopt``; it bootstraps index + chunks from the
     shared ColumnStore and replays the shared stream log from the
     checkpoint watermark (the same ``_adopt_shard`` path crash
     recovery uses), holding the shard RECOVERY;
  3. **await ACTIVE** — the draining node polls the successor's health
     body (the ``_sync_peer_statuses`` gossip channel) until the shard
     is advertised ``active``; meanwhile it KEEPS serving leaf/pushdown
     traffic for the shard from its (complete) resident state, and the
     successor's planner redirects reads for the mid-replay shard back
     to the draining owner (``handoff_sources``), so no query anywhere
     ever lands on a half-replayed copy;
  4. **flip + release** — ownership flips in the local ShardMapper
     (bumping the topology epoch -> plan/results caches invalidate),
     the transfer is pushed to the remaining peers
     (``POST /admin/transfer``; stale-routing bounce-and-retry covers
     any peer the push misses), and only then is the local copy
     released.

On failure (successor dies mid-replay / never goes ACTIVE) the shard
FALLS BACK: the successor is told to abort, the local ingestion driver
restarts from its checkpoint, and the draining node keeps serving — a
failed handoff degrades to "nothing happened", never to a dark shard.

Join/rejoin closes the ``on_node_up`` hard cutover: a restarting node
probes its peers' health bodies first and DEFERS any of its ordinal
shards a peer still serves (no second writer, no empty-shard window);
the temporary owner's failure detector sees the node healthy and runs
the same handoff primitive in reverse (``handback``), so the shard
replays and flips ACTIVE on its home node before the temporary owner
releases.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.parallel.cluster import reassign_dead_shards
from filodb_tpu.parallel.shardmapper import ShardStatus
from filodb_tpu.query.model import QueryError
from filodb_tpu.testing import chaos

_HANDOFF_SECONDS_HELP = ("Wall seconds per planned shard handoff "
                         "(drain-flush + successor replay + flip + "
                         "release)")


def probe_peer_claims(peers: Dict[str, str], shards: Sequence[int],
                      timeout_s: float = 2.0
                      ) -> Dict[int, Tuple[str, str]]:
    """Ask each peer's health body which of ``shards`` it currently
    serves: {shard: (claiming node, advertised status)}. A restarting
    node calls this BEFORE creating its ordinal shards — any shard a
    peer still serves (it adopted it while we were down) is deferred
    until the peer hands it back, closing the dual-writer window a
    blind startup would open. Unreachable peers claim nothing (first
    boot / full-cluster cold start degrade to the normal startup)."""
    claims: Dict[int, Tuple[str, str]] = {}
    want = set(int(s) for s in shards)
    for node, url in sorted(peers.items()):
        try:
            with urllib.request.urlopen(
                    f"{url.rstrip('/')}/__health",
                    timeout=timeout_s) as r:
                body = json.loads(r.read())
        except (OSError, ValueError):
            continue
        for k, st in (body.get("shards") or {}).items():
            try:
                sh = int(k)
            except (TypeError, ValueError):
                continue
            if sh in want and st in ("active", "recovery") \
                    and sh not in claims:
                claims[sh] = (node, st)
    return claims


@guarded_by("_lock", "draining", "incoming", "_cancel_owner",
            "handoffs_started", "handoffs_completed", "handoffs_failed",
            "adoptions_planned", "adoptions_crash", "releases",
            "handback_failures")
class MembershipManager:
    """Planned-membership coordinator for one FiloServer node.

    Owns the per-node handoff state machine and counters; the HTTP
    layer exposes its admin endpoints and /metrics families. All
    mutable state rides ``_lock``; the long-running protocol legs
    (flush, replay await, peer POSTs) run strictly outside it."""

    def __init__(self, server: "FiloServer",  # noqa: F821 — typing only
                 handoff_timeout_s: float = 30.0,
                 poll_interval_s: float = 0.1):
        self.server = server
        self.handoff_timeout_s = float(handoff_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self._lock = threading.Lock()
        self.draining = False
        # shard -> "bootstrapping" | "cancelled": planned adoptions in
        # flight on THIS node (the successor side)
        self.incoming: Dict[int, str] = {}
        # shard -> node to restore ownership to when an adoption is
        # aborted (the rolling-back draining owner)
        self._cancel_owner: Dict[int, str] = {}
        self.handoffs_started = 0
        self.handoffs_completed = 0
        self.handoffs_failed = 0
        self.adoptions_planned = 0
        self.adoptions_crash = 0        # bumped by the crash-adopt path
        self.releases = 0
        self.handback_failures = 0

    # -- introspection -----------------------------------------------------
    def metrics_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "draining": 1 if self.draining else 0,
                "incoming": len(self.incoming),
                "handoffs_started": self.handoffs_started,
                "handoffs_completed": self.handoffs_completed,
                "handoffs_failed": self.handoffs_failed,
                "adoptions_planned": self.adoptions_planned,
                "adoptions_crash": self.adoptions_crash,
                "releases": self.releases,
                "handback_failures": self.handback_failures,
            }

    def note_crash_adoption(self) -> None:
        with self._lock:
            self.adoptions_crash += 1

    def note_release(self) -> None:
        with self._lock:
            self.releases += 1

    # -- the drain/leave side ---------------------------------------------
    def drain(self, timeout_s: Optional[float] = None) -> Dict:
        """Walk every locally-served shard through planned handoff.
        Synchronous: returns when each shard is either handed off or
        rolled back (the rolling-restart runbook curls this, then stops
        the process). Successors follow the same deterministic
        round-robin the crash path uses, so a later crash of the
        drained node reassigns nothing twice."""
        srv = self.server
        with self._lock:
            already = self.draining
            self.draining = True
        det = getattr(srv, "detector", None)
        alive = sorted(det.alive_peers()) if det is not None \
            else sorted(srv.http.peers)
        if not alive:
            with self._lock:
                self.draining = False
            raise QueryError("drain: no alive peer to hand shards to")
        mine = sorted(n for n in srv.mapper.shards_for_node(srv.node_id)
                      if n in self._local_shard_nums())
        table = reassign_dead_shards(mine, alive)
        out = {"node": srv.node_id, "handed_off": [], "failed": [],
               "already_draining": already}
        for sh, succ in sorted(table.items()):
            ok, err = self.handoff_shard(sh, succ, timeout_s=timeout_s)
            if ok:
                out["handed_off"].append({"shard": sh, "to": succ})
            else:
                out["failed"].append({"shard": sh, "to": succ,
                                      "error": err})
        return out

    def _local_shard_nums(self) -> List[int]:
        srv = self.server
        return [s.shard_num for s in srv.store.shards(srv.ref)]

    def handoff_shard(self, sh: int, successor: str,
                      timeout_s: Optional[float] = None
                      ) -> Tuple[bool, Optional[str]]:
        """One make-before-break handoff. Returns (ok, error)."""
        srv = self.server
        url = srv.http.peers.get(successor)
        if url is None:
            return False, f"unknown successor {successor!r}"
        with self._lock:
            self.handoffs_started += 1
        timeout_s = self.handoff_timeout_s if timeout_s is None \
            else float(timeout_s)
        t0 = time.monotonic()
        tracer = getattr(srv.http, "tracer", None)
        tr = tracer.start(None) if tracer is not None else None
        had_driver = False
        try:
            with obs_trace.activate(tr), \
                    obs_trace.span("shard-handoff", shard=sh,
                                   node=srv.node_id, to=successor):
                # 1. single-writer: stop + flush the local ingestion
                # driver BEFORE the successor may start its own; the
                # shard's resident state stays queryable
                with obs_trace.span("drain-flush", shard=sh):
                    # registry mutation rides the server's reassign
                    # lock (shared with adopt/release workers); the
                    # stop+flush below runs outside it
                    with srv._reassign_lock:
                        drv = srv.drivers.pop(sh, None)
                    had_driver = drv is not None
                    if drv is not None:
                        drv.stop(flush=True)
                    elif srv.store.column_store is not None:
                        srv.store.get_shard(srv.ref, sh).flush_all()
                # 2. adopt request: the successor bootstraps + replays
                chaos.fire("handoff.adopt", shard=sh, node=successor)
                with obs_trace.span("adopt-request", shard=sh):
                    self._post(url, "/admin/adopt",
                               {"shard": sh, "from": srv.node_id})
                # 3. make-before-break: wait for the successor's health
                # body to advertise the shard ACTIVE
                with obs_trace.span("await-active", shard=sh):
                    self._await_active(url, sh,
                                       deadline=t0 + timeout_s)
                # 4. flip ownership (topology epoch bump -> local
                # plan/results caches invalidate via the mapper event),
                # push the transfer to the remaining peers, release
                srv.mapper.assign(sh, successor)
                srv.mapper.update(sh, ShardStatus.ACTIVE, successor)
                with obs_trace.span("transfer", shard=sh):
                    self._broadcast_transfer(sh, successor)
                with obs_trace.span("release", shard=sh):
                    srv._release_shard(sh)
            with self._lock:
                self.handoffs_completed += 1
            obs_metrics.observe("filodb_shard_handoff_seconds",
                                _HANDOFF_SECONDS_HELP,
                                time.monotonic() - t0)
            return True, None
        except Exception as e:      # noqa: BLE001 — any leg may fail
            with self._lock:
                self.handoffs_failed += 1
            obs_trace.event("handoff-failed", shard=sh, error=str(e))
            # fall back to the draining owner: abort the successor's
            # half-adoption (best effort — it may be dead, which is
            # fine) and restart the local writer from its checkpoint
            try:
                self._post(url, "/admin/abort_adopt",
                           {"shard": sh, "owner": srv.node_id},
                           timeout_s=2.0)
            except (OSError, QueryError):
                pass
            if had_driver:
                try:
                    srv._restart_driver(sh)
                except Exception as e2:     # noqa: BLE001
                    return False, f"{e}; driver restart failed: {e2}"
            return False, str(e)
        finally:
            if tr is not None and tracer is not None:
                tracer.finish(tr)

    def _post(self, base_url: str, path: str, body: Dict,
              timeout_s: float = 10.0) -> Dict:
        req = urllib.request.Request(
            f"{base_url.rstrip('/')}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            payload = json.loads(r.read())
        if payload.get("status") != "success":
            raise QueryError(f"{path} on {base_url}: "
                             f"{payload.get('error')}")
        return payload

    def _await_active(self, url: str, sh: int, deadline: float) -> None:
        last = None
        while time.monotonic() < deadline:
            chaos.fire("handoff.await", shard=sh)
            try:
                with urllib.request.urlopen(
                        f"{url.rstrip('/')}/__health",
                        timeout=2.0) as r:
                    body = json.loads(r.read())
                last = (body.get("shards") or {}).get(str(sh))
                if last == "active":
                    return
            except (OSError, ValueError):
                last = "unreachable"
            time.sleep(self.poll_interval_s)
        raise QueryError(
            f"handoff of shard {sh} timed out waiting for the "
            f"successor to go active (last advertised: {last})")

    def _broadcast_transfer(self, sh: int, owner: str) -> None:
        """Best-effort ownership push to every other alive peer; a peer
        the push misses converges through health gossip or the
        stale-routing bounce-and-retry path."""
        srv = self.server
        det = getattr(srv, "detector", None)
        for node, url in sorted(srv.http.peers.items()):
            if node == owner:
                continue        # the new owner already claims it
            if det is not None and det.is_down(node):
                continue
            try:
                chaos.fire("handoff.transfer", shard=sh, node=node)
                self._post(url, "/admin/transfer",
                           {"shard": sh, "owner": owner}, timeout_s=5.0)
            except (OSError, QueryError):
                pass

    # -- the successor / adopt side ---------------------------------------
    def accept_adopt(self, sh: int, from_node: str) -> Dict:
        """Successor side of a handoff (also the hand-back receiver on
        rejoin): bootstrap + replay in the background, redirecting
        reads for the mid-replay shard to the previous owner until the
        ingestion driver flips it ACTIVE."""
        srv = self.server
        sh = int(sh)
        if sh < 0 or sh >= srv.mapper.num_shards:
            raise QueryError(f"adopt: shard {sh} out of range")
        with self._lock:
            state = self.incoming.get(sh)
            if state == "bootstrapping":
                return {"state": "bootstrapping"}
            if sh in srv.drivers or sh in self._local_shard_nums():
                return {"state": "active"}
            self.incoming[sh] = "bootstrapping"
            self.adoptions_planned += 1
        # reads for the shard route back to the still-serving previous
        # owner while we replay (cleared when the driver goes ACTIVE).
        # All handoff_sources mutations ride _lock: the redirect map is
        # shared with the adopt/reaper worker threads
        if from_node in srv.http.peers:
            with self._lock:
                srv.http.handoff_sources[sh] = from_node
        with srv._reassign_lock:
            # remember whose shard this was, so when the node returns
            # (rejoin after drain+restart) the same handoff primitive
            # hands it back
            lst = srv._adopted.setdefault(from_node, [])
            if sh not in lst:
                lst.append(sh)
        threading.Thread(target=self._adopt_run, args=(sh, from_node),
                         daemon=True, name=f"adopt-shard-{sh}").start()
        return {"state": "accepted"}

    def _register_adopt_driver(self, sh: int, drv) -> bool:
        """Single-writer gate for a planned adoption's replay driver:
        registration and abort-cancellation are serialized on ``_lock``
        — an abort that lands mid-bootstrap refuses the registration,
        so the driver never starts after the draining owner has
        resumed ingesting."""
        with self._lock:
            if self.incoming.get(sh) == "cancelled":
                return False
            # nested per the canonical order (membership gate outer,
            # server registry inner — lint/lockorder.py)
            with self.server._reassign_lock:
                self.server.drivers[sh] = drv
        return True

    @thread_root("adopt-shard")
    def _adopt_run(self, sh: int, from_node: str) -> None:
        srv = self.server
        try:
            srv._adopt_shard(
                sh, on_event=self._adopt_event,
                register=lambda drv: self._register_adopt_driver(
                    sh, drv))
        except Exception:       # noqa: BLE001 — surfaced as shard ERROR
            with self._lock:
                srv.http.handoff_sources.pop(sh, None)
                self.incoming.pop(sh, None)
            srv._release_shard(sh)
            srv.mapper.update(sh, ShardStatus.ERROR, srv.node_id)
            return
        with self._lock:
            cancelled = self.incoming.get(sh) == "cancelled"
        if cancelled or sh not in srv.drivers:
            # no streaming driver (or an abort raced the bootstrap):
            # finalize inline — _adopt_shard already flipped ACTIVE on
            # the no-driver path
            self._finalize_adopt(sh, cancelled=cancelled)

    def _adopt_event(self, sh: int, status: ShardStatus,
                     progress: int) -> None:
        """Ingestion-driver event hook for planned adoptions: when the
        replay completes (ACTIVE), clear the read redirect — from here
        on this node serves the shard itself."""
        if status is ShardStatus.ACTIVE:
            with self._lock:
                cancelled = self.incoming.get(sh) == "cancelled"
            # release must not run on the driver's own thread (stop()
            # would join it); hand cancellation to a reaper thread
            if cancelled:
                threading.Thread(
                    target=self._finalize_adopt, args=(sh, True),
                    daemon=True, name=f"abort-adopt-{sh}").start()
            else:
                self._finalize_adopt(sh, cancelled=False)

    @thread_root("abort-adopt-reaper")
    def _finalize_adopt(self, sh: int, cancelled: bool) -> None:
        srv = self.server
        with self._lock:
            srv.http.handoff_sources.pop(sh, None)
            self.incoming.pop(sh, None)
            owner = self._cancel_owner.pop(sh, None)
        if cancelled:
            srv._release_shard(sh)
            self._restore_owner(sh, owner)

    def _restore_owner(self, sh: int, owner: Optional[str]) -> None:
        """An aborted adoption leaves the local mapper claiming a shard
        this node no longer serves — point it back at the rolled-back
        owner (it kept serving throughout)."""
        srv = self.server
        if owner and owner != srv.node_id and owner in srv.http.peers:
            srv.mapper.assign(sh, owner)
            srv.mapper.update(sh, ShardStatus.ACTIVE, owner)

    def abort_adopt(self, sh: int, owner: str = "") -> Dict:
        """The draining owner rolled back (we never went ACTIVE in
        time, or it chose to): drop the half-adopted state so two
        writers never run, and return the mapper claim to ``owner``.
        Safe at any point of the adopt."""
        srv = self.server
        sh = int(sh)
        with self._lock:
            state = self.incoming.get(sh)
            if state is not None:
                self.incoming[sh] = "cancelled"
                if owner:
                    self._cancel_owner[sh] = owner
            # popped under the SAME lock the registration gate takes:
            # either the replay driver registered first (we stop it
            # below) or the gate will refuse it — no interleaving
            # leaves a writer running after the rollback
            with srv._reassign_lock:
                drv = srv.drivers.pop(sh, None)
            srv.http.handoff_sources.pop(sh, None)
        if drv is not None:
            drv.stop(flush=False)
            srv._release_shard(sh)
            with self._lock:
                self.incoming.pop(sh, None)
                self._cancel_owner.pop(sh, None)
            self._restore_owner(sh, owner)
            return {"state": "released"}
        if state is None and sh in self._local_shard_nums():
            # adoption already finalized with no driver (non-streaming)
            srv._release_shard(sh)
            self._restore_owner(sh, owner)
            return {"state": "released"}
        return {"state": "cancelled" if state is not None else "noop"}

    def apply_transfer(self, sh: int, owner: str) -> Dict:
        """A peer completed a handoff: rewire shard -> owner locally
        (bumping the topology epoch; the mapper event invalidates the
        plan/results caches)."""
        srv = self.server
        sh = int(sh)
        if sh < 0 or sh >= srv.mapper.num_shards:
            raise QueryError(f"transfer: shard {sh} out of range")
        if owner != srv.node_id and owner not in srv.http.peers:
            raise QueryError(f"transfer: unknown owner {owner!r}")
        if srv.mapper.node_of(sh) != owner:
            srv.mapper.assign(sh, owner)
            srv.mapper.update(sh, ShardStatus.ACTIVE, owner)
            return {"applied": True}
        return {"applied": False}

    # -- the rejoin / hand-back side --------------------------------------
    def handback(self, node: str) -> None:
        """A node this one adopted shards from is healthy again: hand
        each shard back through the SAME make-before-break handoff
        (replacing the legacy hard cutover). Runs in the background —
        the failure detector's poll thread must keep polling. Per-shard
        retries cover the returning node's startup window (its admin
        endpoints may answer a beat after its health does)."""
        with self.server._reassign_lock:
            mine = list(self.server._adopted.pop(node, []))
        if not mine:
            return
        threading.Thread(target=self._handback_run, args=(node, mine),
                         daemon=True, name=f"handback-{node}").start()

    @thread_root("handback")
    def _handback_run(self, node: str, shards: List[int]) -> None:
        for sh in sorted(shards):
            ok = False
            for attempt in range(3):
                ok, _err = self.handoff_shard(sh, node)
                if ok:
                    break
                time.sleep(0.5 * (attempt + 1))
            if not ok:
                # the shard stays HERE (still served, still single-
                # writer); re-record it so a later recovery can retry
                with self.server._reassign_lock:
                    lst = self.server._adopted.setdefault(node, [])
                    if sh not in lst:
                        lst.append(sh)
                with self._lock:
                    self.handback_failures += 1
