"""Multi-process cluster plane: peer addressing, remote leaf dispatch,
and failure detection.

TPU-native analogue of the reference's v2 cluster mode
(coordinator/v2/FiloDbClusterDiscovery.scala:50 — deterministic
ordinal→shards, no cluster singleton) + plan dispatch
(query/exec/PlanDispatcher.scala:21, RemoteActorPlanDispatcher): each node
owns `shards_for_ordinal(ordinal)`; a query entering any node fans its
LEAF data selection out to the peers owning the other shards over plain
HTTP (the host control plane — bulk device compute stays node-local), and
the full plan evaluates on the entry node over the merged series. Node
loss is detected by health polling (Akka gossip/DeathWatch equivalent,
FilodbCluster.scala): the lost node's shards flip DOWN, and past the
quorum-gated grace window survivors ADOPT them (ShardManager.scala:28
assignShardsToNodes). Planned topology changes — rolling-restart drain
and rejoin hand-back — run the make-before-break handoff protocol in
parallel/membership.py instead of the crash machinery, with topology
epochs and stale-routing retries keeping peer routing coherent.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence

import numpy as np

from filodb_tpu.core.index import ColumnFilter
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.query import qos
from filodb_tpu.parallel.resilience import (BreakerRegistry, Deadline,
                                            RetryPolicy, TransportError,
                                            resilient_call)
from filodb_tpu.parallel.shardmapper import ShardMapper, ShardStatus
from filodb_tpu.query.model import (QueryError, RawSeries,
                                    StaleRoutingError)
from filodb_tpu.testing import chaos


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode()


def _unb64(s: str, dtype, shape) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s), dtype=dtype).reshape(shape)


def series_to_wire(series: Sequence[RawSeries]) -> List[Dict]:
    """RawSeries → JSON-safe dicts. Arrays ride base64 (bit-exact — JSON
    floats can't carry NaN); the reference ships SerializedRangeVector
    containers over Kryo for the same reason (RangeVector.scala:452)."""
    out = []
    for s in series:
        d = {
            "labels": dict(s.labels),
            "n": int(s.ts.size),
            "ts": _b64(s.ts.astype(np.int64)),
            "values": _b64(np.asarray(s.values, dtype=np.float64)),
            "is_counter": bool(s.is_counter),
        }
        if s.values.ndim == 2:
            d["nb"] = int(s.values.shape[1])
        if s.bucket_les is not None:
            d["les"] = [float(x) for x in np.asarray(s.bucket_les)]
        if s.hist_drop_rows is not None:
            d["drops"] = _b64(np.asarray(s.hist_drop_rows,
                                         dtype=np.int64))
        if s.snapshot_key is not None:
            d["snap"] = list(s.snapshot_key)
            d["chunk_len"] = int(s.chunk_len)
        out.append(d)
    return out


def wire_to_series(rows: Sequence[Dict]) -> List[RawSeries]:
    out = []
    for d in rows:
        n = d["n"]
        shape = (n, d["nb"]) if "nb" in d else (n,)
        les = np.array(d["les"], dtype=np.float64) if "les" in d else None
        drops = _unb64(d["drops"], np.int64, (-1,)) if "drops" in d \
            else None
        out.append(RawSeries(
            labels=d["labels"],
            ts=_unb64(d["ts"], np.int64, (n,)),
            values=_unb64(d["values"], np.float64, shape),
            is_counter=d["is_counter"],
            bucket_les=les,
            hist_drop_rows=drops,
            snapshot_key=tuple(d["snap"]) if "snap" in d else None,
            chunk_len=int(d.get("chunk_len", -1)),
        ))
    return out


def _get_json(url_or_req, node_id: str, timeout_s: float) -> Dict:
    """Fetch + parse a peer response, mapping transport errors to
    TransportError (retryable, breaker-counted) and peer application
    errors to QueryError (shared by leaf dispatch and whole-query
    forwarding)."""
    url = getattr(url_or_req, "full_url", url_or_req)
    try:
        chaos.fire("http.peer", node=node_id, url=url)
        with urllib.request.urlopen(url_or_req, timeout=timeout_s) as r:
            payload = json.loads(r.read())
    except (OSError, ValueError) as e:      # ValueError: garbled body
        raise TransportError(f"remote node {node_id} unreachable: {e}")
    if payload.get("status") != "success":
        if payload.get("errorType") == "stale_routing":
            # the peer no longer serves the shards we routed at it (a
            # planned handoff moved them): NOT retryable against the
            # same peer — the entry node re-resolves routing instead
            raise StaleRoutingError(
                owners=payload.get("owners"),
                epoch=int(payload.get("topo_epoch") or 0),
                node=node_id, detail=str(payload.get("error") or ""))
        sr = StaleRoutingError.parse(payload.get("error"))
        if sr is not None:
            raise sr
        raise QueryError(f"remote node {node_id}: {payload.get('error')}")
    return payload


def _drop_grpc_channel(addr: str) -> None:
    """Close + evict a cached gRPC channel (peer died or moved ports);
    no-op when grpc isn't installed or nothing is cached."""
    try:
        from filodb_tpu.grpcsvc.client import drop_channel
        drop_channel(addr)
    except Exception:
        pass


def filters_to_wire(filters: Sequence[ColumnFilter]) -> List[List[str]]:
    return [[f.label, f.op, f.value] for f in filters]


def wire_to_filters(rows: Sequence[Sequence[str]]) -> List[ColumnFilter]:
    return [ColumnFilter(l, op, v) for l, op, v in rows]


class RemoteShardGroup:
    """Stands in a planner shard list for ONE peer node's shard subset.

    `select_raw_series` recognizes it and delegates the leaf data fetch to
    the peer's POST /api/v1/raw/{dataset} endpoint — the ActorPlanDispatcher
    leaf-dispatch hop, over HTTP instead of Akka+Kryo.

    Transport failures retry per ``retry`` within the ``deadline``
    budget; consecutive failures trip the peer's circuit breaker in
    ``breakers`` (keyed by base URL). With ``allow_partial`` the caller
    (select_raw_series) drops this group from the result and records a
    warning instead of failing the query."""

    def __init__(self, node_id: str, base_url: str, dataset: str,
                 shard_nums: Optional[Sequence[int]],
                 timeout_s: float = 60.0,
                 retry: Optional[RetryPolicy] = None,
                 breakers: Optional[BreakerRegistry] = None,
                 deadline: Optional[Deadline] = None,
                 allow_partial: bool = False):
        self.node_id = node_id
        self.base_url = base_url.rstrip("/")
        self.dataset = dataset
        # None = ALL of the peer's shards (cross-cluster raw reads)
        self.shard_nums = list(shard_nums) if shard_nums is not None \
            else None
        self.timeout_s = timeout_s
        self.retry = retry
        self.breakers = breakers
        self.deadline = deadline
        self.allow_partial = allow_partial
        # planner bookkeeping: a group covers many shard numbers
        self.shard_num = tuple(self.shard_nums or ())

    def describe(self) -> str:
        """Human-readable identity for partial-result warnings."""
        sh = ("all" if self.shard_nums is None
              else ",".join(map(str, self.shard_nums)))
        return f"shards [{sh}] on {self.node_id}"

    def fetch_raw(self, filters, start_ms: int, end_ms: int,
                  column: Optional[str],
                  full: bool = True) -> List[RawSeries]:
        msg = {
            "filters": filters_to_wire(filters),
            "start_ms": int(start_ms), "end_ms": int(end_ms),
            "column": column, "shards": self.shard_nums,
            "full": bool(full),
        }
        # tenant QoS: the fan-out leg inherits the entry query's tenant
        # charge (the peer force-debits its own bucket for this tenant)
        # and priority class (its batcher orders the leg accordingly)
        qctx = qos.current()
        if qctx is not None:
            msg["tenant"] = qctx.tenant
            if qctx.priority:
                msg["priority"] = qctx.priority

        def dial(timeout_s: float) -> Dict:
            # server-side deadline propagation: the peer inherits the
            # entry node's REMAINING budget (re-read per attempt — a
            # retry must not hand the peer the original full budget)
            if self.deadline is not None:
                msg["timeout_s"] = round(
                    max(self.deadline.remaining(), 1e-3), 3)
            body = json.dumps(msg).encode()
            headers = {"Content-Type": "application/json"}
            tb = obs_trace.inject_header()
            if tb:      # trace propagation on the JSON control plane
                headers[obs_trace.HEADER] = tb
            req = urllib.request.Request(
                f"{self.base_url}/api/v1/raw/{self.dataset}", data=body,
                headers=headers)
            return _get_json(req, self.node_id, timeout_s)

        with obs_trace.span("remote-peer", node=self.node_id,
                            plane="http", rpc="raw",
                            addr=self.base_url):
            payload = resilient_call(
                dial, key=self.base_url, node_id=self.node_id,
                timeout_s=self.timeout_s, retry=self.retry,
                breakers=self.breakers, deadline=self.deadline)
            obs_trace.absorb_spans(payload.get("trace_spans"))
        return wire_to_series(payload["data"])

    # metadata plans are answered via the HTTP layer's peer fan-out, not
    # through this leaf-dispatch path
    def lookup_partitions(self, filters, start_ts, end_ts):
        return []


class PromQlRemoteExec:
    """Forward a WHOLE query to the peer node owning every involved shard
    and parse its Prometheus JSON back into a grid
    (query/exec/PromQlRemoteExec.scala — HTTP JSON to a remote FiloDB).

    This is the shard-aligned pushdown: when a plan (including a binary
    join whose two sides prune to the same shard set) lives entirely on
    one peer, the peer evaluates it — windowing, joins, aggregation — and
    only the final result crosses the network, not raw series."""

    def __init__(self, query: str, start_ms: int, step_ms: int,
                 end_ms: int, node_id: str, base_url: str, dataset: str,
                 timeout_s: float = 60.0, stats=None,
                 local_only: bool = True,
                 retry: Optional[RetryPolicy] = None,
                 breakers: Optional[BreakerRegistry] = None,
                 deadline: Optional[Deadline] = None,
                 no_cache: bool = False,
                 expect_shards: Optional[Sequence[int]] = None):
        self.query = query
        self.start_ms = start_ms
        self.step_ms = step_ms
        self.end_ms = end_ms
        self.node_id = node_id
        self.base_url = base_url.rstrip("/")
        self.dataset = dataset
        self.timeout_s = timeout_s
        self.stats = stats      # planner QueryStats: peer stats fold in
        # the shard set the entry node believes this peer owns: the
        # peer bounces the query (stale_routing) instead of silently
        # evaluating over a subset when a handoff moved one away
        self.expect_shards = list(expect_shards) \
            if expect_shards is not None else None
        # pushdown within a cluster pins the peer to its local shards;
        # cross-cluster federation lets the remote cluster plan freely
        # (MultiPartitionPlanner semantics)
        self.local_only = local_only
        self.retry = retry
        self.breakers = breakers
        self.deadline = deadline
        # the caller's &cache=false rides the hop: the peer must not
        # serve this query from its results cache either
        self.no_cache = no_cache

    def execute(self):
        import urllib.parse

        from filodb_tpu.query.model import GridResult, RangeParams
        params = RangeParams(self.start_ms, self.step_ms, self.end_ms)
        steps = params.steps
        instant = self.step_ms <= 0
        if instant:
            qs = {"query": self.query, "time": self.start_ms // 1000}
            path = "query"
        else:
            qs = {"query": self.query, "start": self.start_ms // 1000,
                  "end": self.end_ms // 1000,
                  "step": self.step_ms // 1000}
            path = "query_range"
        if self.local_only:
            qs["dispatch"] = "local"    # no fan-back-out (loop prevention)
            if self.expect_shards:
                qs["expect_shards"] = ",".join(
                    str(int(s)) for s in self.expect_shards)
        if self.no_cache:
            qs["cache"] = "false"
        # tenant QoS: pushdown/federation hops name the tenant so the
        # peer charges the same budget (forced on dispatch=local hops;
        # a federation peer applies its own edge admission)
        qctx = qos.current()
        if qctx is not None:
            qs["tenant"] = qctx.tenant
            if qctx.priority:
                qs["priority"] = qos.PRIORITY_NAMES.get(
                    qctx.priority, "interactive")
        qs["hist-wire"] = "1"

        def dial(t: float) -> Dict:
            # forward the remaining deadline budget so the peer's own
            # evaluation inherits it (&timeout=, the knob the HTTP edge
            # already parses); re-read per attempt
            if self.deadline is not None:
                qs["timeout"] = "%.3fs" % max(self.deadline.remaining(),
                                              1e-3)
            url = (f"{self.base_url}/promql/{self.dataset}/api/v1/"
                   f"{path}?" + urllib.parse.urlencode(qs))
            tb = obs_trace.inject_header()
            if tb:      # trace propagation on the HTTP pushdown plane
                url = urllib.request.Request(
                    url, headers={obs_trace.HEADER: tb})
            return _get_json(url, self.node_id, t)

        with obs_trace.span("remote-peer", node=self.node_id,
                            plane="http", rpc="exec",
                            addr=self.base_url):
            payload = resilient_call(
                dial, key=self.base_url, node_id=self.node_id,
                timeout_s=self.timeout_s, retry=self.retry,
                breakers=self.breakers, deadline=self.deadline)
            obs_trace.absorb_spans(payload.get("trace_spans"))
        if self.stats is not None and "stats" in payload:
            self.stats.series_scanned += payload["stats"].get(
                "seriesScanned", 0)
            self.stats.samples_scanned += payload["stats"].get(
                "samplesScanned", 0)
        data = payload["data"]
        keys, rows, hrows, les = [], [], [], None
        any_hist = False
        for entry in data.get("result", []):
            keys.append(dict(entry["metric"]))
            row = np.full(steps.size, np.nan)
            samples = entry.get("values")
            if samples is None and "value" in entry:
                samples = [entry["value"]]
            for t, v in samples or []:
                pos = np.searchsorted(steps, int(float(t) * 1000))
                if pos < steps.size and steps[pos] == int(float(t) * 1000):
                    row[pos] = float(v)
            rows.append(row)
            h = entry.get("hist")
            if h is not None:
                any_hist = True
                les = np.array(h["les"], dtype=np.float64)
                hrows.append(_unb64(h["values"], np.float64,
                                    (steps.size, les.size)))
            else:
                hrows.append(None)
        values = np.vstack(rows) if rows else np.zeros((0, steps.size))
        hv = None
        if any_hist:
            nb = les.size
            hv = np.stack([h if h is not None
                           else np.full((steps.size, nb), np.nan)
                           for h in hrows])
        # a degraded peer answers with partial/warnings markers: carry
        # them through so the entry node's response stays honest
        return GridResult(steps, keys, values, hist_values=hv,
                          bucket_les=les if any_hist else None,
                          partial=bool(payload.get("partial")),
                          warnings=list(payload.get("warnings") or ()))

    def plan_tree(self, indent: int = 0) -> str:
        return (" " * indent + f"PromQlRemoteExec(node={self.node_id}, "
                f"query={self.query!r})")


def reassign_dead_shards(dead_shards: Sequence[int],
                         survivors: Sequence[str]) -> Dict[int, str]:
    """Deterministic round-robin of a dead node's shards over the sorted
    survivor set (ShardAssignmentStrategy.scala:188 — every node computes
    the same table independently, no coordinator election needed)."""
    ordered = sorted(survivors)
    return {sh: ordered[i % len(ordered)]
            for i, sh in enumerate(sorted(dead_shards))}


class FailureDetector:
    """Health-poll peers; flip their shards DOWN after consecutive misses
    and back ACTIVE on recovery (the Akka-cluster gossip/DeathWatch +
    ShardManager reaction, ShardManager.scala:28).

    With ``reassign_grace_s`` set, a node held DOWN past the grace window
    triggers ``on_node_down(node)`` exactly once — the server's elastic
    recovery hook (ShardManager.scala:28 assignShardsToNodes +
    ShardAssignmentStrategy.scala:188): survivors adopt the dead node's
    shards deterministically. When the node comes back, ``on_node_up``
    runs instead of the plain ACTIVE flip so adopters can release."""

    def __init__(self, mapper: ShardMapper, peers: Dict[str, str],
                 shards_by_node: Dict[str, Sequence[int]],
                 interval_s: float = 0.5, threshold: int = 3,
                 timeout_s: float = 2.0,
                 reassign_grace_s: Optional[float] = None,
                 on_node_down=None, on_node_up=None,
                 grpc_peer_sink: Optional[Dict[str, str]] = None,
                 peer_state_sink: Optional[Dict[str, Dict]] = None):
        self.mapper = mapper
        self.peers = dict(peers)
        # mutable {node -> "host:port"} the poller fills from peers'
        # advertised gRPC ports (shared with the planner's grpc_peers,
        # so leaf dispatch upgrades to the binary data plane as soon as
        # a peer is discovered)
        self.grpc_peer_sink = grpc_peer_sink
        # mutable {node -> {"watermarks": {shard: ms}, "epochs":
        # {shard: n}, "topo_epoch": n}} filled from peers' health
        # bodies (ROADMAP 4a): the planner stamps remote shard groups
        # with gossiped ingest watermarks + backfill epochs so the
        # results cache's freshness horizon covers fan-out extents too.
        # Entries are dropped the moment a peer goes down — a stale
        # advertisement must not bound freshness.
        self.peer_state_sink = peer_state_sink
        # set by stop() when the monitor thread failed to exit within
        # the join timeout; surfaced as the detector_thread_wedged
        # gauge so chaos runs can't silently leak pollers
        self.thread_wedged = False
        self.shards_by_node = {k: list(v) for k, v in
                               shards_by_node.items()}
        self.interval_s = interval_s
        self.threshold = threshold
        self.timeout_s = timeout_s
        self.reassign_grace_s = reassign_grace_s
        self.on_node_down = on_node_down
        self.on_node_up = on_node_up
        self._misses: Dict[str, int] = {p: 0 for p in peers}
        self._down: Dict[str, bool] = {p: False for p in peers}
        self._down_since: Dict[str, float] = {}
        self._reassigned: Dict[str, bool] = {p: False for p in peers}
        # status gossip parsed from peers' health bodies: each peer
        # advertises the FSM status of the shards it actually serves,
        # and its own down-view of ITS peers (the quorum input)
        self._peer_shards: Dict[str, Dict[int, str]] = {}
        self._peer_down_view: Dict[str, set] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _probe(self, url: str) -> Optional[Dict]:
        """One health poll: {} on a healthy peer without a parseable
        body, the parsed body when present, None when unreachable."""
        try:
            with urllib.request.urlopen(f"{url.rstrip('/')}/__health",
                                        timeout=self.timeout_s) as r:
                if r.status != 200:
                    return None
                try:
                    return json.loads(r.read())
                except ValueError:
                    return {}
        except OSError:
            return None

    def note_peer_exit(self, node: str) -> None:
        """Supervisor-bus hint: a sibling worker PROCESS exited (the
        supervisor waitpid'd it — ground truth, no probe needed). Drop
        the peer's data-plane channel and its gossiped watermarks
        immediately instead of waiting out poll misses: a dead worker's
        stale watermark advertisement must not keep bounding the
        results cache's freshness horizon while it restarts. Routing is
        deliberately NOT flipped DOWN here — the supervisor is already
        respawning the worker at the same address, so in-flight peer
        calls ride their retry budget through the restart window."""
        if node not in self.peers:
            return
        if self.grpc_peer_sink is not None:
            old = self.grpc_peer_sink.pop(node, None)
            if old is not None:
                _drop_grpc_channel(old)
        if self.peer_state_sink is not None:
            self.peer_state_sink.pop(node, None)

    def note_peer_up(self, node: str) -> None:
        """Supervisor-bus hint: a sibling worker finished restarting.
        Reset the miss counter so one stale in-flight probe can't push
        the fresh process over the down threshold."""
        if node in self._misses:
            self._misses[node] = 0

    def is_down(self, node: str) -> bool:
        return self._down.get(node, False)

    def alive_peers(self) -> List[str]:
        return [p for p in self.peers if not self._down.get(p, False)]

    def down_peers(self) -> List[str]:
        """This node's own down-view (advertised in its health body so
        peers can count quorum votes)."""
        return [p for p, d in self._down.items() if d]

    def _quorum_agrees(self, node: str) -> bool:
        """Require a majority of this node's OTHER alive peers to share
        the down-view before elastic reassignment fires — one node's
        partitioned link must not trigger dual-ingest adoption (the
        Akka-cluster gossip-convergence analogue, FilodbCluster.scala).
        With no other alive peer there is no quorum to consult."""
        voters = [p for p in self.peers
                  if p != node and not self._down.get(p, False)]
        if not voters:
            return True
        agree = sum(1 for p in voters
                    if node in self._peer_down_view.get(p, ()))
        # self + agreeing peers must be a strict majority of self + voters
        return 2 * (1 + agree) > 1 + len(voters)

    def _sync_peer_statuses(self, node: str, adv: Dict[int, str]) -> None:
        """Adopt the owner's advertised shard statuses instead of
        guessing: a shard another survivor adopted stays RECOVERY on
        every node until its owner advertises it ACTIVE (closes the
        window where queries hit a bootstrapping adopter and silently
        return partial results)."""
        for sh, st_str in adv.items():
            if self.mapper.node_of(sh) != node:
                continue
            try:
                st = ShardStatus(st_str)
            except ValueError:
                continue
            if self.mapper.status(sh) is not st:
                self.mapper.update(sh, st, node)

    @staticmethod
    def _int_map(raw) -> Dict[int, object]:
        try:
            return {int(k): v for k, v in (raw or {}).items()}
        except (TypeError, ValueError):
            return {}

    def poll_once(self) -> None:
        for node, url in self.peers.items():
            body = self._probe(url)
            if body is not None:
                self._misses[node] = 0
                adv = self._int_map(body.get("shards"))
                self._peer_shards[node] = adv
                self._peer_down_view[node] = set(
                    body.get("down_peers") or ())
                if self.peer_state_sink is not None:
                    # watermark/epoch gossip (ROADMAP 4a): the planner
                    # reads this to stamp remote shard groups for the
                    # results cache's freshness horizon
                    self.peer_state_sink[node] = {
                        "watermarks": self._int_map(
                            body.get("watermarks")),
                        "epochs": self._int_map(
                            body.get("backfill_epochs")),
                        "topo_epoch": int(body.get("topo_epoch") or 0),
                    }
                gport = body.get("grpc_port")
                if gport and self.grpc_peer_sink is not None:
                    host = urllib.parse.urlparse(url).hostname \
                        or "127.0.0.1"
                    addr = f"{host}:{int(gport)}"
                    old = self.grpc_peer_sink.get(node)
                    if old != addr:
                        # a restarted peer advertises a NEW ephemeral
                        # port: re-point the sink and drop the cached
                        # channel to the dead address, or every later
                        # dial would keep hitting it (round-5 advisor)
                        self.grpc_peer_sink[node] = addr
                        if old is not None:
                            _drop_grpc_channel(old)
                came_back = self._down[node]
                if came_back:
                    self._down[node] = False
                    self._down_since.pop(node, None)
                if self._reassigned.get(node, False):
                    # the node is healthy but its shards are still
                    # reassigned away. Run the release hook; only a
                    # SUCCESSFUL hook clears the flag, so a raising
                    # hook is retried on the next poll instead of
                    # wedging ownership on the adopters forever
                    if self.on_node_up is not None:
                        try:
                            self.on_node_up(node)
                            self._reassigned[node] = False
                            continue
                        except Exception:
                            # fall through to the mapper-level hand-
                            # back below (ownership must not wedge);
                            # the hook retries next poll
                            pass
                    else:
                        self._reassigned[node] = False
                    hand_back = list(self.shards_by_node.get(node, []))
                elif came_back:
                    # plain bounce (no reassignment fired): restore
                    # only what the mapper STILL assigns to the node —
                    # a planned handoff may have rewired ownership
                    # while it was away, and a drained node owns none
                    hand_back = list(self.mapper.shards_for_node(node))
                else:
                    self._sync_peer_statuses(node, adv)
                    continue
                for sh in hand_back:
                    # honor what the returning node ADVERTISES: a
                    # node mid-replay says "recovery" and must not
                    # be flipped ACTIVE (queries would lose the
                    # partial-result warning until the next poll)
                    try:
                        st = ShardStatus(adv[sh]) if sh in adv \
                            else ShardStatus.ACTIVE
                    except ValueError:
                        st = ShardStatus.ACTIVE
                    self.mapper.update(sh, st, node)
            else:
                self._misses[node] += 1
                if self._misses[node] >= self.threshold \
                        and not self._down[node]:
                    self._down[node] = True
                    self._down_since[node] = time.monotonic()
                    # flip the shards the mapper assigns the node NOW
                    # (not the startup assignment): planned handoffs
                    # rewire ownership, and a drained node owns nothing
                    for sh in self.mapper.shards_for_node(node):
                        self.mapper.update(sh, ShardStatus.DOWN, node)
                    # forget the dead node's data-plane address: when it
                    # returns (likely on a new ephemeral port) the sink
                    # re-learns from its fresh health advertisement
                    # instead of dialing the dead address forever
                    if self.grpc_peer_sink is not None:
                        old = self.grpc_peer_sink.pop(node, None)
                        if old is not None:
                            _drop_grpc_channel(old)
                    # a dead peer's gossiped watermarks must not keep
                    # bounding the results cache's freshness horizon
                    if self.peer_state_sink is not None:
                        self.peer_state_sink.pop(node, None)
                if (self._down[node] and self.reassign_grace_s is not None
                        and not self._reassigned.get(node, False)
                        and time.monotonic() - self._down_since[node]
                        >= self.reassign_grace_s
                        and self._quorum_agrees(node)):
                    self._reassigned[node] = True
                    if self.on_node_down is not None:
                        try:
                            self.on_node_down(node)
                        except Exception:
                            pass     # keep the monitor thread alive

    @thread_root("failure-detector")
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.poll_once()

    def start(self) -> "FailureDetector":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # the monitor thread failed to exit (a health probe
                # wedged past its timeout, or a hook hung): surface it
                # — chaos runs must not silently leak pollers. The
                # /metrics gauge detector_thread_wedged rides this.
                self.thread_wedged = True
                import sys
                print(f"filodb: FailureDetector monitor thread failed "
                      f"to exit within 5s (peers={sorted(self.peers)})",
                      file=sys.stderr)
