"""Multi-host device mesh: jax.distributed over ICI/DCN.

The reference scales out with one Akka/NCCL process per node
(coordinator/FilodbCluster.scala:39); the TPU-native equivalent is a
single jax.distributed job spanning hosts — every process contributes
its local devices to ONE global ('shard','time') mesh, and the psum /
all_gather collectives of the windowed aggregate then ride ICI (or DCN
across hosts) exactly as on one host (SURVEY §7 step 6; the
"How to Scale Your Model" recipe: pick a mesh, annotate shardings, let
XLA insert the collectives).

``init_process`` wires one process into the cluster;
``window_aggregate_distributed`` runs MeshExecutor's fused windowed
aggregate with every process holding only ITS shard groups' data —
global arrays are assembled from process-local tiles, so no host ever
materializes another host's samples.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def init_process(coordinator_address: str, num_processes: int,
                 process_id: int) -> None:
    """Join this process to the jax.distributed cluster. Call BEFORE any
    jax backend initialization (on CPU test rigs also set
    XLA_FLAGS=--xla_force_host_platform_device_count=K and
    jax_platforms=cpu first — see tests/test_distributed.py)."""
    import jax
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def window_aggregate_distributed(mesh_ex, local_series_by_shard,
                                 local_group_ids, params, func: str,
                                 agg: str, window_ms: int,
                                 num_groups: int, offset_ms: int = 0,
                                 scalar: float = 0.0) -> np.ndarray:
    """Run MeshExecutor's windowed aggregate across processes.

    Each process passes the shard groups its LOCAL devices own (their
    count must equal this process's slice of the mesh 'shard' axis); the
    packed tiles are stitched into global arrays sharded over the mesh,
    so the grouped psum-tree reduction crosses process boundaries on the
    wire, not through any host. Returns the full [num_groups, T] result
    on every process."""
    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from filodb_tpu.parallel.mesh import _GATHER_FUNCS, pack_sharded
    from filodb_tpu.query.tpu import TpuBackend

    mesh = mesh_ex.mesh
    n_shard = mesh.shape["shard"]
    n_time = mesh.shape["time"]
    nproc = jax.process_count()
    if n_shard % nproc:
        raise ValueError(f"shard axis {n_shard} must divide across "
                         f"{nproc} processes")
    if len(local_series_by_shard) != n_shard // nproc:
        raise ValueError("pass exactly this process's shard groups")

    # agree on global pad shapes + window bound (static jit args must
    # match across processes or the compiled programs diverge)
    local_maxs = max([1] + [len(r) for r in local_series_by_shard])
    local_maxn = max([1] + [s.ts.size for row in local_series_by_shard
                            for s in row])
    w_local = 0
    if func in _GATHER_FUNCS:
        all_local = [s for row in local_series_by_shard for s in row]
        w_local = TpuBackend._window_sample_bound(all_local, window_ms,
                                                  local_maxn)
    agreed = multihost_utils.process_allgather(
        np.array([local_maxs, local_maxn, w_local], np.int64))
    s_pad = int(agreed[:, 0].max())
    n_pad = int(agreed[:, 1].max())
    w_bound = int(agreed[:, 2].max())
    # pow2 bucketize like pack_sharded's defaults (compile-cache reuse)
    s_pad = 1 << (s_pad - 1).bit_length()
    n_pad = 1 << (n_pad - 1).bit_length()

    ts, vals, lens, _ = pack_sharded(local_series_by_shard,
                                     drop_nan=(func != "last_sample"),
                                     s_pad=s_pad, n_pad=n_pad)
    gl = len(local_series_by_shard)
    gids = np.full((gl, s_pad), -1, dtype=np.int32)
    for g, row in enumerate(local_group_ids):
        gids[g, :len(row)] = row

    steps = params.steps
    T = steps.size
    T_pad = -(-T // n_time) * n_time
    step = np.int64(params.step_ms if T > 1 else 1)
    w0e = np.int64(steps[0] - offset_ms)
    w0s = np.int64(w0e - window_ms)

    def to_global(arr, spec):
        return multihost_utils.host_local_array_to_global_array(
            arr, mesh, spec)

    g_ts = to_global(ts, P("shard", None, None))
    g_vals = to_global(vals, P("shard", None, None))
    g_lens = to_global(lens, P("shard", None))
    g_gids = to_global(gids, P("shard", None))

    out = mesh_ex._step(func, agg, num_groups, T_pad // n_time, w_bound,
                        g_ts, g_vals, g_lens, g_gids, w0s, w0e, step,
                        scalar)
    # [num_groups, T_pad] sharded over 'time': recover the full grid on
    # every host (with the default shard-only mesh the time axis is
    # whole already; a time-split mesh gathers process slices in order)
    host = np.asarray(multihost_utils.global_array_to_host_local_array(
        out, mesh, P(None, "time")))
    if host.shape[1] != T_pad:
        host = np.concatenate(
            list(multihost_utils.process_allgather(host)), axis=1)
    return host[:, :T]
