"""Per-shard ingestion driver: checkpoint recovery then steady-state
ingest with interleaved group flushes.

(Reference: coordinator/IngestionActor.scala — ``startIngestion`` :174
reads the checkpoint watermark, ``doRecovery`` :297 replays the stream
from it publishing RecoveryInProgress events, ``normalIngestion`` :240
drives TimeSeriesShard.startIngestion; flush tasks are interleaved with
ingest on the shard's single ingest thread, TimeSeriesShard.scala:897.)

The TPU build keeps the same protocol minus the actor machinery: one
Python thread per shard runs

    bootstrap (index + checkpoints from the ColumnStore, done by caller)
      -> recovery: replay stream from min(checkpoints) to the stream end
         observed at startup, shard status RECOVERY(progress%)
         (rows already flushed are dropped by the partitions' OOO guard)
      -> steady state: poll the stream; every ``flush_every_records``
         offsets (or ``flush_interval_s`` wall clock) flush the next
         flush group round-robin, checkpointing the last ingested offset.

Flush rotation mirrors the reference's groups-per-shard scheduling
(doc/ingestion.md "Recovery and Persistence"): each group checkpoint =
"all my partitions' rows at/below this offset are encoded+persisted", so
the replay watermark is min over groups.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.ingest import health as ingest_health
from filodb_tpu.ingest.stream import IngestionStream
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.parallel.shardmapper import ShardMapper, ShardStatus
from filodb_tpu.testing import chaos

_FLUSH_HELP = ("Wall seconds per flush-group persist (encode + "
               "ColumnStore write + checkpoint)")


class IngestionDriver:
    """Drives one shard from one stream (IngestionActor + shard thread)."""

    def __init__(self, shard: TimeSeriesShard, stream: IngestionStream,
                 mapper: Optional[ShardMapper] = None,
                 flush_every_records: Optional[int] = None,
                 flush_interval_s: float = 1.0,
                 poll_interval_s: float = 0.02,
                 on_event: Optional[Callable] = None,
                 max_resident_samples: int = 0,
                 ingest_batch_records: int = 64,
                 max_decode_cache_bytes: int = 0,
                 max_quarantined_records: int = 0):
        self.shard = shard
        self.stream = stream
        self.mapper = mapper
        self.flush_every_records = flush_every_records
        self.flush_interval_s = flush_interval_s
        self.poll_interval_s = poll_interval_s
        self.on_event = on_event or (lambda *a: None)
        # memory-pressure watermark (0 = no cap): checked after flushes
        self.max_resident_samples = max_resident_samples
        # WAL read batch per poll (ingest-batch-records): bigger batches
        # amortize per-poll overhead during replay at the cost of
        # coarser flush-cadence checks between records
        self.ingest_batch_records = max(1, int(ingest_batch_records))
        # decode/merge-cache byte budget (0 = unbounded): trimmed on the
        # flush path via TimeSeriesShard.trim_decode_caches
        self.max_decode_cache_bytes = int(max_decode_cache_bytes)
        # integrity knob (integrity-max-quarantined-records): tolerated
        # quarantined-record loss before the shard degrades to
        # read-only. 0 = any quarantined record trips it.
        self.max_quarantined_records = int(max_quarantined_records)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._next_group = 0
        self._last_flush_t = 0.0
        self._records_since_flush = 0
        self.next_offset = 0          # next stream offset to ingest
        self.recovered_to = -1        # end of the recovery replay window

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "IngestionDriver":
        self._thread = threading.Thread(
            target=self._run, name=f"ingest-shard-{self.shard.shard_num}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, flush: bool = True, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        if flush and self.next_offset > 0:
            # final flush of all groups at the last ingested offset, so a
            # clean shutdown restarts with an up-to-date watermark
            self.shard.flush_all(offset=self.next_offset - 1)

    # -- protocol ----------------------------------------------------------
    def _set_status(self, status: ShardStatus, progress: int = 0) -> None:
        if self.mapper is not None:
            self.mapper.update(self.shard.shard_num, status,
                               progress_pct=progress)
        self.on_event(self.shard.shard_num, status, progress)

    @thread_root("ingest-shard")
    def _run(self) -> None:
        try:
            self._last_flush_t = time.monotonic()
            self._recover()
            self._set_status(ShardStatus.ACTIVE)
            self._last_flush_t = time.monotonic()
            while not self._stop.is_set():
                if not self._ingest_available():
                    self._maybe_flush(force_time_check=True)
                    self._stop.wait(self.poll_interval_s)
        except Exception:               # pragma: no cover - defensive
            self._set_status(ShardStatus.ERROR)
            raise

    def _recover(self) -> None:
        """Replay from the checkpoint watermark to the stream end observed
        at startup (IngestionActor.doRecovery :297).  The OOO guard drops
        rows at/below each partition's persisted end time, so replaying
        below per-group checkpoints is idempotent."""
        watermark = self.shard.recovery_watermark()
        # groups that never flushed have no checkpoint -> replay everything
        start = watermark + 1 if watermark >= 0 else 0
        end = self.stream.end_offset()          # recovery target
        self.next_offset = start
        self.recovered_to = end
        if start >= end:
            return
        self._set_status(ShardStatus.RECOVERY, 0)
        while self.next_offset < end and not self._stop.is_set():
            if not self._ingest_available(
                    limit=min(self.ingest_batch_records,
                              end - self.next_offset),
                    recovering=True):
                break                            # stream shrank (shouldn't)
            done = self.next_offset - start
            pct = int(100 * done / max(1, end - start))
            self._set_status(ShardStatus.RECOVERY, min(pct, 99))

    def _ingest_available(self, limit: Optional[int] = None,
                          recovering: bool = False) -> bool:
        """Poll + ingest one batch; returns True if anything was read.

        ``recovering=True`` (the startup replay) applies batches even
        once the quarantine knob trips: every record the scan kept is
        checksum-verified acked data, and dropping it would turn one
        corrupt record into a whole-shard truncation. The read-only
        flag (and its metric/event) still raises immediately — it gates
        NEW post-recovery ingest only."""
        if self.shard.integrity_read_only and not recovering:
            return False
        if limit is None:
            limit = self.ingest_batch_records
        batch = self.stream.read(self.next_offset, max_records=limit)
        # the read may have quarantined corrupt records: refresh the
        # shard's integrity state BEFORE applying the batch, so nothing
        # new lands once loss exceeds the knob
        q = getattr(self.stream, "quarantined_records", None)
        if q is not None or self.shard.column_store is not None:
            # read-only keeps the mapper status ACTIVE: the shard still
            # SERVES queries (flagged in health + metrics + events), it
            # just stops applying new records
            if self.shard.update_integrity(q() if q is not None else 0,
                                           self.max_quarantined_records) \
                    and not recovering:
                return False
        if not batch:
            return False
        # chaos fault point: a failing stream consumer (the Kafka-poll
        # failure analogue) — the driver thread's defensive handler
        # flips the shard to ERROR, which tests assert on
        chaos.fire("ingest.batch", shard=self.shard.shard_num,
                   offset=self.next_offset)
        for sd in batch:
            self.shard.ingest(sd.container, sd.offset)
            self.next_offset = sd.offset + 1
            self._records_since_flush += 1
            self._maybe_flush()
        return True

    def _maybe_flush(self, force_time_check: bool = False) -> None:
        due = False
        if self.flush_every_records is not None:
            due = self._records_since_flush >= self.flush_every_records
        if not due:
            now = time.monotonic()
            if now - self._last_flush_t >= self.flush_interval_s:
                due = True
        if not due or self.next_offset == 0:
            return
        group = self._next_group
        self._next_group = (self._next_group + 1) % self.shard.num_groups
        # chaos fault point: a failing flush (ColumnStore write error)
        chaos.fire("ingest.flush", shard=self.shard.shard_num,
                   group=group)
        try:
            with obs_metrics.timed("filodb_flush_seconds", _FLUSH_HELP):
                self.shard.flush_group(group, offset=self.next_offset - 1)
        except OSError as e:
            if ingest_health.GLOBAL.note_write_error(
                    e, f"flush shard={self.shard.shard_num} group={group}"):
                # out-of-space: the flush retries on its normal cadence
                # (the batch stays resident; the checkpoint did not
                # advance) — NOT a driver-thread-killing error
                self._last_flush_t = time.monotonic()
                return
            raise
        ingest_health.GLOBAL.note_write_ok()
        if self.max_resident_samples:
            self.shard.ensure_headroom(self.max_resident_samples)
        if self.max_decode_cache_bytes:
            self.shard.trim_decode_caches(self.max_decode_cache_bytes)
        self._records_since_flush = 0
        self._last_flush_t = time.monotonic()


def start_ingestion(shards: List[TimeSeriesShard],
                    streams: List[IngestionStream],
                    mapper: Optional[ShardMapper] = None,
                    **kw) -> List[IngestionDriver]:
    """Start one driver per (shard, stream) pair."""
    drivers = [IngestionDriver(sh, st, mapper, **kw)
               for sh, st in zip(shards, streams)]
    for d in drivers:
        d.start()
    return drivers
