"""Ingestion streams: pull-based, offset-carrying sample sources.

The reference's ingestion source boundary is IngestionStream
(coordinator/IngestionStream.scala:14,43) with the production impl bound
1 shard <-> 1 Kafka partition (kafka/KafkaIngestionStream.scala:26; ``get``
:81 returns an Observable[SomeData(RecordContainer, offset)] seeked to the
recovery offset).  Here the same contract is a poll API over monotonic
record ordinals:

  * ``SomeData`` = one RecordContainer + the offset it was published at.
  * ``IngestionStream.read(from_offset, max_records)`` returns whatever is
    available (possibly empty) — the ingestion driver polls it, exactly
    like a Kafka consumer poll loop.
  * ``LogIngestionStream`` is the durable Kafka-partition equivalent: an
    append-only framed file per shard.  The gateway (producer side) appends
    containers; the server (consumer side) tails the file across process
    boundaries, so a killed server replays from its checkpoint watermark.
  * ``MemoryIngestionStream`` is the in-process test stream (the
    reference's sources/CsvStream analogue).

Readers never truncate: a torn tail may be a writer mid-append (the two
sides are different processes); the reader simply waits for the record to
complete.
"""

from __future__ import annotations

import os
import struct
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.record import PartKey, RecordContainer
from filodb_tpu.core.schemas import ColumnType, Schemas
from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.memory.histogram import _decode_scheme, _encode_scheme
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.store import integrity
from filodb_tpu.testing import chaos

_APPEND_HELP = ("Wall seconds per durable-stream append (encode + "
                "write + flush + any fsync this append performed)")
_FSYNC_HELP = ("Wall seconds per durable-stream os.fsync (group commit "
               "coalesces appends: fsync count / append count is the "
               "coalescing ratio)")

_REC_MAGIC = 0xF10D
# record header: magic u16, schema_name_len u16, nrows u32, payload_len u32
_REC_HDR = struct.Struct("<HHII")


@dataclass(frozen=True)
class SomeData:
    """One published batch (IngestionStream.scala SomeData)."""
    container: RecordContainer
    offset: int


class IngestionStream:
    """Source abstraction (IngestionStream.scala:14): a sequence of
    RecordContainers with monotonically increasing offsets."""

    def read(self, from_offset: int, max_records: int = 64
             ) -> List[SomeData]:
        """Poll: return up to ``max_records`` batches at/after
        ``from_offset`` that are available now (may be empty)."""
        raise NotImplementedError

    def end_offset(self) -> int:
        """Offset one past the last published record (Kafka endOffset)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


@guarded_by("_lock", "_records")
class MemoryIngestionStream(IngestionStream):
    """In-process stream for tests and embedded producers."""

    def __init__(self):
        self._records: List[RecordContainer] = []
        self._lock = threading.Lock()

    def append(self, container: RecordContainer) -> int:
        with self._lock:
            self._records.append(container)
            return len(self._records) - 1

    def read(self, from_offset: int, max_records: int = 64
             ) -> List[SomeData]:
        with self._lock:
            hi = min(len(self._records), from_offset + max_records)
            return [SomeData(self._records[i], i)
                    for i in range(max(0, from_offset), hi)]

    def end_offset(self) -> int:
        with self._lock:
            return len(self._records)


# ---------------------------------------------------------------------------
# Container wire format (the RecordContainer serde — the Kafka payload
# analogue, kafka/RecordContainerSerde)
# ---------------------------------------------------------------------------

def _encode_values(schema, columns: Sequence[Sequence], row: int) -> bytes:
    out = bytearray()
    for col, colvals in zip(schema.data_columns, columns):
        v = colvals[row]
        if col.col_type == ColumnType.HISTOGRAM:
            scheme, counts = v
            counts = np.asarray(counts, dtype="<f8")
            sb = _encode_scheme(scheme)
            out.extend(struct.pack("<HH", len(sb), counts.size))
            out.extend(sb)
            out.extend(counts.tobytes())
        else:
            out.extend(struct.pack("<d", float(v)))
    return bytes(out)


def _decode_values(schema, buf: bytes, off: int) -> Tuple[Tuple, int]:
    vals = []
    for col in schema.data_columns:
        if col.col_type == ColumnType.HISTOGRAM:
            sb_len, n = struct.unpack_from("<HH", buf, off)
            off += 4
            scheme, _ = _decode_scheme(buf, off)
            off += sb_len
            counts = np.frombuffer(buf, dtype="<f8", count=n, offset=off)
            off += 8 * n
            vals.append((scheme, counts))
        else:
            (v,) = struct.unpack_from("<d", buf, off)
            off += 8
            vals.append(v)
    return tuple(vals), off


def encode_container(container: RecordContainer) -> bytes:
    """Serialize one RecordContainer to a framed record."""
    schema = container.schema
    name = schema.name.encode()
    payload = bytearray()
    for i in range(len(container)):
        pk = container.part_keys[i].to_bytes()
        payload.extend(struct.pack("<H", len(pk)))
        payload.extend(pk)
        payload.extend(struct.pack("<q", container.timestamps[i]))
        payload.extend(_encode_values(schema, container.columns, i))
    return (_REC_HDR.pack(_REC_MAGIC, len(name), len(container),
                          len(payload)) + name + bytes(payload))


def decode_container(buf: bytes, off: int, schemas: Schemas
                     ) -> Tuple[Optional[RecordContainer], int]:
    """Decode one framed record at ``off``; returns (container, next_off)
    or (None, off) when the record is incomplete (torn / mid-write)."""
    if off + _REC_HDR.size > len(buf):
        return None, off
    magic, name_len, nrows, payload_len = _REC_HDR.unpack_from(buf, off)
    if magic != _REC_MAGIC:
        raise ValueError(f"bad stream record magic at {off}")
    end = off + _REC_HDR.size + name_len + payload_len
    if end > len(buf):
        return None, off
    p = off + _REC_HDR.size
    name = buf[p:p + name_len].decode()
    p += name_len
    schema = schemas.by_name(name)
    cont = RecordContainer(schema)
    for _ in range(nrows):
        (pk_len,) = struct.unpack_from("<H", buf, p)
        p += 2
        pk = PartKey.from_bytes(buf[p:p + pk_len])
        p += pk_len
        (ts,) = struct.unpack_from("<q", buf, p)
        p += 8
        vals, p = _decode_values(schema, buf, p)
        cont.add(pk, ts, *vals)
    return cont, end


def legacy_wal_probe(buf: bytes, off: int) -> int:
    """Integrity-scanner probe for pre-framing WAL records: total
    record length when a plausible legacy record starts at ``off``,
    -1 when one starts but runs past the buffer (torn), 0 otherwise."""
    if off + _REC_HDR.size > len(buf):
        return -1 if off + 2 <= len(buf) and \
            struct.unpack_from("<H", buf, off)[0] == _REC_MAGIC else 0
    magic, name_len, _, payload_len = _REC_HDR.unpack_from(buf, off)
    if magic != _REC_MAGIC:
        return 0
    if payload_len > integrity.MAX_PAYLOAD:
        return 0
    total = _REC_HDR.size + name_len + payload_len
    return total if off + total <= len(buf) else -1


# producer and consumer sides may be different THREADS in one process
# (embedded gateway + ingest driver): the writer handle, the record
# index, and the scan watermark all ride one lock
@guarded_by("_lock", "_write_f", "_records", "_scan_end", "_tail_state",
            "_tail_off", "_tail_reason", "_tail_reported_off",
            "_read_bad", "_quarantined_records", "_quarantined_bytes",
            "_last_sync_t", "_unsynced_bytes")
class LogIngestionStream(IngestionStream):
    """Durable file-backed stream: one append-only framed log per shard —
    the Kafka-partition analogue (1 shard <-> 1 log, KafkaIngestionStream).

    Producer side uses ``append``; consumer side polls ``read``.  The two
    may be different processes: the reader tails the file, stopping at any
    incomplete tail record until the writer finishes it.

    Group-commit fsync: per-append ``os.fsync`` was the residual
    episodic stall on shared container disks (ROADMAP follow-up — one
    slow fsync froze the ingest thread mid-batch). With
    ``group_commit_s > 0`` appends write+flush but fsync only when the
    time window elapses or ``group_commit_bytes`` accumulate unsynced —
    the Kafka ``log.flush.interval`` shape. The durability window is
    bounded by exactly those two knobs; ``sync()`` forces, ``close()``
    syncs the tail. ``group_commit_s = 0`` (the default) keeps the
    strict fsync-per-append behavior. Every real fsync observes
    ``filodb_ingest_fsync_seconds`` so the stall the ROADMAP saw is
    visible data, not a guess."""

    def __init__(self, path: str, schemas: Schemas,
                 group_commit_s: float = 0.0,
                 group_commit_bytes: int = 1 << 20,
                 integrity_frames: bool = True):
        self.path = path
        self.schemas = schemas
        self.group_commit_s = float(group_commit_s)
        self.group_commit_bytes = int(group_commit_bytes)
        # integrity_frames=False writes legacy unframed records — kept
        # for mixed-version tests and the bench's CRC on/off split
        self.integrity_frames = bool(integrity_frames)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._write_f = None
        self._lock = threading.Lock()
        # reader state: scanner-verified records, the classified-bytes
        # watermark the next scan resumes from, and the last tail state
        self._records: List[integrity.ScanRecord] = []
        self._scan_end = 0
        self._tail_state = "clean"
        self._tail_off = 0
        self._tail_reason = ""
        self._tail_reported_off = -1
        # read-time verification strikes per ordinal: first failure
        # retries from disk (transient), second skips-and-advances
        self._read_bad: Dict[int, int] = {}
        self._quarantined_records = 0
        self._quarantined_bytes = 0
        # group-commit state: when the last fsync happened and how many
        # bytes are flushed-but-unsynced since
        self._last_sync_t = 0.0
        self._unsynced_bytes = 0
        self.appends = 0
        self.fsyncs = 0

    # -- producer side ----------------------------------------------------
    def append(self, container: RecordContainer, fsync: bool = True) -> int:
        """Publish one container; returns its offset (ordinal).  One writer
        per shard log (the shard<->partition ownership invariant); on
        takeover, a torn tail left by a crashed writer is truncated so the
        new append lands on a record boundary (a CORRUPT tail — bad bytes,
        not just incomplete — is quarantined before the truncate)."""
        import time as _time
        t0 = _time.perf_counter()
        payload = encode_container(container)
        data = integrity.encode_frame(payload) if self.integrity_frames \
            else payload
        with self._lock:
            if self._write_f is None:
                self._refresh_locked()
                if os.path.exists(self.path) and \
                        os.path.getsize(self.path) > self._scan_end:
                    if self._tail_state == "corrupt":
                        self._quarantine_tail_locked()
                    os.truncate(self.path, self._scan_end)
                    self._tail_state = "clean"
                self._write_f = open(self.path, "ab")
            off = len(self._records)
            try:
                chaos.write("wal.append", self._write_f, data,
                            path=self.path, nbytes=len(data))
                self._write_f.flush()
            except OSError:
                # the buffer may hold a torn prefix: flush it out and
                # drop the handle so the next append takes over (and
                # truncates the torn tail) instead of appending after it
                try:
                    self._write_f.close()
                except OSError:
                    pass
                self._write_f = None
                raise
            self._unsynced_bytes += len(data)
            if fsync:
                # graftlint: disable=lock-blocking-reachable (single-writer WAL: the lock IS the producer/consumer serialization; group commit bounds the fsync window)
                self._maybe_fsync_locked()
            hdr = integrity.FRAME_HDR.size if self.integrity_frames else 0
            self._records.append(integrity.ScanRecord(
                self._scan_end, len(data), self._scan_end + hdr,
                len(payload), self.integrity_frames))
            self._scan_end += len(data)
            self.appends += 1
        obs_metrics.observe("filodb_ingest_append_seconds", _APPEND_HELP,
                            _time.perf_counter() - t0,
                            obs_metrics.FSYNC_BUCKETS_S)
        return off

    def _maybe_fsync_locked(self, force: bool = False) -> None:
        """Group commit: fsync now when forced, when group commit is
        off, or when the time/size bound tripped; otherwise leave the
        bytes flushed-but-unsynced (the bounded durability window)."""
        import time as _time
        if self._unsynced_bytes == 0:
            return
        now = _time.monotonic()
        if not force and self.group_commit_s > 0:
            if (now - self._last_sync_t < self.group_commit_s
                    and self._unsynced_bytes < self.group_commit_bytes):
                return
        t0 = _time.perf_counter()
        chaos.fire("wal.fsync", path=self.path)
        os.fsync(self._write_f.fileno())
        obs_metrics.observe("filodb_ingest_fsync_seconds", _FSYNC_HELP,
                            _time.perf_counter() - t0,
                            obs_metrics.FSYNC_BUCKETS_S)
        self.fsyncs += 1
        self._last_sync_t = now
        self._unsynced_bytes = 0

    def sync(self) -> None:
        """Force-fsync any unsynced tail (checkpoint barriers)."""
        with self._lock:
            if self._write_f is not None:
                # graftlint: disable=lock-blocking-reachable (checkpoint barrier: readers must not observe the log mid-sync)
                self._maybe_fsync_locked(force=True)

    # -- consumer side ----------------------------------------------------
    def _refresh_locked(self) -> int:
        """Extend the record index over newly appended bytes via the
        integrity scanner; returns the current record count. Corrupt
        regions are quarantined and SKIPPED (replay resumes at the next
        verified boundary) — the pre-integrity behavior of silently
        halting indexing forever is gone."""
        if not os.path.exists(self.path):
            return 0
        size = os.path.getsize(self.path)
        if size <= self._scan_end:
            return len(self._records)
        with open(self.path, "rb") as f:
            f.seek(self._scan_end)
            buf = f.read(size - self._scan_end)
        buf = chaos.filter_read("wal.read", buf, path=self.path,
                                offset=self._scan_end)
        res = integrity.scan_buffer(buf, probe=legacy_wal_probe,
                                    base=self._scan_end)
        for reg in res.corrupt:
            integrity.quarantine(
                self.path, "wal", reg.offset,
                buf[reg.offset - self._scan_end:
                    reg.offset - self._scan_end + reg.length],
                reg.reason)
            self._quarantined_records += 1
            self._quarantined_bytes += reg.length
        self._records.extend(res.records)
        self._scan_end += res.consumed
        self._tail_state = res.tail_state
        self._tail_off = res.tail_off
        self._tail_reason = res.tail_reason
        if (res.tail_state == "corrupt"
                and res.tail_off != self._tail_reported_off):
            # bad bytes with no resync point yet: more appends may
            # reveal one (then the region quarantines above), takeover
            # quarantines + truncates, fsck repairs — but say so NOW
            self._tail_reported_off = res.tail_off
            integrity.record_corruption(
                "wal", self.path, res.tail_off,
                size - res.tail_off, res.tail_reason, action="pending")
        return len(self._records)

    def _quarantine_tail_locked(self) -> None:
        """Copy a corrupt tail to the sidecar before takeover truncates
        it (truncation must never destroy the only copy of bad bytes)."""
        try:
            with open(self.path, "rb") as f:
                f.seek(self._scan_end)
                tail = f.read()
        except OSError:
            return
        if tail:
            integrity.quarantine(self.path, "wal", self._scan_end, tail,
                                 self._tail_reason or "corrupt tail",
                                 action="quarantined-truncated")
            self._quarantined_records += 1
            self._quarantined_bytes += len(tail)

    def _empty_container(self) -> RecordContainer:
        """Zero-row placeholder emitted for a record whose bytes failed
        read-time verification twice: replay ADVANCES past the damage
        (the bytes are already quarantined) instead of stalling."""
        schema = next(iter(self.schemas.schemas.values()))
        return RecordContainer(schema)

    def read(self, from_offset: int, max_records: int = 64
             ) -> List[SomeData]:
        with self._lock:
            n = self._refresh_locked()
            lo = max(0, from_offset)
            hi = min(n, lo + max_records)
            if lo >= hi:
                return []
            records = self._records[lo:hi]
        base = records[0].offset
        end = records[-1].offset + records[-1].length
        with open(self.path, "rb") as f:
            f.seek(base)
            buf = f.read(end - base)
        buf = chaos.filter_read("wal.read", buf, path=self.path,
                                offset=base)
        out: List[SomeData] = []
        for i, rec in enumerate(records):
            ordinal = lo + i
            try:
                if rec.framed:
                    # read-path verification: the CRC is re-checked on
                    # every decode, not only at scan time — bit rot
                    # between scan and read cannot reach a query
                    payload, _ = integrity.decode_frame(
                        buf, rec.offset - base)
                    if payload is None:
                        break              # torn at buffer end: wait
                    cont, _ = decode_container(payload, 0, self.schemas)
                else:
                    cont, _ = decode_container(buf, rec.offset - base,
                                               self.schemas)
                    if cont is None:
                        break
            except (integrity.FrameError, ValueError, KeyError,
                    struct.error) as e:
                with self._lock:
                    strikes = self._read_bad.get(ordinal, 0)
                    self._read_bad[ordinal] = strikes + 1
                if strikes == 0:
                    # first failure: stop here and let the next poll
                    # re-read from disk (a transient flip heals itself)
                    integrity.record_corruption(
                        "wal", self.path, rec.offset, rec.length,
                        f"read-time verification failed: {e}",
                        action="read-retry")
                    break
                # persistent damage: quarantine the bytes, emit an
                # empty batch at this ordinal so replay advances
                integrity.quarantine(
                    self.path, "wal", rec.offset,
                    buf[rec.offset - base:rec.offset - base + rec.length],
                    f"read-time verification failed: {e}",
                    action="skipped")
                with self._lock:
                    self._quarantined_records += 1
                    self._quarantined_bytes += rec.length
                cont = self._empty_container()
            out.append(SomeData(cont, ordinal))
        return out

    def end_offset(self) -> int:
        with self._lock:
            return self._refresh_locked()

    def quarantined_records(self) -> int:
        with self._lock:
            return self._quarantined_records

    def quarantined_bytes(self) -> int:
        with self._lock:
            return self._quarantined_bytes

    def tail_state(self) -> str:
        with self._lock:
            return self._tail_state

    def close(self) -> None:
        with self._lock:
            if self._write_f is not None:
                # sync the group-commit tail: a clean close must not
                # leave the durability window open
                # graftlint: disable=lock-blocking-reachable (close-time tail sync; no reader may race the handle teardown)
                self._maybe_fsync_locked(force=True)
                self._write_f.close()
                self._write_f = None
