"""Ingestion streams: pull-based, offset-carrying sample sources.

The reference's ingestion source boundary is IngestionStream
(coordinator/IngestionStream.scala:14,43) with the production impl bound
1 shard <-> 1 Kafka partition (kafka/KafkaIngestionStream.scala:26; ``get``
:81 returns an Observable[SomeData(RecordContainer, offset)] seeked to the
recovery offset).  Here the same contract is a poll API over monotonic
record ordinals:

  * ``SomeData`` = one RecordContainer + the offset it was published at.
  * ``IngestionStream.read(from_offset, max_records)`` returns whatever is
    available (possibly empty) — the ingestion driver polls it, exactly
    like a Kafka consumer poll loop.
  * ``LogIngestionStream`` is the durable Kafka-partition equivalent: an
    append-only framed file per shard.  The gateway (producer side) appends
    containers; the server (consumer side) tails the file across process
    boundaries, so a killed server replays from its checkpoint watermark.
  * ``MemoryIngestionStream`` is the in-process test stream (the
    reference's sources/CsvStream analogue).

Readers never truncate: a torn tail may be a writer mid-append (the two
sides are different processes); the reader simply waits for the record to
complete.
"""

from __future__ import annotations

import os
import struct
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.record import PartKey, RecordContainer
from filodb_tpu.core.schemas import ColumnType, Schemas
from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.memory.histogram import _decode_scheme, _encode_scheme
from filodb_tpu.obs import metrics as obs_metrics

_APPEND_HELP = ("Wall seconds per durable-stream append (encode + "
                "write + flush + any fsync this append performed)")
_FSYNC_HELP = ("Wall seconds per durable-stream os.fsync (group commit "
               "coalesces appends: fsync count / append count is the "
               "coalescing ratio)")

_REC_MAGIC = 0xF10D
# record header: magic u16, schema_name_len u16, nrows u32, payload_len u32
_REC_HDR = struct.Struct("<HHII")


@dataclass(frozen=True)
class SomeData:
    """One published batch (IngestionStream.scala SomeData)."""
    container: RecordContainer
    offset: int


class IngestionStream:
    """Source abstraction (IngestionStream.scala:14): a sequence of
    RecordContainers with monotonically increasing offsets."""

    def read(self, from_offset: int, max_records: int = 64
             ) -> List[SomeData]:
        """Poll: return up to ``max_records`` batches at/after
        ``from_offset`` that are available now (may be empty)."""
        raise NotImplementedError

    def end_offset(self) -> int:
        """Offset one past the last published record (Kafka endOffset)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


@guarded_by("_lock", "_records")
class MemoryIngestionStream(IngestionStream):
    """In-process stream for tests and embedded producers."""

    def __init__(self):
        self._records: List[RecordContainer] = []
        self._lock = threading.Lock()

    def append(self, container: RecordContainer) -> int:
        with self._lock:
            self._records.append(container)
            return len(self._records) - 1

    def read(self, from_offset: int, max_records: int = 64
             ) -> List[SomeData]:
        with self._lock:
            hi = min(len(self._records), from_offset + max_records)
            return [SomeData(self._records[i], i)
                    for i in range(max(0, from_offset), hi)]

    def end_offset(self) -> int:
        with self._lock:
            return len(self._records)


# ---------------------------------------------------------------------------
# Container wire format (the RecordContainer serde — the Kafka payload
# analogue, kafka/RecordContainerSerde)
# ---------------------------------------------------------------------------

def _encode_values(schema, columns: Sequence[Sequence], row: int) -> bytes:
    out = bytearray()
    for col, colvals in zip(schema.data_columns, columns):
        v = colvals[row]
        if col.col_type == ColumnType.HISTOGRAM:
            scheme, counts = v
            counts = np.asarray(counts, dtype="<f8")
            sb = _encode_scheme(scheme)
            out.extend(struct.pack("<HH", len(sb), counts.size))
            out.extend(sb)
            out.extend(counts.tobytes())
        else:
            out.extend(struct.pack("<d", float(v)))
    return bytes(out)


def _decode_values(schema, buf: bytes, off: int) -> Tuple[Tuple, int]:
    vals = []
    for col in schema.data_columns:
        if col.col_type == ColumnType.HISTOGRAM:
            sb_len, n = struct.unpack_from("<HH", buf, off)
            off += 4
            scheme, _ = _decode_scheme(buf, off)
            off += sb_len
            counts = np.frombuffer(buf, dtype="<f8", count=n, offset=off)
            off += 8 * n
            vals.append((scheme, counts))
        else:
            (v,) = struct.unpack_from("<d", buf, off)
            off += 8
            vals.append(v)
    return tuple(vals), off


def encode_container(container: RecordContainer) -> bytes:
    """Serialize one RecordContainer to a framed record."""
    schema = container.schema
    name = schema.name.encode()
    payload = bytearray()
    for i in range(len(container)):
        pk = container.part_keys[i].to_bytes()
        payload.extend(struct.pack("<H", len(pk)))
        payload.extend(pk)
        payload.extend(struct.pack("<q", container.timestamps[i]))
        payload.extend(_encode_values(schema, container.columns, i))
    return (_REC_HDR.pack(_REC_MAGIC, len(name), len(container),
                          len(payload)) + name + bytes(payload))


def decode_container(buf: bytes, off: int, schemas: Schemas
                     ) -> Tuple[Optional[RecordContainer], int]:
    """Decode one framed record at ``off``; returns (container, next_off)
    or (None, off) when the record is incomplete (torn / mid-write)."""
    if off + _REC_HDR.size > len(buf):
        return None, off
    magic, name_len, nrows, payload_len = _REC_HDR.unpack_from(buf, off)
    if magic != _REC_MAGIC:
        raise ValueError(f"bad stream record magic at {off}")
    end = off + _REC_HDR.size + name_len + payload_len
    if end > len(buf):
        return None, off
    p = off + _REC_HDR.size
    name = buf[p:p + name_len].decode()
    p += name_len
    schema = schemas.by_name(name)
    cont = RecordContainer(schema)
    for _ in range(nrows):
        (pk_len,) = struct.unpack_from("<H", buf, p)
        p += 2
        pk = PartKey.from_bytes(buf[p:p + pk_len])
        p += pk_len
        (ts,) = struct.unpack_from("<q", buf, p)
        p += 8
        vals, p = _decode_values(schema, buf, p)
        cont.add(pk, ts, *vals)
    return cont, end


# producer and consumer sides may be different THREADS in one process
# (embedded gateway + ingest driver): the writer handle, the record
# position index, and the valid-prefix watermark all ride one lock
@guarded_by("_lock", "_write_f", "_positions", "_valid_end",
            "_last_sync_t", "_unsynced_bytes")
class LogIngestionStream(IngestionStream):
    """Durable file-backed stream: one append-only framed log per shard —
    the Kafka-partition analogue (1 shard <-> 1 log, KafkaIngestionStream).

    Producer side uses ``append``; consumer side polls ``read``.  The two
    may be different processes: the reader tails the file, stopping at any
    incomplete tail record until the writer finishes it.

    Group-commit fsync: per-append ``os.fsync`` was the residual
    episodic stall on shared container disks (ROADMAP follow-up — one
    slow fsync froze the ingest thread mid-batch). With
    ``group_commit_s > 0`` appends write+flush but fsync only when the
    time window elapses or ``group_commit_bytes`` accumulate unsynced —
    the Kafka ``log.flush.interval`` shape. The durability window is
    bounded by exactly those two knobs; ``sync()`` forces, ``close()``
    syncs the tail. ``group_commit_s = 0`` (the default) keeps the
    strict fsync-per-append behavior. Every real fsync observes
    ``filodb_ingest_fsync_seconds`` so the stall the ROADMAP saw is
    visible data, not a guess."""

    def __init__(self, path: str, schemas: Schemas,
                 group_commit_s: float = 0.0,
                 group_commit_bytes: int = 1 << 20):
        self.path = path
        self.schemas = schemas
        self.group_commit_s = float(group_commit_s)
        self.group_commit_bytes = int(group_commit_bytes)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._write_f = None
        self._lock = threading.Lock()
        # reader state: byte positions of each complete record
        self._positions: List[int] = []
        self._valid_end = 0
        # group-commit state: when the last fsync happened and how many
        # bytes are flushed-but-unsynced since
        self._last_sync_t = 0.0
        self._unsynced_bytes = 0
        self.appends = 0
        self.fsyncs = 0

    # -- producer side ----------------------------------------------------
    def append(self, container: RecordContainer, fsync: bool = True) -> int:
        """Publish one container; returns its offset (ordinal).  One writer
        per shard log (the shard<->partition ownership invariant); on
        takeover, a torn tail left by a crashed writer is truncated so the
        new append lands on a record boundary."""
        import time as _time
        t0 = _time.perf_counter()
        data = encode_container(container)
        with self._lock:
            if self._write_f is None:
                self._refresh_locked()
                if os.path.exists(self.path) and \
                        os.path.getsize(self.path) > self._valid_end:
                    os.truncate(self.path, self._valid_end)
                self._write_f = open(self.path, "ab")
            off = len(self._positions)
            self._write_f.write(data)
            self._write_f.flush()
            self._unsynced_bytes += len(data)
            if fsync:
                # graftlint: disable=lock-blocking-reachable (single-writer WAL: the lock IS the producer/consumer serialization; group commit bounds the fsync window)
                self._maybe_fsync_locked()
            self._positions.append(self._valid_end)
            self._valid_end += len(data)
            self.appends += 1
        obs_metrics.observe("filodb_ingest_append_seconds", _APPEND_HELP,
                            _time.perf_counter() - t0,
                            obs_metrics.FSYNC_BUCKETS_S)
        return off

    def _maybe_fsync_locked(self, force: bool = False) -> None:
        """Group commit: fsync now when forced, when group commit is
        off, or when the time/size bound tripped; otherwise leave the
        bytes flushed-but-unsynced (the bounded durability window)."""
        import time as _time
        if self._unsynced_bytes == 0:
            return
        now = _time.monotonic()
        if not force and self.group_commit_s > 0:
            if (now - self._last_sync_t < self.group_commit_s
                    and self._unsynced_bytes < self.group_commit_bytes):
                return
        t0 = _time.perf_counter()
        os.fsync(self._write_f.fileno())
        obs_metrics.observe("filodb_ingest_fsync_seconds", _FSYNC_HELP,
                            _time.perf_counter() - t0,
                            obs_metrics.FSYNC_BUCKETS_S)
        self.fsyncs += 1
        self._last_sync_t = now
        self._unsynced_bytes = 0

    def sync(self) -> None:
        """Force-fsync any unsynced tail (checkpoint barriers)."""
        with self._lock:
            if self._write_f is not None:
                # graftlint: disable=lock-blocking-reachable (checkpoint barrier: readers must not observe the log mid-sync)
                self._maybe_fsync_locked(force=True)

    # -- consumer side ----------------------------------------------------
    def _refresh_locked(self) -> int:
        """Extend the position index over newly appended bytes; returns the
        current record count."""
        if not os.path.exists(self.path):
            return 0
        size = os.path.getsize(self.path)
        if size <= self._valid_end:
            return len(self._positions)
        with open(self.path, "rb") as f:
            f.seek(self._valid_end)
            buf = f.read(size - self._valid_end)
        p = 0
        while p + _REC_HDR.size <= len(buf):
            magic, name_len, _, payload_len = _REC_HDR.unpack_from(buf, p)
            if magic != _REC_MAGIC:
                # corrupt bytes mid-log: stop indexing here permanently
                break
            end = p + _REC_HDR.size + name_len + payload_len
            if end > len(buf):
                break                      # torn tail: writer mid-append
            self._positions.append(self._valid_end + p)
            p = end
        self._valid_end += p
        return len(self._positions)

    def read(self, from_offset: int, max_records: int = 64
             ) -> List[SomeData]:
        with self._lock:
            n = self._refresh_locked()
            lo = max(0, from_offset)
            hi = min(n, lo + max_records)
            if lo >= hi:
                return []
            positions = self._positions[lo:hi]
            valid_end = self._valid_end
        out: List[SomeData] = []
        with open(self.path, "rb") as f:
            f.seek(positions[0])
            buf = f.read(valid_end - positions[0])
        for i, pos in enumerate(positions):
            cont, _ = decode_container(buf, pos - positions[0], self.schemas)
            if cont is None:
                break
            out.append(SomeData(cont, lo + i))
        return out

    def end_offset(self) -> int:
        with self._lock:
            return self._refresh_locked()

    def close(self) -> None:
        with self._lock:
            if self._write_f is not None:
                # sync the group-commit tail: a clean close must not
                # leave the durability window open
                # graftlint: disable=lock-blocking-reachable (close-time tail sync; no reader may race the handle teardown)
                self._maybe_fsync_locked(force=True)
                self._write_f.close()
                self._write_f = None
