"""Ingest-write health: the clean ENOSPC degradation state machine.

Before this module, a full disk surfaced as an unhandled OSError in
whatever thread happened to hit it first — a gateway producer thread
dying mid-connection or an ingestion driver flipping its shard to
ERROR. The failure is environmental and RECOVERABLE (space gets
freed), so it deserves a state, not a stack trace:

  * any write-path ENOSPC/EDQUOT flips the process to **ingest
    read-only**: remote ingest answers 503 + Retry-After, the gateway
    drops (and counts) lines instead of crashing handler threads, and
    flushes retry on their normal cadence — queries keep serving
    throughout.
  * recovery is AUTOMATIC: while read-only, one probe write per
    ``probe_interval_s`` is let through; the first success clears the
    state. No operator restart required after freeing space.

The state is process-global (one disk per process in every supported
deployment) and surfaced in the health body (``ingest_read_only``),
``/metrics`` (``filodb_ingest_read_only`` gauge) and the structured
event ring."""

from __future__ import annotations

import errno
import threading
import time
from typing import Dict, Optional

from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.obs import events as obs_events
from filodb_tpu.obs import metrics as obs_metrics

_RO_HELP = ("1 while ingest is degraded to read-only (write-path "
            "ENOSPC/EDQUOT); queries keep serving")
_OUT_OF_SPACE_ERRNOS = (errno.ENOSPC, getattr(errno, "EDQUOT", errno.ENOSPC))


class IngestReadOnly(RuntimeError):
    """Ingest is degraded to read-only; the HTTP edge maps this to
    503 + Retry-After (recoverable: resubmit after space is freed)."""

    def __init__(self, reason: str, retry_after_s: float = 1.0):
        super().__init__(reason)
        self.retry_after_s = retry_after_s


def is_out_of_space(exc: BaseException) -> bool:
    return (isinstance(exc, OSError)
            and exc.errno in _OUT_OF_SPACE_ERRNOS)


@guarded_by("_lock", "_read_only", "_reason", "_since", "_last_probe_t")
class IngestHealth:
    """Process-wide ingest writability state with rate-limited
    recovery probes. Writers report outcomes (``note_write_error`` /
    ``note_write_ok``); edges consult ``read_only()`` and claim probe
    slots via ``should_probe()``."""

    def __init__(self, probe_interval_s: float = 1.0):
        self.probe_interval_s = float(probe_interval_s)
        self._lock = threading.Lock()
        self._read_only = False
        self._reason = ""
        self._since = 0.0
        self._last_probe_t = 0.0

    def read_only(self) -> bool:
        with self._lock:
            return self._read_only

    def reason(self) -> str:
        with self._lock:
            return self._reason

    def note_write_error(self, exc: BaseException, where: str) -> bool:
        """Report a write-path failure. Returns True when it is the
        out-of-space family (the caller should degrade, not crash);
        other errors are the caller's to handle."""
        if not is_out_of_space(exc):
            return False
        reason = f"{where}: {exc}"
        with self._lock:
            entered = not self._read_only
            self._read_only = True
            self._reason = reason
            if entered:
                self._since = time.monotonic()
        if entered:
            obs_metrics.GLOBAL_REGISTRY.gauge(
                "filodb_ingest_read_only", _RO_HELP).set(1.0)
            obs_events.emit("ingest-read-only", state="entered",
                            where=where, reason=str(exc))
        return True

    def note_write_ok(self) -> None:
        """A write-path success clears the degradation (the probe that
        got through, or any organic write while racing recovery)."""
        with self._lock:
            left = self._read_only
            self._read_only = False
            self._reason = ""
        if left:
            obs_metrics.GLOBAL_REGISTRY.gauge(
                "filodb_ingest_read_only", _RO_HELP).set(0.0)
            obs_events.emit("ingest-read-only", state="recovered")

    def probe_due(self) -> bool:
        """Peek: would a probe be allowed now? (Non-claiming — the
        fast-path 503 check.)"""
        with self._lock:
            if not self._read_only:
                return True
            return (time.monotonic() - self._last_probe_t
                    >= self.probe_interval_s)

    def should_probe(self) -> bool:
        """Claim the probe slot: True at most once per interval while
        read-only (that caller attempts the real write)."""
        with self._lock:
            if not self._read_only:
                return True
            now = time.monotonic()
            if now - self._last_probe_t < self.probe_interval_s:
                return False
            self._last_probe_t = now
            return True

    def retry_after_s(self) -> float:
        return max(1.0, self.probe_interval_s)

    def snapshot(self) -> Dict:
        with self._lock:
            return {"read_only": self._read_only, "reason": self._reason}

    def reject(self) -> IngestReadOnly:
        """The exception the ingest edge raises while degraded."""
        with self._lock:
            reason = self._reason or "ingest is read-only"
        return IngestReadOnly(f"ingest degraded to read-only "
                              f"({reason}); retry after space is freed",
                              retry_after_s=self.retry_after_s())

    def reset(self) -> None:
        """Test hook."""
        with self._lock:
            self._read_only = False
            self._reason = ""
            self._last_probe_t = 0.0


GLOBAL = IngestHealth()
