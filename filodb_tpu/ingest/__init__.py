"""Streaming ingestion: stream sources, per-shard drivers, recovery.

(Reference packages: kafka/ + coordinator IngestionActor/IngestionStream.)
"""

from filodb_tpu.ingest.driver import IngestionDriver, start_ingestion
from filodb_tpu.ingest.stream import (IngestionStream, LogIngestionStream,
                                      MemoryIngestionStream, SomeData,
                                      decode_container, encode_container)

__all__ = [
    "IngestionDriver", "start_ingestion", "IngestionStream",
    "LogIngestionStream", "MemoryIngestionStream", "SomeData",
    "decode_container", "encode_container",
]
