"""Streaming ingestion: stream sources, per-shard drivers, recovery.

(Reference packages: kafka/ + coordinator IngestionActor/IngestionStream.)

The driver imports are lazy (PEP 562): ``IngestionDriver`` pulls in the
memstore and therefore jax, which offline tools walking durable files
(``python -m filodb_tpu.fsck``) must not pay for just to reach the
stream codec.
"""

from filodb_tpu.ingest.stream import (IngestionStream, LogIngestionStream,
                                      MemoryIngestionStream, SomeData,
                                      decode_container, encode_container)

__all__ = [
    "IngestionDriver", "start_ingestion", "IngestionStream",
    "LogIngestionStream", "MemoryIngestionStream", "SomeData",
    "decode_container", "encode_container",
]


def __getattr__(name):
    if name in ("IngestionDriver", "start_ingestion"):
        from filodb_tpu.ingest import driver
        return getattr(driver, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
