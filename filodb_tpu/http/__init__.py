"""HTTP API edge (reference: http/FiloHttpServer.scala:23,
PrometheusApiRoute.scala:42, HealthRoute, ClusterApiRoute)."""

from filodb_tpu.http.server import FiloHttpServer

__all__ = ["FiloHttpServer"]
