"""Threaded HTTP server exposing the Prometheus API over the memstore.

Routes mirror the reference (http/PrometheusApiRoute.scala:48-129,
HealthRoute.scala, ClusterApiRoute.scala):

  GET/POST /promql/{dataset}/api/v1/query_range?query&start&end&step
  GET/POST /promql/{dataset}/api/v1/query?query&time
  GET      /promql/{dataset}/api/v1/labels
  GET      /promql/{dataset}/api/v1/label/{name}/values
  GET      /promql/{dataset}/api/v1/series?match[]=<selector>&start&end
  GET      /__health | /__liveness
  GET      /api/v1/cluster/{dataset}/status

stdlib http.server (the JVM reference uses Akka-HTTP; the edge is not the
hot path — all bulk compute is device-side behind QueryEngine)."""

from __future__ import annotations

import functools
import json
import re
import sys
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from filodb_tpu.http import prom_json
from filodb_tpu.ingest import health as ingest_health
from filodb_tpu.lint import capacity as lint_capacity
from filodb_tpu.lint.caches import publishes
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs import events as obs_events
from filodb_tpu.obs import devprof as obs_devprof
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.obs.profiler import SamplingProfiler
from filodb_tpu.obs.selfmon import SELFMON_DATASET
from filodb_tpu.obs.slowlog import InflightRegistry, SlowQueryLog
from filodb_tpu.obs.trace import Tracer
from filodb_tpu.parallel.resilience import (Deadline, DeadlineExceeded,
                                            PeerResilience)
from filodb_tpu.promql.parser import (TimeStepParams, parse_query,
                                      parse_query_range, selector_to_filters)
from filodb_tpu.query import logical as lp
from filodb_tpu.query import qos
from filodb_tpu.testing import chaos
from filodb_tpu.query.engine import QueryEngine  # noqa: F401 (re-export)
from filodb_tpu.query.planner import QueryPlanner
from filodb_tpu.query.model import (GridResult, QueryError, QueryLimitError,
                                    QueryLimits, ScalarResult,
                                    StaleRoutingError)

_ROUTE = re.compile(r"^/promql/(?P<ds>[^/]+)/api/v1/(?P<rest>.+)$")

# reserved internal datasets: strictly node-local planners (no
# fan-out / mesh / mapper), own cardinality accounting. __selfmon__
# holds self-ingested telemetry; __rules__ holds recording-rule outputs
# and the synthetic ALERTS state series (dataset name == tenant name by
# the same convention as __selfmon__).
INTERNAL_DATASETS = (SELFMON_DATASET, qos.RULES_TENANT)

_QLAT_HELP = ("End-to-end query latency in seconds at the HTTP edge "
              "(parse + plan + execute + encode)")


# promlint findings per (query text, schema snapshot): queries repeat
# (dashboards), the analysis is pure, and the hot path must not re-walk
# the AST per refresh
@functools.lru_cache(maxsize=512)
def _lint_memo(query: str, schema_items: Tuple) -> Tuple:
    from filodb_tpu.promql import semant
    schemas = semant.MetricSchemas(dict(schema_items))
    return tuple(semant.lint_query(query, schemas))


class _Handled(Exception):
    """Control-flow: response (code, payload) already decided."""


class _FastHeaders(dict):
    """Case-insensitive header map for the fast request-parse path
    (keys stored lower-cased)."""

    def get(self, name, default=None):  # noqa: A003 — dict interface
        return dict.get(self, name.lower(), default)

    def __contains__(self, name):
        return dict.__contains__(self, str(name).lower())


class FiloHttpServer:
    """Serves one or more datasets; each maps to a list of shards."""

    def __init__(self, shards_by_dataset: Dict[str, list],
                 backend: Optional[object] = None,
                 shard_mapper: Optional[object] = None,
                 mesh_executor: Optional[object] = None,
                 spread: int = 1,   # MUST match ingest spread (default-spread)
                 host: str = "127.0.0.1", port: int = 0,
                 ds_store_by_dataset: Optional[Dict[str, object]] = None,
                 raw_retention_ms: int = 0,
                 query_limits: Optional[QueryLimits] = None,
                 spread_provider: Optional[object] = None,
                 node_id: Optional[str] = None,
                 peers: Optional[Dict[str, str]] = None,
                 buddies: Optional[Dict[str, str]] = None,
                 partitions: Optional[Dict[str, str]] = None,
                 local_partitions: Optional[List[str]] = None,
                 grpc_peers: Optional[Dict[str, str]] = None,
                 grpc_partitions: Optional[Dict[str, str]] = None,
                 query_timeout_s: float = 30.0,
                 resilience: Optional[PeerResilience] = None,
                 plan_cache_size: int = 256,
                 results_cache_mb: float = 64.0,
                 results_cache_hot_window_ms: float = 10_000.0,
                 max_inflight_queries: int = 4,
                 admission_wait_s: float = 5.0,
                 qos_budgets: Optional[qos.TenantBudgets] = None,
                 qos_degrade_max_steps: int = 64,
                 qos_shed_degraded: bool = True,
                 tracer: Optional[Tracer] = None,
                 slow_query_ms: float = 1000.0,
                 slow_query_capacity: int = 128,
                 peer_fanout_workers: int = 0,
                 worker_id: Optional[int] = None,
                 profiler: Optional[SamplingProfiler] = None):
        self.shards_by_dataset = shards_by_dataset
        self.backend = backend
        self.shard_mapper = shard_mapper
        self.mesh_executor = mesh_executor
        self.spread = spread
        self.ds_store_by_dataset = ds_store_by_dataset or {}
        self.raw_retention_ms = raw_retention_ms
        self.query_limits = query_limits
        self.spread_provider = spread_provider
        # multi-process cluster plane (parallel/cluster.py): this node's id
        # + peer node_id -> base URL for leaf dispatch and metadata fan-out
        self.node_id = node_id
        self.peers = dict(peers or {})
        self.buddies = dict(buddies or {})
        self.partitions = dict(partitions or {})
        self.local_partitions = list(local_partitions or ())
        self.grpc_peers = dict(grpc_peers or {})
        self.grpc_partitions = dict(grpc_partitions or {})
        # degraded-mode execution: default per-query deadline budget +
        # the server-lifetime retry policy / breaker registry (breaker
        # state persists across queries by construction)
        self.query_timeout_s = float(query_timeout_s)
        if resilience is None:
            from filodb_tpu.parallel.resilience import (BreakerRegistry,
                                                        RetryPolicy)
            resilience = PeerResilience(RetryPolicy(), BreakerRegistry())
        self.resilience = resilience
        # set by the standalone server: FailureDetector whose down-view
        # rides the health body (quorum input for elastic reassignment)
        self.detector = None
        # set by the standalone server: MembershipManager behind the
        # /admin/{drain,adopt,transfer,abort_adopt} endpoints
        self.membership = None
        # elastic membership read-path state:
        #  * handoff_sources — shard -> previous-owner node for shards
        #    THIS node is adopting mid-handoff; the planner redirects
        #    reads there until the replay flips ACTIVE, so no query
        #    ever sees a half-replayed copy;
        #  * peer_watermarks — gossiped per-peer ingest watermarks /
        #    backfill epochs (FailureDetector peer_state_sink) stamped
        #    onto remote shard groups for results-cache freshness;
        #  * stale-routing counters for /metrics.
        self.handoff_sources: Dict[int, str] = {}
        self.peer_watermarks: Dict[str, Dict] = {}
        self.stale_routing_bounces = 0
        self.stale_routing_retries = 0
        # observability spine (filodb_tpu.obs): the tracer owns the
        # sampling decision + the bounded ring behind /debug/traces;
        # the slow-query log and in-flight registry serve
        # /debug/slow_queries and /debug/queries. Tracing defaults OFF
        # — span() stays on its no-op path and responses are
        # byte-identical to the untraced build.
        self.tracer = tracer if tracer is not None \
            else Tracer(enabled=False, node=node_id or "")
        self.slow_log = SlowQueryLog(threshold_ms=float(slow_query_ms),
                                     capacity=int(slow_query_capacity))
        self.inflight = InflightRegistry()
        # set by the standalone server under --profiler (or injected by
        # tests): the wall-clock sampling profiler behind /debug/profile.
        # None (the default) keeps the endpoint a 404 and the metrics
        # surface untouched.
        self.profiler = profiler
        # admission control on the QUERY endpoints (query/qos.py): with
        # hundreds of keep-alive connections, unbounded in-flight
        # handlers thrash the GIL (every runnable thread pays switch-
        # interval preemptions); excess requests park on the
        # controller's semaphore and are admitted FIFO-ish as slots
        # free — but the wait is BOUNDED (admission_wait_s): saturation
        # answers 429 + Retry-After instead of hanging until the
        # client's own timeout. Per-tenant token-bucket budgets make
        # the shed SELECTIVE: the over-budget tenant degrades/throttles
        # while everyone else sails through. Metadata, health, and
        # cluster-plane endpoints bypass the gate.
        self.admission = qos.AdmissionController(
            max_inflight=max(1, int(max_inflight_queries))
            if max_inflight_queries else 0,
            wait_s=float(admission_wait_s),
            budgets=qos_budgets)
        # brownout ladder knobs: coarsen rung targets at most this many
        # evaluation steps; False turns the whole ladder off (over-
        # budget goes straight to 429)
        self.qos_degrade_max_steps = int(qos_degrade_max_steps)
        self.qos_shed_degraded = bool(qos_shed_degraded)
        # set by the standalone server on the worker that owns the
        # gateway: the GatewayServer behind /api/v1/ingest/influx (the
        # remote-ingest edge with real backpressure — 503 + Retry-After
        # while ingest is degraded to read-only)
        self.gateway = None
        # set by the standalone server: TenantMetering (per-tenant
        # cardinality gauges; also the cost estimator's fan-out
        # cardinality view via make_planner)
        self.tenant_metering = None
        # set by the standalone server under --self-monitor: the
        # SelfMonitor loop (obs/selfmon.py) whose liveness gauges ride
        # /metrics
        self.selfmon = None
        # set by the standalone server when rules are configured: the
        # RulesEngine (filodb_tpu/rules) behind /api/v1/rules and
        # /api/v1/alerts; its evaluations call rule_eval_range below
        self.rules = None
        # serving fast path: parsed-plan LRU (start/end abstracted out of
        # the key; dashboards re-issuing the same text skip parse+plan).
        # Invalidation: shard-topology events from the mapper, plus the
        # explicit invalidate_plan_cache() hook for schema changes.
        from filodb_tpu.query.plancache import PlanCache
        self.plan_cache = PlanCache(capacity=plan_cache_size)
        if shard_mapper is not None:
            try:
                shard_mapper.subscribe(
                    lambda ev: self.plan_cache.invalidate("topology"))
            except Exception:       # mapper without event support
                pass
        # incremental range-query results cache (query/resultcache.py):
        # per-step matrix extents keyed on the plan cache's range-
        # abstracted key + step alignment; sliding-window dashboard
        # re-issues recompute only the uncovered tail. Topology/schema
        # invalidation rides the plan cache's listener hook; freshness
        # is bounded by shard ingest watermarks + the hot window.
        from filodb_tpu.query.resultcache import ResultCache
        self.result_cache = ResultCache(
            max_bytes=int(float(results_cache_mb) * (1 << 20)),
            hot_window_ms=float(results_cache_hot_window_ms))
        self.plan_cache.add_invalidation_listener(
            self.result_cache.invalidate)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: load clients (and peers' leaf
            # dispatch) reuse connections instead of paying a TCP
            # handshake + handler-thread spawn per request; every
            # response carries Content-Length, so pipelined handling is
            # safe on the stdlib server
            protocol_version = "HTTP/1.1"
            # without TCP_NODELAY the stdlib server's small header
            # writes hit the Nagle + delayed-ACK interaction: every
            # response on a persistent connection stalls ~40ms
            disable_nagle_algorithm = True
            # buffer the response writes (status line + each header is
            # its own write() when unbuffered -> one syscall and one
            # packet per header); flushed per request by handle()
            wbufsize = 64 * 1024

            def log_message(self, fmt, *args):   # quiet
                pass

            def parse_request(self):
                """Fast path for plain HTTP/1.0-1.1 requests: the stock
                parser routes headers through email.parser at ~0.2ms per
                request — a third of the serving fast path's budget.
                Anything unusual (odd request line, HTTP/0.9, oversized
                headers) falls back to the stock parser, which re-reads
                from ``raw_requestline`` (no header bytes consumed)."""
                line = str(self.raw_requestline, "iso-8859-1")
                words = line.rstrip("\r\n").split()
                if len(words) != 3 or words[2] not in ("HTTP/1.1",
                                                       "HTTP/1.0"):
                    return BaseHTTPRequestHandler.parse_request(self)
                self.requestline = line.rstrip("\r\n")
                self.command, self.path, self.request_version = words
                headers = _FastHeaders()
                prev = None
                while True:
                    raw = self.rfile.readline(65537)
                    if len(raw) > 65536:
                        self.send_error(431)
                        return False
                    if raw in (b"\r\n", b"\n", b""):
                        break
                    if raw[:1] in (b" ", b"\t") and prev is not None:
                        headers[prev] += " " + raw.strip().decode(
                            "iso-8859-1")
                        continue
                    k, _, v = raw.partition(b":")
                    prev = k.decode("iso-8859-1").strip().lower()
                    headers[prev] = v.strip().decode("iso-8859-1")
                self.headers = headers
                conntype = headers.get("connection", "").lower()
                if conntype == "close":
                    self.close_connection = True
                elif self.request_version == "HTTP/1.1":
                    self.close_connection = False
                else:
                    self.close_connection = conntype != "keep-alive"
                if headers.get("expect", "").lower() == "100-continue" \
                        and self.protocol_version >= "HTTP/1.1" \
                        and self.request_version >= "HTTP/1.1":
                    if not self.handle_expect_100():
                        return False
                return True

            def do_GET(self):
                outer._handle(self)

            def do_POST(self):
                outer._handle(self)

        class _Server(ThreadingHTTPServer):
            # stdlib default listen backlog is 5: a burst of concurrent
            # clients overflows it and every overflowed connect stalls
            # a full SYN-retransmission timeout (~1s) before the
            # handshake completes — raise it to serving levels
            request_queue_size = 128

            # same logical root as _handle below, but marked at the
            # per-connection thread's SPAWN TARGET: samples taken while
            # the stdlib is parsing the request line or flushing the
            # response (no _handle frame on the stack yet/any more)
            # still attribute to "http-handler"
            @thread_root("http-handler")
            def process_request_thread(self, request, client_address):
                ThreadingHTTPServer.process_request_thread(
                    self, request, client_address)

        self.httpd = _Server((host, port), Handler)
        self.port = self.httpd.server_port
        self._thread: Optional[threading.Thread] = None
        # metadata/cardinality peer fan-out concurrency: was a
        # hard-coded min(8, len(targets)) — size it from the knob
        # (0 = auto from the host's core count) and surface it in
        # /metrics so operators can see what a node actually uses
        if peer_fanout_workers and int(peer_fanout_workers) > 0:
            self.fanout_workers = int(peer_fanout_workers)
        else:
            import os
            self.fanout_workers = min(32, max(2, os.cpu_count() or 2))
        # process-sharded serving: this worker's ordinal in a
        # supervisor deployment (None = standalone single process).
        # Rides /metrics so the supervisor's aggregate view can tell
        # workers apart even before it injects its own worker label.
        self.worker_id = worker_id
        # extra accept edges (process-sharded serving): SO_REUSEPORT /
        # inherited-fd listener sockets whose accept loops feed the
        # same ThreadingHTTPServer machinery as the private port
        self._extra_listeners: list = []

    # -- lifecycle --------------------------------------------------------
    @thread_root("accept-edge")
    def _serve_private(self) -> None:
        # the private-port accept loop shares the "accept-edge" root
        # with add_listener's extra edges: one inventory entry for
        # "thread that accepts connections", and a frame the sampling
        # profiler can attribute
        self.httpd.serve_forever()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._serve_private,
                                        daemon=True, name="accept-edge")
        self._thread.start()

    def add_listener(self, sock) -> None:
        """Attach an extra listening socket (the shared public accept
        edge in a multi-worker deployment: an SO_REUSEPORT-bound socket,
        or one inherited from the supervisor where SO_REUSEPORT is
        unavailable). Accepted connections are handled by the same
        per-connection handler threads as the private port — one HTTP
        surface, two accept edges."""
        import socket as _socket

        @thread_root("accept-edge")
        def _accept_loop():
            while True:
                try:
                    conn, addr = sock.accept()
                except OSError:
                    return          # socket closed on stop()
                try:
                    # ThreadingMixIn spawns the handler thread; the
                    # handler applies keep-alive/NODELAY itself
                    self.httpd.process_request(conn, addr)
                except Exception:   # noqa: BLE001 — edge must not die
                    try:
                        conn.close()
                    except OSError:
                        pass
        t = threading.Thread(target=_accept_loop, daemon=True,
                             name=f"accept-edge-{len(self._extra_listeners)}")
        self._extra_listeners.append((sock, t))
        if isinstance(sock, _socket.socket):
            sock.settimeout(None)
        t.start()

    def stop(self) -> None:
        for sock, _t in self._extra_listeners:
            try:
                sock.close()
            except OSError:
                pass
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- request handling -------------------------------------------------
    # the stdlib ThreadingHTTPServer spawns one handler thread per
    # connection — the AST engine cannot see that spawn, so the entry
    # point is marked explicitly: every query/admin path below runs on
    # one of these roots concurrently with the ingest/detector/worker
    # threads
    @thread_root("http-handler")
    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        retry_after_s: Optional[float] = None
        try:
            parsed = urllib.parse.urlparse(req.path)
            qs = urllib.parse.parse_qs(parsed.query)
            body_json = None
            body_raw = b""
            if req.command == "POST":
                ln = int(req.headers.get("Content-Length") or 0)
                if ln > (64 << 20):     # request-size cap (DoS guard)
                    code, payload = 413, prom_json.error(
                        "request body too large")
                    raise _Handled()
                body_raw = req.rfile.read(ln) if ln else b""
                ctype = req.headers.get("Content-Type", "")
                if "application/x-www-form-urlencoded" in ctype:
                    for k, v in urllib.parse.parse_qs(
                            body_raw.decode()).items():
                        qs.setdefault(k, []).extend(v)
                elif "application/json" in ctype and body_raw:
                    body_json = json.loads(body_raw)
            # propagated trace context (Dapper-style): a peer hop's
            # header makes this node record spans under the caller's
            # trace and ship them back in the response envelope
            tctx = obs_trace.parse_context(
                req.headers.get(obs_trace.HEADER))
            code, payload = self._route(
                parsed.path, qs, body_json, body_raw, tctx=tctx,
                tenant_hdr=req.headers.get(qos.TENANT_HEADER),
                priority_hdr=req.headers.get(qos.PRIORITY_HEADER))
        except _Handled:
            pass
        except qos.AdmissionRejected as e:
            # admission said no and no degraded answer exists: 429 +
            # Retry-After. Distinct from the 503 deadline path below —
            # a rejected query was never executed, so the client can
            # back off and resubmit as-is.
            code, payload = 429, prom_json.error(str(e), "throttled")
            retry_after_s = e.retry_after_s
        except ingest_health.IngestReadOnly as e:
            # the ingest edge while write-path out-of-space degradation
            # is active: recoverable — resubmit after space is freed
            code, payload = 503, prom_json.error(str(e), "read_only")
            retry_after_s = e.retry_after_s
        except QueryLimitError as e:
            code, payload = 422, prom_json.error(str(e), "query_limit")
        except DeadlineExceeded as e:
            # clean budget-exhaustion error (Prometheus timeout shape),
            # never a hung socket
            code, payload = 503, prom_json.error(str(e), "timeout")
        except QueryError as e:
            code, payload = 400, prom_json.error(str(e))
        except Exception as e:   # noqa: BLE001 — edge must not crash
            code, payload = 500, prom_json.error(str(e), "internal")
        extra_headers = {}
        if retry_after_s is not None:
            extra_headers["Retry-After"] = str(
                max(1, int(retry_after_s + 0.999)))
        if isinstance(payload, prom_json.PreEncoded):
            body = payload.body
            ctype = payload.ctype
        elif isinstance(payload, bytes):  # remote-read protobuf
            body = payload
            ctype = "application/x-protobuf"
            extra_headers["Content-Encoding"] = "snappy"
        elif isinstance(payload, str):  # /metrics exposition text
            body = payload.encode()
            ctype = "text/plain; version=0.0.4"
        else:
            body = json.dumps(payload).encode()
            ctype = "application/json"
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        for k, v in extra_headers.items():
            req.send_header(k, v)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def _route(self, path: str, qs: Dict, body_json=None,
               body_raw: bytes = b"", tctx=None,
               tenant_hdr: Optional[str] = None,
               priority_hdr: Optional[str] = None):
        if path in ("/__health", "/__liveness", "/__readiness"):
            # the health body doubles as status gossip: locally-served
            # shards with their FSM status (peers sync these instead of
            # optimistically flipping adopted shards ACTIVE), plus this
            # node's own down-view of its peers (quorum input for
            # elastic reassignment). FilodbCluster.scala gossip analogue.
            shards_adv: Dict[str, str] = {}
            watermarks: Dict[str, int] = {}
            epochs: Dict[str, int] = {}
            for lst in self.shards_by_dataset.values():
                for i, s in enumerate(lst):
                    n = getattr(s, "shard_num", i)
                    if self.shard_mapper is not None:
                        shards_adv[str(n)] = \
                            self.shard_mapper.status(n).value
                    # per-shard ingest watermark + backfill epoch ride
                    # the health body (ROADMAP 4a): peers stamp them
                    # onto remote shard groups so the results cache's
                    # freshness horizon covers fan-out extents too
                    wm = getattr(s, "ingest_watermark_ms", None)
                    if wm is not None:
                        watermarks[str(n)] = int(wm)
                    epochs[str(n)] = int(getattr(
                        s, "ingest_backfill_epoch", 0) or 0)
            down = (sorted(self.detector.down_peers())
                    if self.detector is not None else [])
            # storage-integrity flags: per-shard quarantined-record
            # counts and which shards degraded to read-only, plus the
            # process-wide ENOSPC ingest-read-only state
            quarantined: Dict[str, int] = {}
            integrity_ro: List[str] = []
            for lst in self.shards_by_dataset.values():
                for i, s in enumerate(lst):
                    n = getattr(s, "shard_num", i)
                    q = int(getattr(
                        s, "integrity_quarantined_records", 0) or 0)
                    if q:
                        quarantined[str(n)] = q
                    if getattr(s, "integrity_read_only", False):
                        integrity_ro.append(str(n))
            body = {"status": "healthy", "shards": shards_adv,
                    "down_peers": down,
                    "watermarks": watermarks,
                    "backfill_epochs": epochs,
                    "ingest_read_only":
                        ingest_health.GLOBAL.read_only(),
                    "integrity": {"quarantined": quarantined,
                                  "read_only_shards": integrity_ro}}
            if self.shard_mapper is not None \
                    and hasattr(self.shard_mapper, "topology_epoch"):
                body["topo_epoch"] = self.shard_mapper.topology_epoch
            mem = self.membership
            if mem is not None:
                body["draining"] = bool(mem.draining)
            gs = getattr(self, "grpc_server", None)
            if gs is not None:
                # advertise the data-plane port; peers combine it with
                # this node's known host (gossip discovery for
                # ephemeral-port deployments)
                body["grpc_port"] = gs.port
            # introspection: which peers this node has discovered
            body["grpc_peers"] = dict(self.grpc_peers)
            return 200, body
        if path == "/metrics":
            # ?exemplars=1: content-negotiated OpenMetrics exemplar
            # suffixes on histogram buckets (metric -> trace links);
            # the plain exposition stays byte-identical without it
            want_ex = (self._param(qs, "exemplars", "")
                       or "").lower() in ("1", "true", "yes")
            return 200, self._metrics_text(exemplars=want_ex)
        if path.startswith("/admin/"):
            return self._admin(path, qs, body_json)
        if path == "/debug/traces":
            return 200, self._debug_traces(qs)
        if path == "/debug/profile":
            return self._debug_profile(qs)
        if path == "/debug/queries":
            return 200, {"status": "success",
                         "data": self.inflight.snapshot()}
        if path == "/debug/threads":
            # the @thread_root inventory: every registered thread entry
            # point with its module-qualified root function, the
            # @guarded_by summary of its class, and which live threads
            # currently run it (joined against threading.enumerate())
            from filodb_tpu.lint.threads import thread_inventory
            return 200, {"status": "success",
                         "data": thread_inventory()}
        if path == "/debug/events":
            # the structured operational journal (obs/events.py):
            # corruption detections, quarantine actions, integrity and
            # ingest-read-only transitions — newest first
            limit = int(self._param(qs, "limit", "100") or 100)
            kind = self._param(qs, "kind", None)
            return 200, {"status": "success",
                         "data": obs_events.snapshot(limit=limit,
                                                     kind=kind)}
        if path == "/api/v1/ingest/influx":
            return self._ingest_influx(body_raw)
        if path == "/debug/slow_queries":
            limit = int(self._param(qs, "limit", "50") or 50)
            return 200, {"status": "success",
                         "summary": self.slow_log.snapshot(),
                         "data": self.slow_log.records(limit)}
        if path == "/api/v1/rules":
            return self._rules_api(qs)
        if path == "/api/v1/alerts":
            return self._alerts_api(qs)
        m = re.match(r"^/api/v1/cluster/(?P<ds>[^/]+)/status$", path)
        if m:
            return 200, self._cluster_status(m.group("ds"))
        m = re.match(r"^/api/v1/raw/(?P<ds>[^/]+)$", path)
        if m:
            return self._raw_dispatch(m.group("ds"), body_json,
                                      tctx=tctx)
        m = re.match(r"^/api/v1/cardinality/(?P<ds>[^/]+)$", path)
        if m:
            return self._cardinality(m.group("ds"), qs)
        m = re.match(r"^/api/v1/cardinality-local/(?P<ds>[^/]+)$", path)
        if m:
            return self._cardinality(m.group("ds"), qs, local=True)
        m = _ROUTE.match(path)
        if not m:
            return 404, prom_json.error(f"no route for {path}", "not_found")
        ds, rest = m.group("ds"), m.group("rest")
        # dispatch=local: a forwarded query must evaluate on this node's
        # shards only (no fan-back-out; loop prevention for pushdown —
        # federation forwarding is likewise disabled)
        local_dispatch = self._param(qs, "dispatch") == "local"
        # degraded-mode knobs: per-query deadline budget (&timeout=,
        # Prom-style) + opt-in partial responses (&allow_partial=true,
        # the Thanos partial_response analogue; default fail-fast)
        timeout_s = self._parse_duration_s(
            self._param(qs, "timeout"), self.query_timeout_s)
        deadline = Deadline.after(timeout_s)
        allow_partial = (self._param(qs, "allow_partial", "")
                         or "").lower() in ("true", "1", "yes")
        # &cache=false: results-cache escape hatch — this query neither
        # reads nor seeds the cache, and pushdown hops propagate the flag
        no_cache = (self._param(qs, "cache", "")
                    or "").lower() in ("false", "0", "no")
        # stale-routing bounce (pushdown plane): a dispatch=local hop
        # names the shards the entry node expects this peer to serve;
        # if a handoff moved one away, bounce with the new owners
        # instead of silently evaluating over a subset
        if local_dispatch and rest in ("query_range", "query"):
            raw_expect = self._param(qs, "expect_shards")
            if raw_expect:
                try:
                    want = [int(x) for x in raw_expect.split(",") if x]
                except ValueError:
                    raise QueryError(
                        f"bad expect_shards {raw_expect!r}")
                missing = [n for n in want
                           if n not in self._local_shard_nums(ds)]
                if missing:
                    return 200, self._stale_routing_payload(missing)

        def mk_engine():
            eng = self.make_planner(ds, local_dispatch=local_dispatch,
                                    deadline=deadline,
                                    allow_partial=allow_partial,
                                    no_result_cache=no_cache)
            if eng is None:
                raise QueryError(f"dataset {ds} not set up")
            return eng
        if rest == "query_range":
            fn = lambda eng: self._query_range(eng, qs, ds, tctx=tctx)
        elif rest == "query":
            fn = lambda eng: self._query_instant(eng, qs, ds, tctx=tctx)
        else:
            fn = None
        if fn is not None:
            # tenant QoS: identity from &tenant= / X-Filo-Tenant (by
            # convention the workspace), priority class from
            # &priority= / X-Filo-Priority. A dispatch=local hop is a
            # fan-out LEG: the entry node already made the admission
            # decision, so the leg force-charges and never sheds. The
            # reserved __selfmon__ tenant (self-telemetry + the
            # standing rules workload) likewise charges FORCED — its
            # queries must not bounce off a drained bucket — and runs
            # at the background class unless a priority was explicit.
            tenant = (self._param(qs, "tenant") or tenant_hdr
                      or qos.DEFAULT_TENANT)
            raw_priority = self._param(qs, "priority") or priority_hdr
            priority = qos.parse_priority(raw_priority)
            selfmon_tenant = tenant in qos.INTERNAL_TENANTS
            if selfmon_tenant and not raw_priority:
                priority = qos.PRIORITY_BACKGROUND
            qctx = qos.QosContext(
                tenant=tenant, priority=priority,
                forced=local_dispatch or selfmon_tenant)
            chaos.fire("qos.admit", tenant=qctx.tenant, endpoint=rest)
            adm = self.admission
            try:
                if adm is None or not adm.gated:
                    with qos.activate(qctx):
                        code, payload = self._run_query_routing_retry(
                            mk_engine, fn)
                else:
                    with adm.slot(tenant=qctx.tenant):
                        with qos.activate(qctx):
                            code, payload = \
                                self._run_query_routing_retry(
                                    mk_engine, fn)
            except qos.AdmissionRejected as e:
                # host saturation leaves one free rung: a stale cached
                # extent costs neither a slot nor compute. Over-budget
                # rejections already walked the full ladder — re-raise.
                if e.reason != "saturated" or rest != "query_range":
                    raise
                out = self._shed_stale_saturated(ds, qs, qctx, deadline,
                                                 no_cache)
                if out is None:
                    raise
                code, payload = out
            if local_dispatch and isinstance(payload, dict) \
                    and self.shard_mapper is not None \
                    and hasattr(self.shard_mapper, "topology_epoch"):
                # a pushdown hop's response carries the responder's
                # topology epoch alongside the result (client-facing
                # responses are untouched — this is the peer plane)
                payload["topo_epoch"] = self.shard_mapper.topology_epoch
            return code, payload
        engine = self.make_planner(ds, local_dispatch=local_dispatch,
                                   deadline=deadline,
                                   allow_partial=allow_partial,
                                   no_result_cache=no_cache)
        if engine is None:
            return 400, prom_json.error(f"dataset {ds} not set up")
        if rest == "labels":
            return self._labels(engine, qs, ds)
        lm = re.match(r"^label/(?P<name>[^/]+)/values$", rest)
        if lm:
            return self._label_values(engine, lm.group("name"), qs, ds)
        if rest == "series":
            return self._series(engine, qs, ds)
        if rest == "read":
            return self._remote_read(ds, body_raw)
        return 404, prom_json.error(f"no route for {path}", "not_found")

    # -- elastic membership admin plane -----------------------------------
    def _admin(self, path: str, qs: Dict, body: Optional[Dict]):
        """POST /admin/drain | /admin/adopt | /admin/transfer |
        /admin/abort_adopt — the planned-membership control plane
        (parallel/membership.py). Peer-facing endpoints answer HTTP 200
        with a status envelope like the query plane, so callers share
        one error-handling path."""
        mem = self.membership
        if mem is None:
            return 400, prom_json.error(
                "elastic membership is not enabled on this node")
        body = body or {}
        if path == "/admin/drain":
            timeout = self._param(qs, "timeout")
            out = mem.drain(timeout_s=float(timeout)
                            if timeout else None)
            return 200, {"status": "success", "data": out}
        if path == "/admin/adopt":
            if body.get("shard") is None:
                return 400, prom_json.error("adopt: missing shard")
            out = mem.accept_adopt(int(body["shard"]),
                                   str(body.get("from") or ""))
            return 200, {"status": "success", "data": out}
        if path == "/admin/transfer":
            if body.get("shard") is None or not body.get("owner"):
                return 400, prom_json.error(
                    "transfer: missing shard/owner")
            out = mem.apply_transfer(int(body["shard"]),
                                     str(body["owner"]))
            return 200, {"status": "success", "data": out}
        if path == "/admin/abort_adopt":
            if body.get("shard") is None:
                return 400, prom_json.error("abort_adopt: missing shard")
            out = mem.abort_adopt(int(body["shard"]),
                                  str(body.get("owner") or ""))
            return 200, {"status": "success", "data": out}
        return 404, prom_json.error(f"no route for {path}", "not_found")

    # -- recording rules & alerting (filodb_tpu/rules) --------------------
    def _rules_proxy(self, path: str, qs: Dict):
        """Under the supervisor only ONE worker evaluates rules; a
        request landing on a stand-by worker (the kernel balances the
        public port) proxies to the evaluator's private port so clients
        see authoritative state regardless of which worker accepted.
        ``__local__`` breaks proxy loops when elections disagree for a
        beat. Returns None when no proxy applies (answer locally)."""
        eng = self.rules
        if eng is None or qs.get("__local__"):
            return None
        snap = eng.snapshot()
        if snap["active"]:
            return None
        target = self.peers.get(f"node{eng.evaluator_ordinal()}")
        if not target:
            return None
        import urllib.request as ureq
        q = {k: v for k, v in qs.items()}
        q["__local__"] = ["1"]
        url = (target.rstrip("/") + path + "?"
               + urllib.parse.urlencode(q, doseq=True))
        try:
            with ureq.urlopen(url, timeout=5) as r:
                return 200, json.loads(r.read())
        except (OSError, ValueError):
            return None     # fall back to the local (stand-by) view

    def _rules_api(self, qs: Dict):
        """GET /api/v1/rules (Prometheus rules API shape). Extensions:
        ``&explain=analyze`` inlines each rule's retained last
        evaluation (query, exact range, cache dispositions, duration,
        error) — the rules engine's own &explain surface."""
        proxied = self._rules_proxy("/api/v1/rules", qs)
        if proxied is not None:
            return proxied
        eng = self.rules
        if eng is None:
            return 200, {"status": "success",
                         "data": {"groups": [], "evaluating": False}}
        explain = self._param(qs, "explain") == "analyze"
        data = eng.rules_payload(explain=explain)
        if self._param(qs, "debug"):
            # scheduler/election introspection (the failover audit
            # trail): alive set, announce state, election-event ring
            data["debug"] = eng.snapshot()
        return 200, {"status": "success", "data": data}

    def _alerts_api(self, qs: Dict):
        """GET /api/v1/alerts: active alert instances + the bounded
        structured-event ring of state transitions."""
        proxied = self._rules_proxy("/api/v1/alerts", qs)
        if proxied is not None:
            return proxied
        eng = self.rules
        if eng is None:
            return 200, {"status": "success", "data": {"alerts": []}}
        return 200, {"status": "success", "data": eng.alerts_payload()}

    def rule_eval_range(self, ds: str, query: str, plan,
                        start_ms: int, step_ms: int, end_ms: int):
        """One standing-query evaluation for the rules engine, through
        the NORMAL serving path: plan-cost charge (FORCED, on the
        reserved ``__rules__`` tenant — standing evaluation never
        bounces off a drained bucket), results-cache split (the tick is
        a step-aligned tail recompute: the warm prefix serves from
        cache, only the newest step materializes), engine execution at
        BACKGROUND priority. Returns ``(result, stages)``; the stages
        dict carries the cache dispositions the engine retains per rule
        for ``/api/v1/rules?explain=analyze``. No admission slot is
        taken: the scheduler is a single standing consumer, not a burst
        of client connections."""
        deadline = Deadline.after(self.query_timeout_s)
        engine = self.make_planner(ds, deadline=deadline)
        if engine is None:
            raise QueryError(f"rules: dataset {ds} not set up")
        stages: Dict[str, object] = {}
        qctx = qos.QosContext(tenant=qos.RULES_TENANT,
                              priority=qos.PRIORITY_BACKGROUND,
                              forced=True)
        with qos.activate(qctx):
            with obs_trace.span("rule-eval", query=query, dataset=ds):
                # forced context: charges the reserved tenant's bucket
                # and returns None — rule evaluation is never shed
                self._charge_or_shed(engine, {}, ds, query, plan,
                                     start_ms // 1000, end_ms // 1000,
                                     step_ms // 1000, stages)
                ses = self.result_cache.begin(
                    engine, ds, query, plan, start_ms, step_ms, end_ms)
                exs = [engine.materialize(p) for p in ses.plans]
                res = ses.finish(engine,
                                 [ex.execute() for ex in exs])
        stages["resultCache"] = ses.state
        stages["cachedSteps"] = ses.cached_steps
        if isinstance(res, GridResult):
            stages["series"] = res.num_series
            if res.partial:
                stages["partial"] = True
        return res, stages

    def _local_shard_nums(self, ds: str) -> set:
        return {getattr(s, "shard_num", i)
                for i, s in enumerate(self.shards_by_dataset.get(ds, ()))}

    def _stale_routing_payload(self, missing) -> Dict:
        """The bounce envelope a peer returns instead of silently
        evaluating over a subset of the shards the caller routed at it:
        names the owners THIS node's mapper records (it witnessed the
        handoff), so the caller can rewire and retry."""
        owners = {}
        if self.shard_mapper is not None:
            owners = {str(n): self.shard_mapper.node_of(n)
                      for n in missing}
        epoch = getattr(self.shard_mapper, "topology_epoch", 0) \
            if self.shard_mapper is not None else 0
        self.stale_routing_bounces += 1
        err = StaleRoutingError(
            owners={int(k): v for k, v in owners.items()},
            epoch=epoch, node=self.node_id or "",
            detail="shards %s are not served here" % sorted(missing))
        return {"status": "error", "errorType": "stale_routing",
                "error": str(err), "owners": owners,
                "topo_epoch": epoch}

    def _apply_owner_hints(self, e: StaleRoutingError) -> None:
        """Fold a stale-routing responder's owner map into the local
        mapper before re-materializing: the responder is the former
        owner and witnessed the handoff. Hints naming unknown nodes —
        or claiming THIS node serves a shard it doesn't — are ignored
        (the retry then waits for gossip/transfer to converge)."""
        if self.shard_mapper is None:
            return
        from filodb_tpu.parallel.shardmapper import ShardStatus
        local = {n for lst in self.shards_by_dataset.values()
                 for n in (getattr(s, "shard_num", i)
                           for i, s in enumerate(lst))}
        for sh, owner in sorted(e.owners.items()):
            if not owner or not (0 <= sh < self.shard_mapper.num_shards):
                continue
            if owner == self.node_id:
                if sh not in local:
                    continue        # bogus hint: we don't serve it
            elif owner not in self.peers:
                continue
            if self.shard_mapper.node_of(sh) != owner:
                self.shard_mapper.assign(sh, owner)
                self.shard_mapper.update(sh, ShardStatus.ACTIVE, owner)

    def _run_query_routing_retry(self, mk_engine, fn):
        """Execute a query, re-resolving routing on StaleRoutingError:
        a peer mid-/post-handoff bounced rather than answer for shards
        it no longer serves. The bounce carries the new owners; apply
        them, drop cached plans/results keyed on the stale world, and
        re-materialize. A stale-epoch peer response is therefore never
        returned to a client — the query either converges on fresh
        routing or fails loudly after bounded attempts."""
        import time as _time
        attempts = 3
        for i in range(attempts):
            try:
                return fn(mk_engine())
            except StaleRoutingError as e:
                self.stale_routing_retries += 1
                self._apply_owner_hints(e)
                # plans are routing-independent but the results cache
                # keys on the topology world: drop both (the listener
                # wiring clears the results cache too)
                self.plan_cache.invalidate("stale-routing")
                if i == attempts - 1:
                    raise QueryError(
                        "shard routing did not converge after "
                        f"{attempts} attempts: {e.detail or e}")
                _time.sleep(0.05 * (i + 1))

    # -- tenant QoS: cost admission + the shed-to-degraded ladder ---------
    def _charge_or_shed(self, engine, qs, ds: str, query: str, plan,
                        start: int, end: int, step: int,
                        stages: Dict) -> Optional[Tuple[int, object]]:
        """Charge the parsed plan's estimated cost to the tenant's
        budget. Returns None when the query may proceed normally, a
        ``(code, payload)`` degraded answer when the tenant is over
        budget but the ladder produced one, and raises
        :class:`~filodb_tpu.query.qos.AdmissionRejected` (429 +
        Retry-After) when it did not."""
        adm = self.admission
        qctx = qos.current()
        if adm is None or qctx is None or not adm.budgets.enabled:
            return None
        bucket = adm.budgets.bucket(qctx.tenant)
        if bucket is None:
            return None                     # unbudgeted tenant
        if qctx.forced:
            # fan-out leg: inherit the entry node's charge, never shed
            bucket.charge_forced(engine.estimate_cost(plan).total)
            return None
        if bucket.remaining() <= 0.0:
            # drained-bucket fast path: nothing can charge, so skip
            # plan pricing entirely — a tight-loop abuser ignoring
            # Retry-After must not buy repeated cost walks with each
            # rejection. Only the (charged) stale rung can answer.
            bucket.note_throttled()
            qctx.degraded = True
            qctx.priority = qos.PRIORITY_BEST_EFFORT
            out = self._shed_degraded(engine, qs, ds, query, plan,
                                      start, end, step, stages,
                                      drained=True)
            if out is not None:
                return out
            adm.budgets.record_rejected(qctx.tenant)
            raise qos.AdmissionRejected(
                f"tenant {qctx.tenant!r} has exhausted its query "
                f"budget and no degraded answer exists",
                retry_after_s=bucket.retry_after_s(bucket.burst),
                tenant=qctx.tenant, reason="over-budget")
        cost = engine.estimate_cost(plan).total
        stages["qosCost"] = round(cost, 1)
        if bucket.try_charge(cost):
            return None
        # over budget: the tenant's own work degrades; everyone else
        # is untouched. Executions below run at best-effort priority so
        # the batcher never lets them head-of-line block interactive
        # queries.
        qctx.degraded = True
        qctx.priority = qos.PRIORITY_BEST_EFFORT
        obs_trace.event("qos-shed", tenant=qctx.tenant,
                        cost=round(cost, 1))
        out = self._shed_degraded(engine, qs, ds, query, plan,
                                  start, end, step, stages)
        if out is not None:
            return out
        adm.budgets.record_rejected(qctx.tenant)
        if cost > bucket.burst:
            # the query prices above burst: it can NEVER charge cleanly
            # no matter how long the client waits (burst IS the largest
            # clean admission). The old `retry_after_s(cost)` capped at
            # burst and read "Retry-After: 1" off a full bucket — a
            # lie. Name the alternative that WOULD fit instead, or say
            # explicitly that nothing does.
            alt = self._never_admittable_alternative(
                engine, plan, start, end, step, bucket.burst)
            if alt is not None:
                kind, alt_step, alt_cost = alt
                hint = (f"retry with step>={alt_step}s (estimated "
                        f"cost {alt_cost:.0f} fits the burst)"
                        if kind == "coarsen" else
                        f"retry the newest slice only (estimated "
                        f"cost {alt_cost:.0f} fits the burst)")
                raise qos.AdmissionRejected(
                    f"tenant {qctx.tenant!r}: estimated cost "
                    f"{cost:.0f} exceeds the budget's burst capacity "
                    f"{bucket.burst:.0f} and can never admit cleanly; "
                    f"{hint}",
                    retry_after_s=bucket.retry_after_s(alt_cost),
                    tenant=qctx.tenant, reason="never-admittable")
            raise qos.AdmissionRejected(
                f"tenant {qctx.tenant!r}: estimated cost {cost:.0f} "
                f"exceeds the budget's burst capacity "
                f"{bucket.burst:.0f} at every degraded resolution — "
                f"never admittable under this tenant's budget; raise "
                f"the budget or narrow the query",
                retry_after_s=None,
                tenant=qctx.tenant, reason="never-admittable")
        raise qos.AdmissionRejected(
            f"tenant {qctx.tenant!r} is over its query budget "
            f"(estimated cost {cost:.0f}) and no degraded answer "
            f"exists",
            retry_after_s=adm.budgets.retry_after_s(qctx.tenant, cost),
            tenant=qctx.tenant, reason="over-budget")

    def _never_admittable_alternative(self, engine, plan, start: int,
                                      end: int, step: int,
                                      burst: float):
        """A cheaper shape of the same query that CAN admit cleanly
        under ``burst``, for the never-admittable 429 body:
        ``("coarsen", step_s, cost)`` (preferred — the resolution the
        degrade ladder would pick), ``("partial", step_s, cost)`` for
        the newest-slice shape, or None when even those price above
        burst."""
        if step <= 0:
            return None
        from filodb_tpu.query.engine import lp_replace_range
        coarse = qos.coarsen_step_s(start, step, end,
                                    self.qos_degrade_max_steps)
        try:
            if coarse > step:
                plan_b = lp_replace_range(plan, start * 1000,
                                          coarse * 1000, end * 1000)
                c = engine.estimate_cost(plan_b).total
                if c <= burst:
                    return ("coarsen", coarse, c)
            n_steps = (end - start) // step + 1
            if n_steps > 4:
                keep = max(1, n_steps // 8)
                start_c = start + (n_steps - keep) * step
                plan_c = lp_replace_range(plan, start_c * 1000,
                                          step * 1000, end * 1000)
                c = engine.estimate_cost(plan_c).total
                if c <= burst:
                    return ("partial", step, c)
        except Exception:   # noqa: BLE001 — a hint must never 500
            return None
        return None

    def _shed_degraded(self, engine, qs, ds: str, query: str, plan,
                       start: int, end: int, step: int,
                       stages: Dict, drained: bool = False
                       ) -> Optional[Tuple[int, object]]:
        """The brownout ladder, in order of preference:

        1. **stale-cache** — an overlapping results-cache extent served
           past the freshness horizon (costs nothing; correctness
           invalidators still apply — stale, never wrong);
        2. **downsample** — re-plan at a coarser step through the
           normal materialize path, which routes the bigger step
           through the raw/downsample tiering where available;
        3. **partial** — evaluate only the newest slice of the range
           and return it via the partial-results plumbing.

        Rungs 2-3 still charge their (much smaller) estimated cost —
        a tenant deep in debt gets neither. Every rung stamps a
        ``shed(...)`` warning naming itself, so clients and dashboards
        see exactly what they got. Returns None when no rung applies
        (the caller answers 429 + Retry-After)."""
        qctx = qos.current()
        tenant = qctx.tenant if qctx is not None else qos.DEFAULT_TENANT
        budgets = self.admission.budgets
        if not self.qos_shed_degraded or step <= 0:
            return None
        start_ms, step_ms, end_ms = start * 1000, step * 1000, end * 1000
        chaos.fire("qos.shed", tenant=tenant, query=query)
        # rung 1: stale cache (skipped when the client explicitly sent
        # &cache=false — the escape hatch means "never answer me from
        # cached state", stale least of all)
        bypass = (self._param(qs, "cache", "")
                  or "").lower() in ("false", "0", "no")
        grid = None if bypass else \
            self.result_cache.stale_serve(engine, ds, query, plan,
                                          start_ms, step_ms, end_ms)
        if grid is not None and budgets.try_charge(
                tenant, qos.stale_serve_cost(grid.num_series,
                                             grid.values.shape[1])):
            # a stale serve is cheap but not free (encode-only cost
            # charged above): the budget bounds the tenant's TOTAL
            # work, degraded serving included
            grid.warnings.append(
                f"shed(stale-cache): tenant {tenant!r} over budget; "
                f"served cached extent past the freshness horizon")
            budgets.record_degraded(tenant, "stale")
            obs_trace.event("qos-shed", rung="stale", tenant=tenant)
            stages["qosShed"] = "stale"
            return 200, self._encode_degraded(engine, grid, qs)
        if drained:
            # deep debt: the compute rungs below could never charge —
            # don't pay their plan walks either
            return None
        from filodb_tpu.query.engine import lp_replace_range

        def run_rung(rung: str, plan_x, note: str,
                     partial: bool = False):
            """Charge + execute one compute rung. An EXECUTION failure
            (a mid-loss fan-out leg, a transient query error) refunds
            the rung's charge and falls through to the next rung /
            terminal 429 — it must never surface as a 400: the client
            sent a valid query, the degraded answer just wasn't
            available. Deadline exhaustion keeps its own 503 shape."""
            cost_x = engine.estimate_cost(plan_x).total
            if not budgets.try_charge(tenant, cost_x):
                return None
            obs_trace.event("qos-shed", rung=rung, tenant=tenant)
            try:
                res = engine.materialize(plan_x).execute()
            except (DeadlineExceeded, qos.AdmissionRejected):
                raise
            except Exception as e:     # noqa: BLE001 — fall to next rung
                budgets.refund(tenant, cost_x)
                obs_trace.event("qos-shed-failed", rung=rung,
                                tenant=tenant, error=str(e)[:200])
                return None
            budgets.record_degraded(tenant, rung)
            stages["qosShed"] = rung
            if isinstance(res, GridResult):
                res.partial = res.partial or partial
                res.warnings.append(note)
                return 200, self._encode_degraded(engine, res, qs)
            if isinstance(res, ScalarResult):
                return 200, prom_json.scalar(res, instant=False)
            return None

        # rung 2: coarser resolution through the tiering path
        coarse = qos.coarsen_step_s(start, step, end,
                                    self.qos_degrade_max_steps)
        if coarse > step:
            plan_b = lp_replace_range(plan, start_ms, coarse * 1000,
                                      end_ms)
            out = run_rung(
                "downsample", plan_b,
                f"shed(downsample): tenant {tenant!r} over budget; "
                f"step coarsened {step}s -> {coarse}s")
            if out is not None:
                return out
        # rung 3: newest-slice partial
        n_steps = (end - start) // step + 1
        if n_steps > 4:
            keep = max(1, n_steps // 8)
            start_c = start + (n_steps - keep) * step
            plan_c = lp_replace_range(plan, start_c * 1000, step_ms,
                                      end_ms)
            out = run_rung(
                "partial", plan_c,
                f"shed(partial): tenant {tenant!r} over budget; "
                f"returned newest {keep}/{n_steps} steps",
                partial=True)
            if out is not None:
                return out
        return None

    def _shed_stale_saturated(self, ds: str, qs: Dict, qctx,
                              deadline, no_cache: bool
                              ) -> Optional[Tuple[int, object]]:
        """Host-saturation fallback: the bounded admission wait timed
        out, but a stale cached extent needs neither a slot nor
        compute — parse (plan cache) and look it up. None when there
        is no usable extent (the caller answers 429)."""
        if no_cache or not self.qos_shed_degraded:
            return None
        query = self._param(qs, "query")
        if not query:
            return None
        try:
            start = int(float(self._param(qs, "start", "0")))
            end = int(float(self._param(qs, "end", "0")))
            step = int(float(self._param(qs, "step", "10")))
        except ValueError:
            return None
        if step <= 0 or end < start:
            return None
        engine = self.make_planner(ds, deadline=deadline)
        if engine is None:
            return None
        plan = self.plan_cache.lookup(ds, query, start * 1000,
                                      step * 1000, end * 1000)
        if plan is None:
            plan = parse_query_range(query,
                                     TimeStepParams(start, step, end))
            self.plan_cache.store(ds, query, start * 1000, step * 1000,
                                  end * 1000, plan)
        grid = self.result_cache.stale_serve(
            engine, ds, query, plan, start * 1000, step * 1000,
            end * 1000)
        if grid is None:
            return None
        if not self.admission.budgets.try_charge(
                qctx.tenant, qos.stale_serve_cost(
                    grid.num_series, grid.values.shape[1])):
            return None         # budget bounds degraded serving too
        grid.warnings.append(
            "shed(stale-cache): host saturated; served cached extent "
            "past the freshness horizon")
        self.admission.budgets.record_degraded(qctx.tenant, "stale")
        return 200, self._encode_degraded(engine, grid, qs)

    def _encode_degraded(self, engine, res: GridResult, qs):
        """Encode a shed-ladder result. Degraded answers are exactly
        what a brownout serves in VOLUME, so the bulk matrix path
        (pre-encoded bytes, memoized fragments) matters here too; the
        warnings/partial markers ride the envelope on both paths.
        Never admitted to the results cache (the shed warning trips the
        degraded guard — these must not poison healthy queries)."""
        hist_wire = bool(self._param(qs, "hist-wire"))
        stats_json = self._query_stats(engine, res)
        if isinstance(res, GridResult) and not hist_wire \
                and not res.is_hist():
            st = engine.stats
            warnings = list(getattr(st, "warnings", ()) or ())
            warnings.extend(w for w in res.warnings
                            if w not in warnings)
            partial = bool(getattr(st, "partial", False) or res.partial)
            return prom_json.matrix_bytes(res, stats_json,
                                          warnings=warnings,
                                          partial=partial)
        out = prom_json.matrix(res, hist_wire=hist_wire)
        out["stats"] = stats_json
        prom_json.attach_degraded(out, res, engine.stats)
        return out

    # dispatch-scope "publisher": scoped engines are born here (pull
    # event — the results cache keys on dispatch_scope() per lookup)
    @publishes("dispatch-scope")
    def make_planner(self, ds: str, local_dispatch: bool = False,
                     deadline: Optional[Deadline] = None,
                     allow_partial: bool = False,
                     no_result_cache: bool = False):
        """Planner over this node's view of a dataset (shared by the HTTP
        endpoints and the gRPC query service). ``local_dispatch`` pins
        evaluation to local shards — no peer fan-out, no federation."""
        shards = self.shards_by_dataset.get(ds)
        if shards is None:
            return None
        if ds in INTERNAL_DATASETS:
            # a reserved internal dataset (self-telemetry / rule
            # outputs) is strictly node-local: its shard numbers are
            # worker ordinals outside the user dataset's mapper world,
            # every process serves only its own internal series, and
            # internal queries must never fan out, push down, or ride
            # the mesh. A minimal planner over the local shard(s) keeps
            # the whole cluster plane out of the loop — and out of its
            # failure domain.
            planner = QueryPlanner(
                shards, backend=self.backend, deadline=deadline,
                allow_partial=allow_partial,
                no_result_cache=no_result_cache,
                limits=self.query_limits, dataset=ds,
                node_id=self.node_id)
            planner.metering = self.tenant_metering
            return planner
        peers = {} if local_dispatch else self.peers
        partitions = {} if local_dispatch else self.partitions
        grpc_peers = {} if local_dispatch else self.grpc_peers
        grpc_partitions = {} if local_dispatch else self.grpc_partitions
        # mid-handoff read redirect: shards this node is adopting route
        # back to their still-serving previous owner until replay
        # completes (resolved to URLs here; applies under dispatch=local
        # too — the data is by definition this node's shard set)
        handoff = {}
        if self.handoff_sources:
            down = set(self.detector.down_peers()) \
                if self.detector is not None else set()
            for sh, node in dict(self.handoff_sources).items():
                url = self.peers.get(node)
                if url and node not in down:
                    handoff[sh] = (node, url)
        planner = QueryPlanner(shards, backend=self.backend,
                            handoff_sources=handoff,
                            peer_watermarks=self.peer_watermarks,
                            deadline=deadline,
                            allow_partial=allow_partial,
                            no_result_cache=no_result_cache,
                            resilience=self.resilience,
                            shard_mapper=self.shard_mapper,
                            mesh_executor=self.mesh_executor,
                            spread=self.spread,
                            ds_store=self.ds_store_by_dataset.get(ds),
                            raw_retention_ms=self.raw_retention_ms,
                            limits=self.query_limits,
                            spread_provider=self.spread_provider,
                            node_id=self.node_id, peers=peers,
                            buddies=self.buddies,
                            partitions=partitions,
                            local_partitions=self.local_partitions,
                            dataset=ds,
                            grpc_peers=grpc_peers,
                            grpc_partitions=grpc_partitions,
                            local_dispatch=local_dispatch)
        # QoS cost estimation: the metering snapshot prices remote
        # shard groups (local trackers only know local shards)
        planner.metering = self.tenant_metering
        return planner

    # the schema mutation publisher (admin invalidate endpoint, bus
    # broadcast, ops jobs): graftlint requires it to reach every
    # registered cache's schema hook — plan cache directly, results
    # cache through the plan cache's listener chain
    @publishes("schema")
    def invalidate_plan_cache(self, reason: str = "schema") -> None:
        """Explicit plan-cache invalidation hook. Topology changes flow
        in automatically via ShardMapper events; callers that change a
        dataset's SCHEMAS (column set, value column, bucket scheme) must
        call this so no cached plan outlives the world it was parsed
        against."""
        self.plan_cache.invalidate(reason)

    # -- endpoints --------------------------------------------------------
    @staticmethod
    def _param(qs, name, default=None):
        v = qs.get(name)
        return v[0] if v else default

    def _ingest_influx(self, body_raw: bytes):
        """Remote ingest edge: newline-delimited influx lines in the
        POST body, routed through the gateway's builders into the
        per-shard WALs. Unlike the fire-and-forget TCP gateway this
        endpoint has an ack channel: 200 means every line's container
        was appended (fsync'd when group commit is off — the soak
        test's acked-sample ledger trusts exactly this); while ingest
        is degraded to read-only it answers 503 + Retry-After."""
        gw = self.gateway
        if gw is None:
            return 404, prom_json.error(
                "no gateway on this worker (the gateway rides exactly "
                "one worker per host)", "not_found")
        health = ingest_health.GLOBAL
        if health.read_only() and not health.probe_due():
            # fast 503 without touching the disk; the rate-limited
            # probe slot is claimed inside _publish when due
            raise health.reject()
        from filodb_tpu.core.record import RecordBuilder
        builders: Dict[int, RecordBuilder] = {}
        accepted = rejected = 0
        for raw in body_raw.splitlines():
            line = raw.decode("utf-8", errors="replace").strip()
            if not line or line.startswith("#"):
                continue
            if gw._route_line(line, builders):
                accepted += 1
            else:
                rejected += 1
        gw._publish(builders, raise_on_error=True)
        return 200, {"status": "success",
                     "data": {"accepted": accepted,
                              "rejected": rejected}}

    @staticmethod
    def _parse_duration_s(raw: Optional[str], default_s: float) -> float:
        """&timeout= value: plain seconds or a Prometheus-style suffixed
        duration (500ms / 30s / 2m / 1h). Bad values keep the default."""
        if not raw:
            return default_s
        try:
            m = re.match(r"^\s*([0-9.]+)\s*(ms|s|m|h)?\s*$", raw)
            if not m:
                return default_s
            v = float(m.group(1))
            scale = {"ms": 1e-3, "s": 1.0, "m": 60.0,
                     "h": 3600.0}.get(m.group(2) or "s", 1.0)
            return max(v * scale, 1e-3)
        except ValueError:
            return default_s

    def _lint_schema_items(self) -> Tuple:
        """Explicit metric-schema snapshot for promlint: the recording
        rules' ``schema:`` declarations (PR 12 extension). Hashable so
        the lint memo can key on it; recomputed per query — it is a
        tiny tuple walk and rules can be reloaded at runtime."""
        eng = self.rules
        if eng is None:
            return ()
        items = []
        for g in getattr(eng, "groups", ()):
            for r in getattr(g, "rules", ()):
                if getattr(r, "kind", "") == "recording" and \
                        getattr(r, "schema", None):
                    items.append((r.name, r.schema))
        return tuple(sorted(items))

    def _promql_lint(self, engine, qs, query: str):
        """promlint on a user query: findings ride the response
        ``warnings`` array; ``&lint=strict`` turns error-severity
        findings into a 400 with structured diagnostics;
        ``&lint=off`` skips. Returns None to proceed, or a (code,
        payload) rejection."""
        mode = (self._param(qs, "lint", "") or "").lower()
        if mode == "off":
            return None
        diags = _lint_memo(query, self._lint_schema_items())
        if not diags:
            return None
        if mode == "strict":
            errs = [d for d in diags if d.severity == "error"]
            if errs:
                out = prom_json.error(
                    "promlint: " + "; ".join(
                        f"[{d.rule}] {d.message}" for d in errs),
                    "bad_data")
                out["lint"] = [
                    {"rule": d.rule, "message": d.message,
                     "pos": d.pos, "end": d.end,
                     "severity": d.severity} for d in diags]
                return 400, out
        engine.stats.warnings.extend(
            f"promlint: {d.render()}" for d in diags)
        return None

    def _query_range(self, engine, qs, ds: str = "timeseries",
                     tctx=None):
        import time as _time
        query = self._param(qs, "query")
        if not query:
            raise QueryError("missing query parameter")
        start = int(float(self._param(qs, "start", "0")))
        end = int(float(self._param(qs, "end", "0")))
        step = int(float(self._param(qs, "step", "10")))
        if end < start:
            raise QueryError("end < start")
        # tracing: a propagated context (peer hop) is always honored;
        # fresh requests sample per tracer policy; &explain=trace forces
        # a trace for this one request and inlines it in the response;
        # &explain=analyze extends it with per-stage device stats
        # (executable identity + cost analysis, batcher occupancy,
        # cache dispositions, shed decisions — obs/devprof.py)
        explain = self._param(qs, "explain")
        explain_trace = explain in ("trace", "analyze")
        tr = self.tracer.start(tctx, force=explain_trace)
        entry = self.inflight.register(
            query, ds, kind="range",
            trace_id=tr.trace_id if tr is not None else None)
        stages: Dict[str, object] = {}
        t0 = _time.perf_counter()
        code = 0
        try:
            with obs_trace.activate(tr):
                with obs_trace.span("query", query=query, dataset=ds,
                                    node=self.node_id or ""):
                    code, payload = self._query_range_stages(
                        engine, qs, ds, query, start, end, step, entry,
                        stages,
                        force_dict=tctx is not None or explain_trace)
            if tr is not None and isinstance(payload, dict):
                if tctx is not None:
                    # peer hop: ship the local spans back; the entry
                    # node's recorder stitches them into ONE trace
                    payload["trace_spans"] = tr.spans_json()
                else:
                    if explain_trace:
                        payload["trace"] = tr.to_json()
                    if explain == "analyze":
                        payload["analyze"] = self._build_analyze(
                            tr, stages)
            return code, payload
        finally:
            # tail retention runs HERE so every exit path (success,
            # QueryError, shed, crash) decides the trace's fate exactly
            # once, with the outcome in hand
            total_s = _time.perf_counter() - t0
            self.inflight.unregister(entry)
            tr = self._finish_request_trace(
                tr, tctx, code, total_s, stages,
                force=explain_trace)
            obs_metrics.observe(
                "filodb_query_latency_seconds", _QLAT_HELP, total_s,
                trace_id=tr.trace_id if tr is not None else None)
            self._maybe_slow_log(total_s, query, ds, "range", engine,
                                 stages, tr)

    def _query_range_stages(self, engine, qs, ds, query, start, end,
                            step, entry, stages, force_dict=False):
        """The staged range-query path: parse (plan cache) ->
        materialize -> execute -> encode, with per-stage spans, the
        in-flight registry's stage pointer, and the ``stages``
        breakdown the slow-query log records. ``force_dict`` routes the
        encode off the pre-encoded fast path so trace keys can attach —
        only peer hops (``trace_spans`` rides the envelope) and explain
        requests need it; a plain request with a pending tail-sampling
        trace keeps the byte fast path."""
        import time as _time
        t0 = _time.perf_counter()
        self.inflight.stage(entry, "parse")
        with obs_trace.span("parse") as sp:
            plan = self.plan_cache.lookup(ds, query, start * 1000,
                                          step * 1000, end * 1000)
            cached = plan is not None
            if plan is None:
                plan = parse_query_range(query,
                                         TimeStepParams(start, step, end))
                self.plan_cache.store(ds, query, start * 1000,
                                      step * 1000, end * 1000, plan)
            pc_state = "hit" if cached else \
                ("miss" if self.plan_cache.enabled else "off")
            sp.tag(plan_cache=pc_state)
        # promlint semantic diagnostics on the user query: warnings in
        # the response envelope; &lint=strict -> 400 with diagnostics
        lint_out = self._promql_lint(engine, qs, query)
        if lint_out is not None:
            return lint_out
        if self._param(qs, "explain") == "analyze":
            # QoS cross-check surface: the static cost lattice that
            # must upper-bound estimate_cost's admission price
            from filodb_tpu.promql import semant as _semant
            stages["staticCostBound"] = _semant.static_cost_bound(
                plan, getattr(engine, "shards", ()),
                metering=getattr(engine, "metering", None)).to_json()
        # cost-based tenant admission (query/qos.py): price the parsed
        # plan BEFORE any execution and charge the tenant's token
        # bucket. Fan-out legs (dispatch=local) force-charge — the
        # entry node already decided; an over-budget entry query walks
        # the degrade ladder (stale-cache -> downsample -> partial) and
        # only 429s when no degraded answer exists.
        out = self._charge_or_shed(engine, qs, ds, query, plan,
                                   start, end, step, stages)
        if out is not None:
            return out
        t1 = _time.perf_counter()
        self.inflight.stage(entry, "plan")
        bypass = (self._param(qs, "cache", "")
                  or "").lower() in ("false", "0", "no")
        with obs_trace.span("plan"):
            # results cache: split the request into the cached extent
            # and the uncovered spans — only the latter materialize
            # (tail-only recomputation; a full hit materializes nothing)
            ses = self.result_cache.begin(
                engine, ds, query, plan, start * 1000, step * 1000,
                end * 1000, bypass=bypass)
            exs = [engine.materialize(p) for p in ses.plans]
        ex_label = type(exs[-1]).__name__ if exs else "ResultCacheHit"
        t2 = _time.perf_counter()
        self.inflight.stage(entry, "execute")
        with obs_trace.span("execute", plan=ex_label) as _esp:
            res = ses.finish(engine, [ex.execute() for ex in exs])
            _esp.tag(result_cache=ses.state,
                     cached_steps=ses.cached_steps)
        t3 = _time.perf_counter()
        stages["parseMs"] = round((t1 - t0) * 1000, 3)
        stages["planMs"] = round((t2 - t1) * 1000, 3)
        stages["execMs"] = round((t3 - t2) * 1000, 3)
        stages["planCache"] = pc_state
        stages["resultCache"] = ses.state
        if isinstance(res, ScalarResult):
            return 200, prom_json.scalar(res, instant=False)
        hist_wire = bool(self._param(qs, "hist-wire"))
        stats_json = self._query_stats(engine, res)
        stats_json["timings"] = {
            "parseMs": stages["parseMs"],
            "planMs": stages["planMs"],
            "execMs": stages["execMs"],
            "plan": ex_label,
            "planCache": pc_state,
            "resultCache": ses.state,
        }
        self.inflight.stage(entry, "encode")
        if isinstance(res, GridResult) and not hist_wire \
                and not res.is_hist() and not force_dict:
            # serving fast path: bulk matrix rows encode straight to
            # JSON bytes (memoized ts/value fragments), skipping the
            # dict tree + json.dumps walk. Peer-hop/explain requests
            # take the dict path below so spans can ride the envelope —
            # plain responses (traced or not) stay byte-identical.
            st = engine.stats
            warnings = list(getattr(st, "warnings", ()) or ())
            warnings.extend(res.warnings)
            partial = bool(getattr(st, "partial", False) or res.partial)
            out = prom_json.matrix_bytes(
                res, stats_json, warnings=warnings, partial=partial,
                rows_memo=ses.encode_memo())
            stages["encodeMs"] = round(
                (_time.perf_counter() - t3) * 1000, 3)
            return 200, out
        with obs_trace.span("encode"):
            out = prom_json.matrix(res, hist_wire=hist_wire)
            out["stats"] = stats_json
            prom_json.attach_degraded(out, res, engine.stats)
        stages["encodeMs"] = round((_time.perf_counter() - t3) * 1000, 3)
        return 200, out

    def _finish_request_trace(self, tr, tctx, code: int, total_s: float,
                              stages: Dict, force: bool = False):
        """The tail-retention decision for one finished request (called
        from the query paths' ``finally``): errors (exception in
        flight or a 4xx/5xx answer), QoS-shed/degraded rungs, and
        latency at/above the slow-query threshold always retain the
        pending trace; the rest keep the start-time sampling coin.
        Returns the trace iff it was retained (i.e. its id resolves in
        ``/debug/traces``) — callers link slowlog records and latency
        exemplars only to that. Peer hops pass through: the entry node
        owns retention, and the forwarded id still links the stitched
        entry-node trace."""
        if tr is None:
            return None
        if tctx is not None:
            return tr
        err = sys.exc_info()[0] is not None or code >= 400
        shed = bool(stages.get("qosShed"))
        will_log = (self.slow_log.enabled
                    and total_s * 1000.0 >= self.slow_log.threshold_ms)
        retained = self.tracer.finish_request(
            tr, error=err, shed=shed, duration_ms=total_s * 1000.0,
            force=force or will_log)
        return tr if retained else None

    def _maybe_slow_log(self, total_s: float, query: str, ds: str,
                        kind: str, engine, stages: Dict, tr) -> None:
        """Build + record the structured slow-query record (only on the
        slow path — fast queries pay one float compare)."""
        if not self.slow_log.enabled \
                or total_s * 1000 < self.slow_log.threshold_ms:
            return
        st = getattr(engine, "stats", None)
        rec = {
            "query": query, "dataset": ds, "kind": kind,
            "stages": dict(stages),
            "shards": sorted(
                int(n) for s in getattr(engine, "shards", ())
                for n in (s.shard_num if isinstance(
                    getattr(s, "shard_num", None), tuple)
                    else (getattr(s, "shard_num", -1),))),
            "seriesScanned": getattr(st, "series_scanned", 0),
            "samplesScanned": getattr(st, "samples_scanned", 0),
            "partial": bool(getattr(st, "partial", False)),
            "warnings": list(getattr(st, "warnings", ()) or ()),
        }
        if tr is not None:
            rec["trace_id"] = tr.trace_id
        self.slow_log.maybe_record(total_s * 1000, rec)

    def _query_instant(self, engine, qs, ds: str = "timeseries",
                       tctx=None):
        import time as _time
        query = self._param(qs, "query")
        if not query:
            raise QueryError("missing query parameter")
        time_s = int(float(self._param(qs, "time", "0")))
        explain = self._param(qs, "explain")
        explain_trace = explain in ("trace", "analyze")
        tr = self.tracer.start(tctx, force=explain_trace)
        entry = self.inflight.register(
            query, ds, kind="instant",
            trace_id=tr.trace_id if tr is not None else None)
        stages: Dict[str, object] = {}
        t0 = _time.perf_counter()
        code = 0
        try:
            with obs_trace.activate(tr):
                with obs_trace.span("query", query=query, dataset=ds,
                                    node=self.node_id or ""):
                    code, payload = self._query_instant_stages(
                        engine, qs, ds, query, time_s, entry, stages)
            if tr is not None and isinstance(payload, dict):
                if tctx is not None:
                    payload["trace_spans"] = tr.spans_json()
                else:
                    if explain_trace:
                        payload["trace"] = tr.to_json()
                    if explain == "analyze":
                        payload["analyze"] = self._build_analyze(
                            tr, stages)
            return code, payload
        finally:
            total_s = _time.perf_counter() - t0
            self.inflight.unregister(entry)
            tr = self._finish_request_trace(
                tr, tctx, code, total_s, stages,
                force=explain_trace)
            obs_metrics.observe(
                "filodb_query_latency_seconds", _QLAT_HELP, total_s,
                trace_id=tr.trace_id if tr is not None else None)
            self._maybe_slow_log(total_s, query, ds, "instant", engine,
                                 stages, tr)

    def _query_instant_stages(self, engine, qs, ds, query, time_s,
                              entry, stages):
        import time as _time
        t0 = _time.perf_counter()
        self.inflight.stage(entry, "parse")
        # instant queries cache under step=0 (start == end == time)
        with obs_trace.span("parse"):
            plan = self.plan_cache.lookup(ds, query, time_s * 1000, 0,
                                          time_s * 1000)
            if plan is None:
                plan = parse_query(query, time_s)
                self.plan_cache.store(ds, query, time_s * 1000, 0,
                                      time_s * 1000, plan)
        lint_out = self._promql_lint(engine, qs, query)
        if lint_out is not None:
            return lint_out
        if self._param(qs, "explain") == "analyze":
            from filodb_tpu.promql import semant as _semant
            stages["staticCostBound"] = _semant.static_cost_bound(
                plan, getattr(engine, "shards", ()),
                metering=getattr(engine, "metering", None)).to_json()
        # cost admission: instant queries charge too, but there is no
        # range to stale-serve/coarsen/trim — over budget means 429
        # (step=0 makes the ladder decline)
        out = self._charge_or_shed(engine, qs, ds, query, plan,
                                   time_s, time_s, 0, stages)
        if out is not None:
            return out
        t1 = _time.perf_counter()
        self.inflight.stage(entry, "execute")
        with obs_trace.span("execute"):
            res = engine.execute(plan)
        t2 = _time.perf_counter()
        stages["parseMs"] = round((t1 - t0) * 1000, 3)
        stages["execMs"] = round((t2 - t1) * 1000, 3)
        if isinstance(res, ScalarResult):
            return 200, prom_json.scalar(res, instant=True)
        self.inflight.stage(entry, "encode")
        with obs_trace.span("encode"):
            out = prom_json.vector(res)
            out["stats"] = self._query_stats(engine, res)
            prom_json.attach_degraded(out, res, engine.stats)
        stages["encodeMs"] = round((_time.perf_counter() - t2) * 1000, 3)
        return 200, out

    def _build_analyze(self, tr, stages: Dict) -> Dict:
        """The ``&explain=analyze`` envelope: the traced spans resolve
        to per-stage device stats — executable identity + compile
        disposition per dispatch, cost-analysis FLOPs/bytes (computed
        on demand, cached per executable), batcher occupancy at
        dispatch, cache dispositions and shed decisions from the stage
        breakdown."""
        batcher_stats = None
        batcher = getattr(self.backend, "batcher", None) \
            if self.backend is not None else None
        if batcher is not None:
            bs = batcher.stats.snapshot()
            batcher_stats = {"enabled": batcher.enabled,
                             "occupancy_avg": bs["occupancy_avg"],
                             "occupancy_max": bs["occupancy_max"],
                             "batches": bs["batches"],
                             "by_priority": bs["by_priority"]}
        qctx = qos.current()
        qos_info = None
        if qctx is not None:
            qos_info = {"tenant": qctx.tenant,
                        "priority": qos.PRIORITY_NAMES.get(
                            qctx.priority, str(qctx.priority)),
                        "degraded": qctx.degraded,
                        "forced": qctx.forced}
            if stages.get("qosShed"):
                qos_info["shed"] = stages["qosShed"]
        return obs_devprof.analyze_payload(
            tr.spans_json(), stages, batcher_stats=batcher_stats,
            qos_info=qos_info,
            residency=lint_capacity.residency_snapshot())

    def _debug_traces(self, qs):
        """GET /debug/traces: recent finished traces (summaries), or one
        full trace via ?id=<trace_id>."""
        tid = self._param(qs, "id")
        if tid:
            tr = self.tracer.get(tid)
            if tr is None:
                return {"status": "error", "errorType": "not_found",
                        "error": f"no trace {tid} in the ring buffer"}
            return {"status": "success", "data": tr.to_json()}
        limit = int(self._param(qs, "limit", "50") or 50)
        full = (self._param(qs, "full", "") or "").lower() in \
            ("true", "1", "yes")
        traces = self.tracer.recent(limit)
        if full:
            data = [t.to_json() for t in traces]
        else:
            data = [{"trace_id": t.to_json()["trace_id"],
                     "num_spans": t.to_json()["num_spans"],
                     "duration_us": t.to_json()["duration_us"]}
                    for t in traces]
        return {"status": "success",
                "summary": self.tracer.snapshot(), "data": data}

    def _debug_profile(self, qs):
        """GET /debug/profile?seconds=N[&format=folded|json]: the
        sampling profiler's aggregate. ``seconds>0`` profiles a window
        (delta of the running sampler, or an inline burst when the
        sampler daemon is off — the handler thread blocks for the
        window, clamped); ``seconds=0`` reads the cumulative aggregate.
        ``format=folded`` answers flamegraph-ready folded text."""
        prof = self.profiler
        if prof is None:
            return 404, {"status": "error", "errorType": "unavailable",
                         "error": "profiler not configured "
                                  "(--profiler-enabled)"}
        try:
            seconds = float(self._param(qs, "seconds", "0") or 0)
        except ValueError:
            raise QueryError("seconds must be a number")
        if seconds > 0:
            folded, selfs = (prof.window(seconds) if prof.running
                             else prof.sample_burst(seconds))
        else:
            folded, selfs = prof.tables()
        fmt = (self._param(qs, "format", "json") or "json").lower()
        if fmt == "folded":
            return 200, prof.folded_text(folded)
        return 200, {"status": "success",
                     "data": prof.report(folded, selfs,
                                         window_s=seconds or None)}

    @staticmethod
    def _query_stats(engine, res) -> Dict:
        """Execution stats in the response (QueryStats threaded through
        results, core/query/QueryContext.scala; Prom &stats=all shape)."""
        st = engine.stats
        nbytes = 0
        if isinstance(res, GridResult):
            nbytes = int(res.values.nbytes)
            if res.hist_values is not None:
                nbytes += int(res.hist_values.nbytes)
        return {"seriesScanned": st.series_scanned,
                "samplesScanned": st.samples_scanned,
                "resultBytes": nbytes}

    def _time_range(self, qs):
        start = int(float(self._param(qs, "start", "0"))) * 1000
        end_raw = self._param(qs, "end")
        end = (int(float(end_raw)) * 1000 if end_raw is not None
               else 1 << 62)
        return start, end

    def _labels(self, engine, qs, ds="timeseries"):
        # Prometheus semantics: result is the UNION over all match[]
        # selectors (none -> all series).
        start, end = self._time_range(qs)
        out: set = set()
        for sel in qs.get("match[]", []) or [None]:
            filters = selector_to_filters(sel) if sel else ()
            out.update(engine.execute(lp.LabelNames(list(filters),
                                                    start, end)))
        if self.peers:
            out |= self._peer_metadata_union(ds, "labels", qs)
        return 200, prom_json.success(sorted(out))

    def _label_values(self, engine, name, qs, ds="timeseries"):
        start, end = self._time_range(qs)
        out: set = set()
        for sel in qs.get("match[]", []) or [None]:
            filters = selector_to_filters(sel) if sel else ()
            out.update(engine.execute(lp.LabelValues(name, list(filters),
                                                     start, end)))
        if self.peers:
            out |= self._peer_metadata_union(ds, f"label/{name}/values",
                                             qs)
        return 200, prom_json.success(sorted(out))

    def _series(self, engine, qs, ds="timeseries"):
        start, end = self._time_range(qs)
        out = []
        seen = set()
        for sel in qs.get("match[]", []):
            filters = selector_to_filters(sel)
            for labels in engine.execute(
                    lp.SeriesKeysByFilters(list(filters), start, end)):
                key = frozenset(labels.items())
                if key not in seen:
                    seen.add(key)
                    out.append(prom_json._metric(labels))
        if self.peers:
            for item in self._peer_metadata_union(ds, "series", qs):
                labels = dict(item)
                key = frozenset(labels.items())
                if key not in seen:
                    seen.add(key)
                    out.append(labels)
        return 200, prom_json.success(out)

    def _cluster_status(self, ds):
        """ClusterApiRoute status (ShardMapper snapshot)."""
        if self.shard_mapper is None:
            shards = self.shards_by_dataset.get(ds, [])
            states = [{"shard": i, "status": "Active"}
                      for i in range(len(shards))]
        else:
            states = [{"shard": i,
                       "status": self.shard_mapper.status(i).value,
                       "address": self.shard_mapper.node_of(i)}
                      for i in range(self.shard_mapper.num_shards)]
        return prom_json.success(states)

    # HELP text per family (fallback: a generic string). Kept verbose —
    # operators read this off the exposition, not the source.
    _METRIC_HELP = {
        "filodb_shard_status": "Shard FSM status (1 per shard; labels "
                               "carry status/node)",
        "filodb_cardinality_total_series": "Total series tracked by the "
                                           "shard's cardinality tracker",
        "filodb_cardinality_active_series": "Actively-ingesting series",
        "filodb_tile_cache_entries": "Device tile-cache entries",
        "filodb_tile_builds_total": "Device tile (re)builds",
        "filodb_tile_cache_hits_total": "Device tile-cache hits",
        "filodb_exec_cache_hits_total": "Compiled-executable reuse hits",
        "filodb_exec_cache_misses_total": "Compiled-executable retraces",
        "filodb_exec_cache_entries": "Distinct compiled kernel shapes",
        "filodb_batcher_enabled": "Micro-batcher admission on/off",
        "filodb_batcher_batches_total": "Device dispatches issued",
        "filodb_batcher_queries_total": "Queries admitted",
        "filodb_batcher_batched_queries_total":
            "Queries that shared a batch (size >= 2)",
        "filodb_batcher_occupancy_avg": "Mean batch size",
        "filodb_batcher_occupancy_max": "Max batch size seen",
        "filodb_batcher_gather_wait_ms_total":
            "Total residual gather-window wait",
        "filodb_plan_cache_entries": "Parsed-plan LRU entries",
        "filodb_plan_cache_hits_total": "Plan-cache hits",
        "filodb_plan_cache_misses_total": "Plan-cache misses",
        "filodb_plan_cache_rebases_total":
            "Cached plans rebased onto a new range",
        "filodb_plan_cache_invalidations_total":
            "Topology/schema invalidations",
        "filodb_result_cache_entries": "Results-cache extents resident",
        "filodb_result_cache_bytes": "Results-cache bytes resident "
                                     "(byte-accounted LRU)",
        "filodb_result_cache_hits_total":
            "Range queries answered entirely from cached extents",
        "filodb_result_cache_partial_hits_total":
            "Range queries stitched from a cached extent + a "
            "recomputed head/tail",
        "filodb_result_cache_misses_total": "Results-cache misses",
        "filodb_result_cache_stitches_total":
            "Span evaluations stitched into cached extents",
        "filodb_result_cache_churn_recomputes_total":
            "Series churn forced a full fresh recompute",
        "filodb_result_cache_bypassed_total":
            "Queries carrying the &cache=false escape hatch",
        "filodb_result_cache_degraded_skips_total":
            "Partial/degraded results refused admission to the cache",
        "filodb_result_cache_evictions_total":
            "Extents evicted by the byte-budget LRU",
        "filodb_result_cache_invalidations_total":
            "Topology/schema invalidations (shared with the plan cache)",
        "filodb_result_cache_watermark_invalidations_total":
            "Extents dropped on ingest-watermark regression "
            "(replay/recovery)",
        "filodb_result_cache_backfill_invalidations_total":
            "Extents dropped on shard backfill-epoch change (a new "
            "series ingested below the watermark)",
        "filodb_result_cache_cached_steps_served_total":
            "Steps served from cached extents",
        "filodb_result_cache_computed_steps_served_total":
            "Steps recomputed through the pipeline",
        "filodb_decode_cache_bytes":
            "Per-shard decode/merge cache bytes (bounded by "
            "decode-cache-mb)",
        "filodb_ingest_watermark_ms":
            "Per-shard settled-time bound (ms): min over per-"
            "partition last timestamps; the results cache's "
            "freshness horizon input",
        "filodb_grpc_rpcs_served_total": "gRPC query-service RPCs served",
        "filodb_breaker_state": "Per-peer circuit-breaker state "
                                "(1 per peer; state label)",
        "filodb_tenant_time_series_total": "Per-tenant series count",
        "filodb_tenant_time_series_active":
            "Per-tenant actively-ingesting series count",
        "filodb_tenant_metering_interval_seconds":
            "Configured tenant-metering snapshot interval",
        "filodb_tenant_metering_last_snapshot_age_seconds":
            "Seconds since the last tenant-metering snapshot",
        "filodb_tenant_metering_snapshots_total":
            "Tenant-metering snapshots taken",
        "filodb_topology_epoch":
            "Monotone topology epoch (bumped on every shard-ownership "
            "change; plan/results caches invalidate on it)",
        "filodb_shard_handoff_started_total":
            "Planned shard handoffs started (drain + hand-back)",
        "filodb_shard_handoff_completed_total":
            "Planned shard handoffs completed (ownership flipped, "
            "local copy released)",
        "filodb_shard_handoff_failed_total":
            "Planned shard handoffs rolled back to the draining owner",
        "filodb_shard_adoptions_total":
            "Shards adopted by this node (kind=planned handoff / "
            "kind=crash reassignment)",
        "filodb_shard_releases_total":
            "Local shard copies released (handoff completion or "
            "owner return)",
        "filodb_membership_draining":
            "1 while this node is draining its shards for a planned "
            "restart",
        "filodb_membership_incoming_shards":
            "Planned adoptions currently replaying on this node",
        "filodb_handback_failures_total":
            "Hand-back handoffs that exhausted their retries (shard "
            "stays on the temporary owner)",
        "filodb_stale_routing_bounces_total":
            "Peer requests bounced because they named shards this "
            "node no longer serves",
        "filodb_stale_routing_retries_total":
            "Queries re-materialized against fresh routing after a "
            "peer's stale-routing bounce",
        "filodb_detector_thread_wedged":
            "1 if the failure-detector monitor thread failed to exit "
            "on stop()",
        "filodb_peer_fanout_workers":
            "Metadata/cardinality peer fan-out concurrency "
            "(peer-fanout-workers knob; auto = host core count)",
        "filodb_worker_ordinal":
            "This process's worker ordinal in a supervisor deployment",
        "filodb_bus_events_published_total":
            "Control-plane events this worker published to the "
            "supervisor bus",
        "filodb_bus_events_applied_total":
            "Control-plane events this worker applied from the "
            "supervisor bus (topology/schema invalidations, "
            "watermark gossip, worker lifecycle hints)",
        "filodb_bus_reconnects_total":
            "Reconnects of this worker's bus client to the supervisor",
        "filodb_bus_connected":
            "1 while the worker's bus client is connected to the "
            "supervisor's control plane",
        "filodb_result_cache_stale_serves_total":
            "Brownout stale-cache rung: extents served past the "
            "freshness horizon to an over-budget tenant / saturated "
            "host",
        "filodb_admission_max_inflight":
            "Admission slots (host bound; a supervisor splits the "
            "host total across workers)",
        "filodb_admission_inflight":
            "Queries currently holding an admission slot",
        "filodb_admission_wait_timeouts_total":
            "Bounded admission waits that timed out (slot never "
            "freed within admission-wait-s)",
        "filodb_admission_rejected_total":
            "Queries answered 429 at the saturation gate",
        "filodb_tenant_budget_remaining":
            "Per-tenant token-bucket balance (cost units; negative = "
            "debt from forced fan-out charges)",
        "filodb_tenant_budget_rate":
            "Per-tenant budget refill rate (cost units/s)",
        "filodb_tenant_cost_charged_total":
            "Estimated cost units charged to the tenant (admitted + "
            "forced)",
        "filodb_tenant_admitted_total":
            "Queries the tenant's budget admitted cleanly",
        "filodb_tenant_throttled_total":
            "Budget charges refused (query entered the degrade "
            "ladder)",
        "filodb_tenant_forced_charges_total":
            "Fan-out leg charges inherited from an entry node",
        "filodb_tenant_degraded_total":
            "Degraded answers served, by ladder rung "
            "(stale/downsample/partial)",
        "filodb_tenant_rejected_total":
            "Tenant queries answered 429 (over budget, no degraded "
            "answer existed)",
        "filodb_batcher_priority_queries_total":
            "Batcher dispatches by priority class (tenant QoS)",
        "filodb_selfmon_alive":
            "1 while the self-monitoring loop thread is running",
        "filodb_selfmon_interval_seconds":
            "Configured self-monitoring collect+ingest interval",
        "filodb_traces_started_total": "Traces started on this node",
        "filodb_traces_stored": "Finished traces in /debug/traces",
        "filodb_slow_queries_total": "Queries over the slow-query "
                                     "threshold",
        "filodb_inflight_queries": "Queries currently executing",
    }

    def _metrics_text(self, exemplars: bool = False) -> str:
        return self.build_exposition(exemplars=exemplars).render()

    def build_exposition(self, exemplars: bool = False
                         ) -> "obs_metrics.ExpositionBuilder":
        """Prometheus exposition — the Kamon-metrics surface
        (TimeSeriesShardStats, TimeSeriesShard.scala:41; MemoryStats;
        ChunkSourceStats; kamon prometheus reporter in
        filodb-defaults.conf:1016), accumulated into an
        :class:`~filodb_tpu.obs.metrics.ExpositionBuilder`: one
        ``# HELP``/``# TYPE`` block per family, consistent label-value
        escaping, no duplicate series, and the global registry's
        counter/gauge/histogram families + collectors (process stats,
        device executable profiles).

        Returning the BUILDER (``/metrics`` renders it; the
        self-monitoring loop walks ``families()`` structurally) is the
        registry-walk API: self-ingestion reads the same samples a
        scrape would see, with no HTTP hop and no text parse."""
        import dataclasses as _dc

        b = obs_metrics.ExpositionBuilder()

        def emit(name, labels, value, mtype=None):
            fam = f"filodb_{name}"
            if mtype is None:
                mtype = "counter" if fam.endswith("_total") else "gauge"
            b.sample(fam, labels, value, mtype=mtype,
                     help=self._METRIC_HELP.get(
                         fam, f"FiloDB metric {fam}"))

        for ds, shards in self.shards_by_dataset.items():
            for shard in shards:
                st = getattr(shard, "stats", None)
                if st is None:
                    continue
                labels = {"dataset": ds,
                          "shard": str(getattr(shard, "shard_num", ""))}
                for f in _dc.fields(st):
                    emit(f.name, labels, getattr(st, f.name))
                if hasattr(shard, "decode_cache_bytes"):
                    emit("decode_cache_bytes", labels,
                         shard.decode_cache_bytes())
                wm = getattr(shard, "ingest_watermark_ms", None)
                if wm is not None:
                    emit("ingest_watermark_ms", labels, wm)
                tracker = getattr(shard, "card_tracker", None)
                if tracker is not None:
                    root = tracker.scan((), 0)
                    if root:
                        emit("cardinality_total_series", labels,
                             root[0].ts_count)
                        emit("cardinality_active_series", labels,
                             root[0].active_ts_count)
        if self.shard_mapper is not None:
            for i in range(self.shard_mapper.num_shards):
                emit("shard_status", {
                    "shard": str(i),
                    "status": self.shard_mapper.status(i).value,
                    "node": str(self.shard_mapper.node_of(i))}, 1)
        if self.backend is not None:
            emit("tile_cache_entries", {},
                 len(getattr(self.backend, "_tile_cache", ())))
            emit("tile_builds_total", {},
                 getattr(self.backend, "tile_builds", 0))
            emit("tile_cache_hits_total", {},
                 getattr(self.backend, "tile_hits", 0))
            # serving fast path: compiled-executable reuse (shape
            # buckets) + micro-batcher occupancy
            exec_stats = getattr(self.backend, "executable_cache_stats",
                                 None)
            if exec_stats is not None:
                st = exec_stats()
                emit("exec_cache_hits_total", {}, st["hits"])
                emit("exec_cache_misses_total", {}, st["misses"])
                emit("exec_cache_entries", {}, st["entries"])
            batcher = getattr(self.backend, "batcher", None)
            if batcher is not None:
                bs = batcher.stats.snapshot()
                emit("batcher_enabled", {}, 1 if batcher.enabled else 0)
                emit("batcher_batches_total", {}, bs["batches"])
                emit("batcher_queries_total", {}, bs["queries"])
                emit("batcher_batched_queries_total", {},
                     bs["batched_queries"])
                emit("batcher_occupancy_avg", {}, bs["occupancy_avg"])
                emit("batcher_occupancy_max", {}, bs["occupancy_max"])
                emit("batcher_gather_wait_ms_total", {},
                     bs["gather_wait_ms"])
                for cls, n in sorted(bs.get("by_priority",
                                            {}).items()):
                    emit("batcher_priority_queries_total",
                         {"class": cls}, n)
        pc = self.plan_cache.snapshot()
        emit("plan_cache_entries", {}, pc["entries"])
        emit("plan_cache_hits_total", {}, pc["hits"])
        emit("plan_cache_misses_total", {}, pc["misses"])
        emit("plan_cache_rebases_total", {}, pc["rebases"])
        emit("plan_cache_invalidations_total", {}, pc["invalidations"])
        for reason, n in sorted(
                pc.get("invalidations_by_reason", {}).items()):
            emit("plan_cache_invalidations_by_reason_total",
                 {"reason": reason}, n)
        rc = self.result_cache.snapshot()
        emit("result_cache_entries", {}, rc["entries"])
        emit("result_cache_bytes", {}, rc["bytes"])
        emit("result_cache_hits_total", {}, rc["hits"])
        emit("result_cache_partial_hits_total", {}, rc["partial_hits"])
        emit("result_cache_misses_total", {}, rc["misses"])
        emit("result_cache_stitches_total", {}, rc["stitches"])
        emit("result_cache_churn_recomputes_total", {},
             rc["churn_recomputes"])
        emit("result_cache_bypassed_total", {}, rc["bypassed"])
        emit("result_cache_degraded_skips_total", {},
             rc["degraded_skips"])
        emit("result_cache_evictions_total", {}, rc["evictions"])
        emit("result_cache_invalidations_total", {},
             rc["invalidations"])
        emit("result_cache_watermark_invalidations_total", {},
             rc["watermark_invalidations"])
        emit("result_cache_backfill_invalidations_total", {},
             rc["backfill_invalidations"])
        emit("result_cache_cached_steps_served_total", {},
             rc["cached_steps_served"])
        emit("result_cache_computed_steps_served_total", {},
             rc["computed_steps_served"])
        emit("result_cache_stale_serves_total", {},
             rc.get("stale_serves", 0))
        # tenant QoS: admission-gate counters + per-tenant budget
        # families (the supervisor sums these host-wide)
        adm = self.admission
        if adm is not None:
            asnap = adm.snapshot()
            emit("admission_max_inflight", {}, asnap["max_inflight"])
            emit("admission_inflight", {}, asnap["inflight"])
            emit("admission_wait_timeouts_total", {},
                 asnap["wait_timeouts"])
            emit("admission_rejected_total", {},
                 asnap["slot_rejections"])
            for tenant, t in sorted(adm.budgets.snapshot().items()):
                lbl = {"tenant": tenant}
                if "remaining" in t:
                    emit("tenant_budget_remaining", lbl,
                         t["remaining"])
                    emit("tenant_budget_rate", lbl, t["rate"])
                    emit("tenant_cost_charged_total", lbl,
                         round(t["charged_total"], 3))
                    emit("tenant_admitted_total", lbl, t["admitted"])
                    emit("tenant_throttled_total", lbl,
                         t["throttled"])
                    emit("tenant_forced_charges_total", lbl,
                         t["forced_charges"])
                for rung, n in sorted(t.get("degraded", {}).items()):
                    emit("tenant_degraded_total",
                         {**lbl, "rung": rung}, n)
                if t.get("rejected"):
                    emit("tenant_rejected_total", lbl, t["rejected"])
        # elastic membership: topology epoch, handoff/adoption state,
        # stale-routing bounce/retry counters, detector liveness
        if self.shard_mapper is not None \
                and hasattr(self.shard_mapper, "topology_epoch"):
            emit("topology_epoch", {},
                 self.shard_mapper.topology_epoch)
        mem = self.membership
        if mem is not None:
            ms = mem.metrics_snapshot()
            emit("shard_handoff_started_total", {},
                 ms["handoffs_started"])
            emit("shard_handoff_completed_total", {},
                 ms["handoffs_completed"])
            emit("shard_handoff_failed_total", {},
                 ms["handoffs_failed"])
            emit("shard_adoptions_total", {"kind": "planned"},
                 ms["adoptions_planned"])
            emit("shard_adoptions_total", {"kind": "crash"},
                 ms["adoptions_crash"])
            emit("shard_releases_total", {}, ms["releases"])
            emit("membership_draining", {}, ms["draining"])
            emit("membership_incoming_shards", {}, ms["incoming"])
            emit("handback_failures_total", {},
                 ms["handback_failures"])
        emit("stale_routing_bounces_total", {},
             self.stale_routing_bounces)
        emit("stale_routing_retries_total", {},
             self.stale_routing_retries)
        emit("peer_fanout_workers", {}, self.fanout_workers)
        if self.worker_id is not None:
            emit("worker_ordinal", {}, int(self.worker_id))
        bus = getattr(self, "bus_client", None)
        if bus is not None:
            bs = bus.metrics_snapshot()
            emit("bus_events_published_total", {}, bs["published"])
            emit("bus_events_applied_total", {}, bs["applied"])
            emit("bus_reconnects_total", {}, bs["reconnects"])
            emit("bus_connected", {}, bs["connected"])
        if self.detector is not None:
            emit("detector_thread_wedged", {},
                 1 if getattr(self.detector, "thread_wedged", False)
                 else 0)
        gs = getattr(self, "grpc_server", None)
        if gs is not None:
            emit("grpc_rpcs_served_total", {}, gs.rpcs_served)
        breakers = getattr(self.resilience, "breakers", None)
        if breakers is not None:
            # degraded-mode counters (PR 1 follow-up): per-peer breaker
            # state + retry-policy attempts/retries/exhaustions/
            # rejections from the server-lifetime BreakerRegistry
            for peer, entry in sorted(breakers.metrics_snapshot().items()):
                state = entry.get("state")
                if state is not None:
                    emit("breaker_state",
                         {"peer": peer, "state": state}, 1)
                for k in ("attempts", "retries", "exhaustions",
                          "rejections"):
                    if k in entry:
                        emit(f"peer_call_{k}_total", {"peer": peer},
                             entry[k])
        meter = getattr(self, "tenant_metering", None)
        if meter is not None:
            # periodic per-tenant cardinality gauges
            # (TenantIngestionMetering.scala publishes these on a timer)
            for prefix, (total, active) in sorted(meter.latest.items()):
                labels = {"_ws_": prefix[0] if len(prefix) > 0 else "",
                          "_ns_": prefix[1] if len(prefix) > 1 else ""}
                emit("tenant_time_series_total", labels, total)
                emit("tenant_time_series_active", labels, active)
            # metering-loop liveness: a stalled/dead snapshot thread
            # shows as a growing last-snapshot age
            emit("tenant_metering_interval_seconds", {},
                 meter.interval_s)
            age = meter.last_snapshot_age_s
            if age is not None:
                emit("tenant_metering_last_snapshot_age_seconds", {},
                     round(age, 3))
            emit("tenant_metering_snapshots_total", {}, meter.snapshots)
        # observability surfaces: tracer + slow-query-log + in-flight
        ts = self.tracer.snapshot()
        emit("traces_started_total", {}, ts["started"])
        emit("traces_stored", {}, ts["stored"])
        emit("slow_queries_total", {}, self.slow_log.snapshot()["recorded"])
        emit("inflight_queries", {}, len(self.inflight))
        sm = getattr(self, "selfmon", None)
        if sm is not None:
            # loop-liveness gauges (the counters/age families ride the
            # global registry and are collected below)
            emit("selfmon_alive", {}, 1 if sm.alive else 0)
            emit("selfmon_interval_seconds", {}, sm.interval_s)
        # tail-sampling retention + export health: only once tracing is
        # on (the default exposition stays byte-identical)
        if self.tracer.enabled:
            emit("traces_tail_dropped_total", {}, ts["tail_dropped"])
            for reason, n in sorted(ts["retained"].items()):
                emit("traces_retained_total", {"reason": reason}, n)
        exp = self.tracer.exporter
        if exp is not None:
            es = exp.snapshot()
            emit("trace_export_queue", {}, es["queued"])
            emit("trace_export_enqueued_total", {}, es["enqueued"])
        # sampling-profiler health (the self-time gauges + tick
        # histogram ride the global registry below)
        prof = self.profiler
        if prof is not None:
            ps = prof.snapshot()
            emit("profiler_running", {}, 1 if ps["running"] else 0)
            emit("profiler_hz", {}, ps["hz"])
            emit("profiler_samples_total", {}, ps["samples"])
            emit("profiler_attributed_samples_total", {},
                 ps["attributed"])
            emit("profiler_distinct_stacks", {}, ps["distinct_stacks"])
            emit("profiler_dropped_stacks_total", {},
                 ps["dropped_stacks"])
        # the global metric registry: counter/gauge families
        # (self-monitor, executable builds), registered collectors
        # (process stats, device-profiler cost gauges), then the
        # stage-latency histograms — query latency, batcher queue wait /
        # batch size, device execute, flush, ingest append + fsync
        obs_metrics.GLOBAL_REGISTRY.collect_into(b, exemplars=exemplars)
        return b

    def _cardinality(self, ds: str, qs: Dict, local: bool = False):
        """GET /api/v1/cardinality/{ds}?prefix=ws,ns&depth=N — per-prefix
        series counts from the cardinality trackers (TsCardinalities plan;
        reference TsCardExec + TenantIngestionMetering surface)."""
        shards = self.shards_by_dataset.get(ds)
        if shards is None:
            return 400, prom_json.error(f"dataset {ds} not set up")
        raw_prefix = self._param(qs, "prefix", "") or ""
        prefix = tuple(p for p in raw_prefix.split(",") if p)
        try:
            depth = int(self._param(qs, "depth",
                                    str(min(len(prefix) + 1, 3))))
        except ValueError:
            raise QueryError("depth must be an integer")
        if depth < len(prefix):
            raise QueryError("depth must be >= prefix length")
        recs = QueryEngine(shards).execute(
            lp.TsCardinalities(prefix, depth))
        if self.peers and not local:
            # cross-node merge: peers answer their local counts
            # (TsCardReduceExec scatter-gather)
            from filodb_tpu.core.cardinality import (CardinalityRecord,
                                                     merge_records)
            remote = self._peer_cardinality(ds, qs)
            recs = merge_records([recs] + [[
                CardinalityRecord(tuple(d["prefix"]), d["tsCount"],
                                  d["activeTsCount"], d["childrenCount"],
                                  d["childrenQuota"])
                for d in batch] for batch in remote])
        return 200, prom_json.success([r.to_json() for r in recs])

    def _peer_cardinality(self, ds: str, qs: Dict) -> List[List[Dict]]:
        targets = self._live_peer_urls(
            "{base}/api/v1/cardinality-local/%s" % ds, qs)
        return [p["data"] for p in self._fanout(targets)]

    # -- cluster plane ----------------------------------------------------
    def _raw_dispatch(self, ds: str, body: Optional[Dict], tctx=None):
        """POST /api/v1/raw/{ds}: the leaf-dispatch endpoint peers call to
        read raw series from THIS node's shards (PlanDispatcher.scala:21 —
        the entry node evaluates the plan over the merged series).
        ``tctx`` is the caller's propagated trace context: spans
        recorded here ride back in ``trace_spans`` for the entry node
        to stitch."""
        from filodb_tpu.parallel.cluster import (series_to_wire,
                                                 wire_to_filters)
        from filodb_tpu.query.model import QueryStats
        if body is None:
            return 400, prom_json.error("missing JSON body")
        # deadline propagation: the caller (an entry node mid-query)
        # forwards its REMAINING budget; this leaf inherits it instead
        # of running unbounded while the entry node has long timed out
        deadline = None
        if body.get("timeout_s") is not None:
            try:
                deadline = Deadline.after(
                    min(float(body["timeout_s"]), self.query_timeout_s))
            except (TypeError, ValueError):
                deadline = None
        tr = self.tracer.start(tctx) if tctx is not None else None
        # tenant QoS budget inheritance on the JSON leaf plane: forced
        # charge (the entry node already made the admission decision)
        # + the leg's priority class for the batcher
        qctx = None
        if body.get("tenant"):
            qctx = qos.QosContext(tenant=str(body["tenant"]),
                                  priority=int(body.get("priority")
                                               or 0), forced=True)
            adm = self.admission
            if adm is not None and adm.budgets.enabled:
                from filodb_tpu.parallel.cluster import wire_to_filters \
                    as _w2f
                adm.budgets.charge_forced(
                    qctx.tenant, qos.estimate_leaf_cost(
                        _w2f(body.get("filters", [])),
                        self.shards_by_dataset.get(ds, ()),
                        int(body.get("start_ms") or 0),
                        int(body.get("end_ms") or 0)))
        with qos.activate(qctx), obs_trace.activate(tr):
            with obs_trace.span("peer-fetch-raw",
                                node=self.node_id or "", dataset=ds,
                                plane="http"):
                try:
                    series = self.leaf_select(
                        ds, wire_to_filters(body.get("filters", [])),
                        int(body["start_ms"]), int(body["end_ms"]),
                        body.get("column"), body.get("shards"),
                        span_snap=bool(body.get("full", True)),
                        stats=QueryStats(), deadline=deadline)
                except StaleRoutingError as e:
                    # HTTP 200 + error envelope (not a 4xx): the
                    # caller must read the owners hint, and a non-2xx
                    # would surface as a retryable transport error
                    return 200, {
                        "status": "error",
                        "errorType": "stale_routing", "error": str(e),
                        "owners": {str(k): v
                                   for k, v in e.owners.items()},
                        "topo_epoch": e.epoch}
        if series is None:
            return 400, prom_json.error(f"dataset {ds} not set up")
        out = {"status": "success", "data": series_to_wire(series)}
        # every peer response carries the responder's topology epoch:
        # the entry node can cross-check its routing freshness
        if self.shard_mapper is not None \
                and hasattr(self.shard_mapper, "topology_epoch"):
            out["topo_epoch"] = self.shard_mapper.topology_epoch
        if tr is not None:
            out["trace_spans"] = tr.spans_json()
        return 200, out

    def leaf_select(self, ds: str, filters, start_ms: int, end_ms: int,
                    column, want_shards, span_snap: bool = True,
                    stats=None, deadline: Optional[Deadline] = None):
        """Shared leaf-dispatch selection (HTTP raw endpoint + the gRPC
        FetchRaw service): span-bounded reads with node-scoped snapshot
        keys, so the payload scales with the query span, not retention
        (SerializedRangeVector semantics, RangeVector.scala:452).
        ``deadline`` carries the entry node's forwarded remaining
        budget; selection checks it per shard and fails fast. A wanted
        shard that is NOT served here raises StaleRoutingError (with
        this node's owner map) instead of silently answering for a
        subset — the caller's routing lags a handoff and must not hand
        an incomplete result to its client."""
        from filodb_tpu.query.engine import (select_raw_series,
                                             select_span_series)
        shards = self.shards_by_dataset.get(ds)
        if shards is None:
            return None
        by_num = {getattr(s, "shard_num", i): s
                  for i, s in enumerate(shards)}
        if want_shards is not None:
            missing = [int(n) for n in want_shards if n not in by_num]
            if missing:
                self.stale_routing_bounces += 1
                owners = {}
                if self.shard_mapper is not None:
                    owners = {n: self.shard_mapper.node_of(n)
                              for n in missing}
                raise StaleRoutingError(
                    owners=owners,
                    epoch=getattr(self.shard_mapper, "topology_epoch",
                                  0) if self.shard_mapper is not None
                    else 0,
                    node=self.node_id or "",
                    detail=f"shards {sorted(missing)} are not served "
                           f"here")
        subset = [by_num[n] for n in want_shards if n in by_num] \
            if want_shards is not None else shards
        if span_snap:
            return select_span_series(
                subset, filters, start_ms, end_ms, column, stats,
                limits=self.query_limits, node_id=self.node_id or "",
                ds=ds, deadline=deadline)
        return select_raw_series(
            subset, filters, start_ms, end_ms, column, stats,
            full=False, limits=self.query_limits, deadline=deadline)

    def _live_peer_urls(self, path_fmt: str, qs: Dict) -> List[str]:
        """URLs for peers whose shards are still queryable (dead peers are
        skipped — the FailureDetector already marked them DOWN)."""
        targets = []
        for node, base in self.peers.items():
            if self.shard_mapper is not None:
                shards = self.shard_mapper.shards_for_node(node)
                if shards and not self.shard_mapper.active_shards(shards):
                    continue
            targets.append(path_fmt.format(base=base.rstrip("/"))
                           + "?" + urllib.parse.urlencode(qs, doseq=True))
        return targets

    def _fanout(self, targets: List[str]) -> List[Dict]:
        """Concurrent GETs; returns successful payloads only (down peers
        yield partial results, matching the query path's semantics).
        Concurrency is ``fanout_workers`` (knob ``peer-fanout-workers``,
        auto-sized from the core count; surfaced in /metrics) — the old
        hard-coded cap of 8 serialized metadata fan-out on wide
        clusters."""
        import urllib.request as ureq
        from concurrent.futures import ThreadPoolExecutor
        if not targets:
            return []

        def fetch(url):
            try:
                with ureq.urlopen(url, timeout=5) as r:
                    payload = json.loads(r.read())
                if payload.get("status") == "success":
                    return payload
            except (OSError, ValueError):
                pass
            return None

        with ThreadPoolExecutor(
                max_workers=min(self.fanout_workers,
                                len(targets))) as ex:
            return [p for p in ex.map(fetch, targets) if p]

    def _peer_metadata_union(self, ds: str, rest: str, qs: Dict) -> set:
        """Fan a labels/label-values request out to peers and union the
        results (metadata scatter-gather; MetadataRemoteExec
        equivalent)."""
        out: set = set()
        if qs.get("__local__"):
            return out
        q = dict(qs)
        q["__local__"] = ["1"]
        targets = self._live_peer_urls(
            "{base}/promql/%s/api/v1/%s" % (ds, rest), q)
        for payload in self._fanout(targets):
            out.update(tuple(sorted(d.items())) if isinstance(d, dict)
                       else d for d in payload["data"])
        return out

    # -- Prometheus remote-read -------------------------------------------
    def _remote_read(self, ds: str, body_raw: bytes):
        """POST /promql/{ds}/api/v1/read: snappy(ReadRequest protobuf) ->
        snappy(ReadResponse) (remote-storage.proto;
        PrometheusApiRoute.scala:129)."""
        from filodb_tpu.core.index import ColumnFilter
        from filodb_tpu.http import remote_read as rr
        from filodb_tpu.query.engine import select_raw_series
        from filodb_tpu.query.model import QueryStats
        from filodb_tpu.query import logical as lp2
        shards = self.shards_by_dataset.get(ds)
        if shards is None:
            return 400, prom_json.error(f"dataset {ds} not set up")
        if not body_raw:
            return 400, prom_json.error("missing remote-read body")
        try:
            queries = rr.decode_read_request(
                rr.snappy_decompress(body_raw))
        except (ValueError, IndexError) as e:
            raise QueryError(f"bad remote-read request: {e}")
        # resolve through the planner so cluster peers / buddy replicas
        # serve their shards — same coverage as /query_range
        planner = QueryPlanner(shards, shard_mapper=self.shard_mapper,
                               spread=self.spread,
                               spread_provider=self.spread_provider,
                               limits=self.query_limits,
                               node_id=self.node_id, peers=self.peers,
                               buddies=self.buddies, dataset=ds,
                               resilience=self.resilience,
                               deadline=Deadline.after(
                                   self.query_timeout_s))
        results = []
        for q in queries:
            # Prometheus clients send __name__; our index stores the
            # metric under the schema's metric column (_metric_), the
            # same mapping the PromQL parser applies
            filters = [ColumnFilter(
                "_metric_" if n == "__name__" else n, op, v)
                for n, op, v in q["matchers"]]
            plan = lp2.RawSeriesPlan(tuple(filters), q["start_ms"],
                                     q["end_ms"])
            shard_objs = planner._resolve_shards(plan)
            # federated workspaces: matchers pinning _ws_ to a partition
            # another cluster owns read that cluster's raw endpoint (the
            # same coverage /query_range gets from partition routing)
            ws = [f.value for f in filters
                  if f.label == "_ws_" and f.op == "eq"]
            if ws and self.partitions:
                url = self.partitions.get(ws[0])
                if url and ws[0] not in self.local_partitions:
                    from filodb_tpu.parallel.cluster import \
                        RemoteShardGroup
                    shard_objs = [RemoteShardGroup(
                        f"partition:{url}", url, ds, None)]
            series = select_raw_series(
                shard_objs, filters,
                q["start_ms"], q["end_ms"], None,
                QueryStats(), limits=self.query_limits)
            out = []
            for s in series:
                if s.values.ndim != 1:
                    continue    # histograms have no remote-read shape
                samples = [(int(t), float(v))
                           for t, v in zip(s.ts, s.values)]
                # external label form: _metric_ -> __name__ (same
                # mapping as the JSON path)
                out.append((prom_json._metric(dict(s.labels)), samples))
            results.append(out)
        return 200, rr.snappy_compress(rr.encode_read_response(results))
