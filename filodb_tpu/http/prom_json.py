"""Prometheus HTTP API JSON response shapes.

(Reference: query/PromQueryResponse.scala + PromCirceSupport — the
`{"status": "success", "data": {"resultType": ..., "result": [...]}}`
envelope; NaN serialization follows the reference's remote-read behavior
of stringified values, and absent samples are omitted from matrices like
Prometheus does.)"""

from __future__ import annotations

import math
from typing import Any, Dict, List

import numpy as np

from filodb_tpu.query.model import GridResult, ScalarResult


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def success(data: Any) -> Dict:
    return {"status": "success", "data": data}


def error(message: str, error_type: str = "bad_data",
          status: str = "error") -> Dict:
    return {"status": status, "errorType": error_type, "error": message}


def matrix(grid: GridResult, hist_wire: bool = False) -> Dict:
    """Range-query result as resultType=matrix; NaN steps are omitted
    (Prometheus staleness: absent sample, not NaN).

    ``hist_wire`` (internal cluster dispatch only) attaches native
    histogram rows as base64 [T, NB] blocks so a forwarded query keeps
    bucket data that the plain text format cannot carry."""
    result: List[Dict] = []
    steps_s = grid.steps / 1000.0
    for i, key in enumerate(grid.keys):
        row = grid.values[i]
        ok = ~np.isnan(row)
        entry = None
        if ok.any():
            values = [[float(t), _fmt(v)]
                      for t, v, o in zip(steps_s, row, ok) if o]
            entry = {"metric": _metric(key), "values": values}
        if hist_wire and grid.is_hist():
            import base64
            hv = np.ascontiguousarray(grid.hist_values[i],
                                      dtype=np.float64)
            entry = entry or {"metric": _metric(key), "values": []}
            entry["hist"] = {
                "les": [float(x) for x in np.asarray(grid.bucket_les)],
                "values": base64.b64encode(hv.tobytes()).decode(),
            }
        if entry is not None:
            result.append(entry)
    return success({"resultType": "matrix", "result": result})


def vector(grid: GridResult) -> Dict:
    """Instant-query result (single step) as resultType=vector."""
    result: List[Dict] = []
    t = float(grid.steps[-1]) / 1000.0 if grid.steps.size else 0.0
    for i, key in enumerate(grid.keys):
        v = grid.values[i, -1] if grid.values.size else np.nan
        if np.isnan(v):
            continue
        result.append({"metric": _metric(key), "value": [t, _fmt(v)]})
    return success({"resultType": "vector", "result": result})


def scalar(res: ScalarResult, instant: bool) -> Dict:
    if instant:
        t = float(res.steps[-1]) / 1000.0
        return success({"resultType": "scalar",
                        "result": [t, _fmt(res.values[-1])]})
    values = [[float(t) / 1000.0, _fmt(v)]
              for t, v in zip(res.steps, res.values)]
    return success({"resultType": "matrix",
                    "result": [{"metric": {}, "values": values}]})


def attach_degraded(out: Dict, res, stats=None) -> Dict:
    """Surface degraded-mode markers on a response envelope: union of
    grid- and stats-level warnings in ``warnings`` plus a top-level
    ``"partial": true`` when any shard group was dropped (the
    Thanos/M3 partial-response shape)."""
    warnings = list(getattr(stats, "warnings", ()) or ())
    partial = bool(getattr(stats, "partial", False))
    if isinstance(res, GridResult):
        warnings.extend(res.warnings)
        partial = partial or res.partial
    if warnings:
        out["warnings"] = sorted(set(warnings))
    if partial:
        out["partial"] = True
    return out


def _metric(key: Dict[str, str]) -> Dict[str, str]:
    out = {}
    for k, v in key.items():
        if k == "_metric_":
            out["__name__"] = v
        else:
            out[k] = v
    return out
