"""Prometheus HTTP API JSON response shapes.

(Reference: query/PromQueryResponse.scala + PromCirceSupport — the
`{"status": "success", "data": {"resultType": ..., "result": [...]}}`
envelope; NaN serialization follows the reference's remote-read behavior
of stringified values, and absent samples are omitted from matrices like
Prometheus does.)"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, List

import numpy as np

from filodb_tpu.query.model import GridResult, ScalarResult


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


# shortest-roundtrip float texts memoized across requests: metric
# streams repeat values heavily (constant rates, integer gauges), and a
# dict hit is ~10x cheaper than repr. Bounded by reset; no lock — a
# lost race just recomputes the same string (CPython dict ops are
# atomic; values are pure functions of the key).
_FMT_MEMO: Dict[float, str] = {}
_FMT_MEMO_MAX = 65536


def _fmt_row(steps_s: np.ndarray, row: np.ndarray, ok: np.ndarray
             ) -> List[List]:
    """Vectorized [ts, "value"] pairs for one matrix row (the serving
    fast path's JSON encode: per-element math.isnan/isinf checks in
    Python dominated the encode cost). ``tolist()`` converts in C; the
    per-element ``repr`` of a Python float is the same shortest-roundtrip
    text ``_fmt`` produces; rows with infinities (rare) fall back to
    ``_fmt`` for the +Inf/-Inf spellings."""
    vals = row[ok]
    ts = steps_s[ok].tolist()
    if np.isinf(vals).any():
        return [[t, _fmt(v)] for t, v in zip(ts, vals.tolist())]
    memo = _FMT_MEMO
    if len(memo) > _FMT_MEMO_MAX:
        memo.clear()
    out = []
    for t, v in zip(ts, vals.tolist()):
        s = memo.get(v)
        if s is None:
            memo[v] = s = repr(v)
        out.append([t, s])
    return out


def success(data: Any) -> Dict:
    return {"status": "success", "data": data}


def error(message: str, error_type: str = "bad_data",
          status: str = "error") -> Dict:
    return {"status": status, "errorType": error_type, "error": message}


class PreEncoded:
    """Response payload already serialized to JSON bytes (the serving
    fast path skips the dict -> json.dumps walk for bulk matrix data);
    the HTTP edge sends ``body`` verbatim with ``ctype``."""

    __slots__ = ("body", "ctype")

    def __init__(self, body: bytes,
                 ctype: str = "application/json"):
        self.body = body
        self.ctype = ctype


# timestamps repeat across queries (step grids) and values repeat across
# steps (constant rates, integer gauges): memoized fragments make the
# bulk encode mostly dict lookups. Unlocked by design — racing writers
# recompute identical strings (CPython dict ops are atomic).
_TS_MEMO: Dict[float, str] = {}


def _ts_frag(t: float) -> str:
    s = _TS_MEMO.get(t)
    if s is None:
        if len(_TS_MEMO) > _FMT_MEMO_MAX:
            _TS_MEMO.clear()
        _TS_MEMO[t] = s = repr(t)
    return s


def matrix_bytes(grid: GridResult, stats_json: Dict,
                 warnings=None, partial: bool = False,
                 rows_memo=None) -> PreEncoded:
    """Serving fast path: a range-query matrix response encoded straight
    to JSON bytes. Byte-identical to ``json.dumps(matrix(grid)
    [+stats/degraded], separators=(",", ":"))`` — pinned by
    tests/test_http_e2e-style golden comparisons in test_plancache.

    Only the plain scalar-matrix shape takes this path (histogram wire
    and scalar results keep the dict path).

    ``rows_memo`` is a results-cache handle (``.get() -> str|None``,
    ``.put(text)``) present only on a FULL hit: the rendered result-row
    text is a pure function of the (immutable) cached extent and the
    range, so repeat hits splice the memoized rows and re-encode only
    the per-request stats tail; stored text is charged against the
    cache's byte budget. Racing writers store identical strings."""
    joined = None
    if rows_memo is not None:
        joined = rows_memo.get()
    if joined is None:
        rows: List[tuple] = []
        steps_s = grid.steps / 1000.0
        memo = _FMT_MEMO
        if len(memo) > _FMT_MEMO_MAX:
            memo.clear()
        for i, key in enumerate(grid.keys):
            row = grid.values[i]
            ok = ~np.isnan(row)
            if not ok.any():
                continue
            vals = row[ok]
            ts = steps_s[ok].tolist()
            metric = json.dumps(_metric(key), sort_keys=True,
                                separators=(",", ":"))
            if np.isinf(vals).any():
                frags = [f'[{_ts_frag(t)},"{_fmt(v)}"]'
                         for t, v in zip(ts, vals.tolist())]
            else:
                frags = []
                for t, v in zip(ts, vals.tolist()):
                    s = memo.get(v)
                    if s is None:
                        memo[v] = s = repr(v)
                    frags.append(f'[{_ts_frag(t)},"{s}"]')
            rows.append((metric, '{"metric":%s,"values":[%s]}'
                         % (metric, ",".join(frags))))
        # deterministic series order (sorted by the encoded metric):
        # responses are a pure function of the data, not of scan /
        # ingest / peer-merge order — the property that makes
        # single-worker and N-worker serving byte-identical
        rows.sort(key=lambda kv: kv[0])
        joined = ",".join(txt for _, txt in rows)
        if rows_memo is not None:
            rows_memo.put(joined)
    tail = ',"stats":' + json.dumps(stats_json, separators=(",", ":"))
    if warnings:
        tail += ',"warnings":' + json.dumps(sorted(set(warnings)),
                                            separators=(",", ":"))
    if partial:
        tail += ',"partial":true'
    body = ('{"status":"success","data":{"resultType":"matrix",'
            '"result":[' + joined + "]}" + tail + "}")
    return PreEncoded(body.encode())


def matrix(grid: GridResult, hist_wire: bool = False) -> Dict:
    """Range-query result as resultType=matrix; NaN steps are omitted
    (Prometheus staleness: absent sample, not NaN).

    ``hist_wire`` (internal cluster dispatch only) attaches native
    histogram rows as base64 [T, NB] blocks so a forwarded query keeps
    bucket data that the plain text format cannot carry."""
    result: List[Dict] = []
    steps_s = grid.steps / 1000.0
    for i, key in enumerate(grid.keys):
        row = grid.values[i]
        ok = ~np.isnan(row)
        entry = None
        if ok.any():
            values = _fmt_row(steps_s, row, ok)
            entry = {"metric": _metric(key), "values": values}
        if hist_wire and grid.is_hist():
            import base64
            hv = np.ascontiguousarray(grid.hist_values[i],
                                      dtype=np.float64)
            entry = entry or {"metric": _metric(key), "values": []}
            entry["hist"] = {
                "les": [float(x) for x in np.asarray(grid.bucket_les)],
                "values": base64.b64encode(hv.tobytes()).decode(),
            }
        if entry is not None:
            result.append(entry)
    result.sort(key=_entry_order)       # deterministic series order
    return success({"resultType": "matrix", "result": result})


def vector(grid: GridResult) -> Dict:
    """Instant-query result (single step) as resultType=vector."""
    result: List[Dict] = []
    t = float(grid.steps[-1]) / 1000.0 if grid.steps.size else 0.0
    for i, key in enumerate(grid.keys):
        v = grid.values[i, -1] if grid.values.size else np.nan
        if np.isnan(v):
            continue
        result.append({"metric": _metric(key), "value": [t, _fmt(v)]})
    result.sort(key=_entry_order)       # deterministic series order
    return success({"resultType": "vector", "result": result})


def scalar(res: ScalarResult, instant: bool) -> Dict:
    if instant:
        t = float(res.steps[-1]) / 1000.0
        return success({"resultType": "scalar",
                        "result": [t, _fmt(res.values[-1])]})
    values = [[float(t) / 1000.0, _fmt(v)]
              for t, v in zip(res.steps, res.values)]
    return success({"resultType": "matrix",
                    "result": [{"metric": {}, "values": values}]})


def attach_degraded(out: Dict, res, stats=None) -> Dict:
    """Surface degraded-mode markers on a response envelope: union of
    grid- and stats-level warnings in ``warnings`` plus a top-level
    ``"partial": true`` when any shard group was dropped (the
    Thanos/M3 partial-response shape)."""
    warnings = list(getattr(stats, "warnings", ()) or ())
    partial = bool(getattr(stats, "partial", False))
    if isinstance(res, GridResult):
        warnings.extend(res.warnings)
        partial = partial or res.partial
    if warnings:
        out["warnings"] = sorted(set(warnings))
    if partial:
        out["partial"] = True
    return out


def _entry_order(entry: Dict) -> str:
    """Sort key for result entries: the canonically-encoded metric.
    Both encode paths (dict tree and pre-encoded bytes) order series by
    it, so a response is a pure function of its data — single-worker
    and N-worker topologies answer byte-identically even though their
    scan/peer-merge orders differ."""
    return json.dumps(entry["metric"], sort_keys=True,
                      separators=(",", ":"))


def _metric(key: Dict[str, str]) -> Dict[str, str]:
    # sorted OUTPUT label order: the JSON text of a metric (and
    # therefore the _entry_order sort key and the matrix_bytes
    # fragments) is stable regardless of the label-map construction
    # order upstream, and insertion-order json.dumps matches
    # sort_keys=True exactly
    return dict(sorted(("__name__" if k == "_metric_" else k, v)
                       for k, v in key.items()))
