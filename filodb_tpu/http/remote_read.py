"""Prometheus remote-read: snappy-framed protobuf over HTTP POST.

(Reference: prometheus/src/main/proto/remote-storage.proto +
PrometheusApiRoute.scala:129 — the standard Prometheus remote storage
interchange: ReadRequest{Query{matchers,start,end}} in,
ReadResponse{QueryResult{TimeSeries{labels,samples}}} out, both snappy
raw-block compressed.)

No third-party deps: the protobuf wire format for these flat messages is
hand-coded (varint/length-delimited/fixed64), and snappy's raw block
format is implemented here — a complete decompressor (Prometheus sends
real compressed bodies) and a spec-valid literal-run compressor for
responses.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Sequence, Tuple

# ---------------------------------------------------------------------------
# snappy raw block format (no framing)
# ---------------------------------------------------------------------------


MAX_UNCOMPRESSED = 64 << 20     # decompression-bomb guard (DoS)


def snappy_decompress(buf: bytes,
                      max_len: int = MAX_UNCOMPRESSED) -> bytes:
    """Full snappy block decompressor (literals + all three copy tags).
    Bounded by ``max_len`` — /read is unauthenticated, so a crafted tiny
    body must not balloon into unbounded memory/CPU."""
    # preamble: uvarint uncompressed length (<= 5 bytes per snappy spec;
    # unbounded continuation bytes would be a bigint CPU bomb)
    ulen = 0
    shift = 0
    pos = 0
    while True:
        if shift > 32:
            raise ValueError("snappy: preamble varint too long")
        b = buf[pos]
        pos += 1
        ulen |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    if ulen > max_len:
        raise ValueError(f"snappy: declared length {ulen} over limit")
    out = bytearray()
    n = len(buf)
    while pos < n:
        tag = buf[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:                       # literal
            length = tag >> 2
            if length >= 60:
                extra = length - 59
                length = int.from_bytes(buf[pos:pos + extra], "little")
                pos += extra
            length += 1
            out += buf[pos:pos + length]
            pos += length
            continue
        if kind == 1:                       # copy, 1-byte offset
            length = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | buf[pos]
            pos += 1
        elif kind == 2:                     # copy, 2-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(buf[pos:pos + 2], "little")
            pos += 2
        else:                               # copy, 4-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        if offset == 0:
            raise ValueError("snappy: zero copy offset")
        start = len(out) - offset
        if start < 0:
            raise ValueError("snappy: offset before start")
        if len(out) + length > ulen:
            raise ValueError("snappy: output exceeds declared length")
        if offset >= length:
            out += out[start:start + length]    # non-overlapping: slice
        else:
            # overlapping copies are byte-at-a-time by spec
            for i in range(length):
                out.append(out[start + i])
    if len(out) != ulen:
        raise ValueError(f"snappy: length mismatch {len(out)} != {ulen}")
    return bytes(out)


def snappy_compress(data: bytes) -> bytes:
    """Spec-valid snappy: uvarint length + literal runs (no back-refs —
    correctness over ratio; peers decompress it with any snappy impl)."""
    out = bytearray()
    ulen = len(data)
    while True:
        b = ulen & 0x7F
        ulen >>= 7
        out.append(b | (0x80 if ulen else 0))
        if not ulen:
            break
    pos = 0
    n = len(data)
    while pos < n:
        chunk = min(n - pos, 1 << 24)
        if chunk <= 60:
            out.append((chunk - 1) << 2)
        elif chunk <= 0xFF:
            out.append(60 << 2)
            out.append(chunk - 1)
        elif chunk <= 0xFFFF:
            out.append(61 << 2)
            out += (chunk - 1).to_bytes(2, "little")
        else:
            out.append(62 << 2)
            out += (chunk - 1).to_bytes(3, "little")
        out += data[pos:pos + chunk]
        pos += chunk
    return bytes(out)


# ---------------------------------------------------------------------------
# minimal protobuf wire codec for the remote-storage messages
# ---------------------------------------------------------------------------


def _uvarint(v: int) -> bytes:
    out = bytearray()
    if v < 0:
        v &= (1 << 64) - 1              # proto int64 two's complement
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def _read_uvarint(buf: bytes, pos: int) -> Tuple[int, int]:
    v = 0
    shift = 0
    while True:
        if shift > 63:      # proto varints are <= 10 bytes
            raise ValueError("protobuf: varint too long")
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_uvarint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_uvarint(buf, pos)
        elif wire == 1:
            v = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _read_uvarint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, v


def _ld(field: int, payload: bytes) -> bytes:
    return _uvarint((field << 3) | 2) + _uvarint(len(payload)) + payload


def _vi(field: int, v: int) -> bytes:
    return _uvarint(field << 3) + _uvarint(v)


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


# matcher type enum -> our ColumnFilter ops (LabelMatcher.Type)
_MATCHER_OPS = {0: "eq", 1: "neq", 2: "re", 3: "nre"}


def decode_read_request(buf: bytes) -> List[Dict]:
    """[{start_ms, end_ms, matchers: [(name, op, value), ...]}, ...]"""
    queries = []
    for field, _, v in _fields(buf):
        if field != 1:          # repeated Query queries = 1
            continue
        q = {"start_ms": 0, "end_ms": 0, "matchers": []}
        for f2, _, v2 in _fields(v):
            if f2 == 1:
                q["start_ms"] = _signed(v2)
            elif f2 == 2:
                q["end_ms"] = _signed(v2)
            elif f2 == 3:       # LabelMatcher
                mtype, name, value = 0, "", ""
                for f3, _, v3 in _fields(v2):
                    if f3 == 1:
                        mtype = v3
                    elif f3 == 2:
                        name = v3.decode()
                    elif f3 == 3:
                        value = v3.decode()
                q["matchers"].append(
                    (name, _MATCHER_OPS.get(mtype, "eq"), value))
        queries.append(q)
    return queries


def encode_read_request(queries: Sequence[Dict]) -> bytes:
    """Inverse of decode_read_request (used by tests/clients)."""
    ops = {v: k for k, v in _MATCHER_OPS.items()}
    out = b""
    for q in queries:
        body = _vi(1, q["start_ms"]) + _vi(2, q["end_ms"])
        for name, op, value in q["matchers"]:
            m = _vi(1, ops[op]) + _ld(2, name.encode()) \
                + _ld(3, value.encode())
            body += _ld(3, m)
        out += _ld(1, body)
    return out


def encode_read_response(results: Sequence[Sequence[Tuple[Dict, list]]]
                         ) -> bytes:
    """results: per query, a list of (labels, [(ts_ms, value), ...])."""
    out = b""
    for series_list in results:
        qr = b""
        for labels, samples in series_list:
            ts_msg = b""
            for name in sorted(labels):
                ts_msg += _ld(1, _ld(1, name.encode())
                              + _ld(2, labels[name].encode()))
            for ts_ms, value in samples:
                s = _uvarint((1 << 3) | 1) + struct.pack("<d", value) \
                    + _vi(2, int(ts_ms))
                ts_msg += _ld(2, s)
            qr += _ld(1, ts_msg)
        out += _ld(1, qr)
    return out


def decode_read_response(buf: bytes):
    """Inverse of encode_read_response."""
    results = []
    for field, _, v in _fields(buf):
        if field != 1:
            continue
        series_list = []
        for f2, _, v2 in _fields(v):
            if f2 != 1:
                continue
            labels: Dict[str, str] = {}
            samples: List[Tuple[int, float]] = []
            for f3, _, v3 in _fields(v2):
                if f3 == 1:
                    name = value = ""
                    for f4, _, v4 in _fields(v3):
                        if f4 == 1:
                            name = v4.decode()
                        elif f4 == 2:
                            value = v4.decode()
                    labels[name] = value
                elif f3 == 2:
                    val, ts = 0.0, 0
                    for f4, _, v4 in _fields(v3):
                        if f4 == 1:
                            (val,) = struct.unpack("<d", v4)
                        elif f4 == 2:
                            ts = _signed(v4)
                    samples.append((ts, val))
            series_list.append((labels, samples))
        results.append(series_list)
    return results
