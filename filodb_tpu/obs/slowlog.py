"""Slow-query log + in-flight query registry.

The operational complement to tracing: tracing samples, the slow-query
log CATCHES — every query whose total latency crosses the threshold
leaves a structured record (query text, dataset, shards touched,
per-stage breakdown, cache dispositions, partial/warning markers, and
the trace id when one was sampled), retrievable from a bounded ring at
``/debug/slow_queries`` and mirrored to the standard logger. The
in-flight registry behind ``/debug/queries`` answers the on-call
question "what is running RIGHT NOW and which stage is it stuck in"
(the reference's QueryActor mailbox visibility equivalent).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from filodb_tpu.lint.locks import guarded_by

log = logging.getLogger("filodb.slowquery")


@guarded_by("_lock", "_records", "recorded")
class SlowQueryLog:
    """Bounded ring of structured slow-query records.

    ``threshold_ms <= 0`` disables recording entirely (one float
    compare per query)."""

    def __init__(self, threshold_ms: float = 1000.0, capacity: int = 128):
        self.threshold_ms = float(threshold_ms)
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=self.capacity)
        self.recorded = 0

    @property
    def enabled(self) -> bool:
        return self.threshold_ms > 0

    def maybe_record(self, elapsed_ms: float, record: Dict) -> bool:
        """Record when over threshold; ``record`` is the caller-built
        structured dict (the caller only builds it on the slow path)."""
        if self.threshold_ms <= 0 or elapsed_ms < self.threshold_ms:
            return False
        record = dict(record)
        record["elapsed_ms"] = round(float(elapsed_ms), 3)
        record["ts"] = time.time()
        with self._lock:
            self._records.append(record)
            self.recorded += 1
        try:
            log.warning("slow query (%.1fms > %.0fms): %s",
                        elapsed_ms, self.threshold_ms,
                        record.get("query", "?"))
        except Exception:
            pass
        return True

    def records(self, limit: int = 50) -> List[Dict]:
        with self._lock:
            out = list(self._records)
        return out[-max(1, int(limit)):][::-1]

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"threshold_ms": self.threshold_ms,
                    "recorded": self.recorded,
                    "stored": len(self._records)}


@guarded_by("_lock", "_inflight")
class InflightRegistry:
    """Currently-running queries and their elapsed stage.

    ``register`` returns a token the request path mutates through
    ``stage()`` (a plain dict write — readers tolerate racy snapshots,
    this is debug introspection, not accounting) and releases via
    ``unregister`` in a finally block."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: Dict[int, Dict] = {}
        self._ids = itertools.count(1)

    def register(self, query: str, dataset: str, **extra) -> Dict:
        qid = next(self._ids)
        entry = {"id": qid, "query": query, "dataset": dataset,
                 "t0": time.time(), "stage": "start", **extra}
        with self._lock:
            self._inflight[qid] = entry
        return entry

    @staticmethod
    def stage(entry: Optional[Dict], stage: str) -> None:
        if entry is not None:
            entry["stage"] = stage

    def unregister(self, entry: Optional[Dict]) -> None:
        if entry is None:
            return
        with self._lock:
            self._inflight.pop(entry["id"], None)

    def snapshot(self) -> List[Dict]:
        now = time.time()
        with self._lock:
            entries = [dict(e) for e in self._inflight.values()]
        out = []
        for e in sorted(entries, key=lambda e: e["t0"]):
            e["elapsed_ms"] = round((now - e.pop("t0")) * 1000, 3)
            out.append(e)
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._inflight)
