"""Observability spine: distributed tracing, Prometheus histograms,
and the slow-query / in-flight registry.

The reference system instruments every shard and exec-plan node through
Kamon (TimeSeriesShardStats, TimeSeriesShard.scala:41; Kamon spans in
QueryActor) and threads QueryStats through execution. This package is
the TPU build's equivalent, shaped for the post-PR-3 concurrent serving
pipeline (plan cache -> micro-batcher -> async device executor ->
HTTP/gRPC peer fan-out):

  * :mod:`filodb_tpu.obs.trace` — a lightweight span API (context
    manager, ~zero cost when no trace is active, sampled when enabled)
    with Dapper-style trace context propagated on both planes (the
    ``X-Filo-Trace`` HTTP header and dedicated gRPC wire fields), so a
    cluster query yields ONE stitched trace covering parse ->
    plan-cache -> select -> pack -> batcher-queue-wait ->
    device-dispatch -> device-sync -> remote-peer subspans (including
    retry attempts and breaker rejections) -> JSON encode.
  * :mod:`filodb_tpu.obs.metrics` — a fixed-bucket Prometheus histogram
    primitive (``_bucket``/``_sum``/``_count`` exposition with
    ``# HELP``/``# TYPE``) replacing point gauges for the stage
    latencies, so p50/p95/p99 are scrapeable instead of recomputed in
    bench scripts.
  * :mod:`filodb_tpu.obs.slowlog` — the slow-query log (structured
    records for queries over a threshold, with a per-stage breakdown)
    and the in-flight query registry behind ``/debug/queries``.
  * :mod:`filodb_tpu.obs.devprof` — device compile/cost profiling:
    per-executable build/recompile counters, XLA ``cost_analysis``
    FLOPs/bytes, and the ``&explain=analyze`` payload.
  * :mod:`filodb_tpu.obs.process` — host/process-level collector
    (RSS, fds, threads, GC, uptime, build info).
  * :mod:`filodb_tpu.obs.selfmon` — the self-monitoring loop: the
    node ingests its own metrics into the reserved ``__selfmon__``
    dataset through the normal ingest path and serves them over
    PromQL.
"""

from filodb_tpu.obs.metrics import (  # noqa: F401
    GLOBAL_REGISTRY, Histogram, MetricsRegistry)
from filodb_tpu.obs.slowlog import (  # noqa: F401
    InflightRegistry, SlowQueryLog)
from filodb_tpu.obs.trace import (  # noqa: F401
    Span, Trace, Tracer, span, trace_active)
