"""Lightweight distributed tracing for the serving pipeline.

Dapper-style propagated trace context: an entry node starts a trace
(sampled), every stage opens spans through the :func:`span` context
manager, and remote hops forward ``trace_id`` + the parent span id on
the wire (HTTP: the ``X-Filo-Trace`` header; gRPC: dedicated fields in
RawRequest/ExecRequest). The PEER records its spans locally and ships
them back in the response envelope, so the entry node's recorder holds
one stitched trace covering every hop — the standard tool for
attributing tail latency in a fan-out system.

Design constraints:

  * ~zero cost when no trace is active: ``span()`` reads one
    thread-local attribute and returns a shared no-op context manager.
    No allocation, no clock read, no string formatting happens on the
    untraced path — disabled-tracing responses stay byte-identical and
    the bench overhead stays within noise.
  * spans may be recorded from multiple threads (HTTP workers, the
    batcher's device-executor thread): the active trace is carried in a
    thread-local and can be captured/reinstalled across thread hops
    (:func:`capture` / :func:`use` — the micro-batcher does this for
    closures it runs on the executor thread).
  * bounded memory: a trace stops recording past ``MAX_SPANS`` (a
    runaway fan-out can't balloon the ring buffer), and the
    :class:`Tracer`'s recorder keeps the last N finished traces.
"""

from __future__ import annotations

import itertools
import json
import random
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import thread_root

# spans per trace cap: a 256-shard fan-out with retries stays well under
# this; anything bigger is a runaway and gets truncated (tagged).
MAX_SPANS = 512

_ids = itertools.count(1)
_state = threading.local()


def _new_id() -> str:
    # 64-bit random hex; cheap, collision-safe at ring-buffer scale
    return f"{random.getrandbits(64):016x}"


class Span:
    """One timed operation inside a trace. Created via :func:`span`;
    mutate tags through ``tag()`` while open."""

    __slots__ = ("name", "span_id", "parent_id", "start_ns", "dur_ns",
                 "tags", "error")

    def __init__(self, name: str, span_id: str, parent_id: Optional[str],
                 start_ns: int):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.dur_ns = -1            # -1 = still open
        self.tags: Dict[str, object] = {}
        self.error: Optional[str] = None

    def tag(self, **tags) -> "Span":
        self.tags.update(tags)
        return self

    def to_json(self) -> Dict:
        d = {"name": self.name, "span_id": self.span_id,
             "parent_id": self.parent_id,
             "start_us": self.start_ns // 1000,
             "dur_us": self.dur_ns // 1000 if self.dur_ns >= 0 else -1}
        if self.tags:
            d["tags"] = dict(self.tags)
        if self.error:
            d["error"] = self.error
        return d

    @classmethod
    def from_json(cls, d: Dict) -> "Span":
        s = cls(d.get("name", "?"), d.get("span_id", "?"),
                d.get("parent_id"), int(d.get("start_us", 0)) * 1000)
        dur = int(d.get("dur_us", -1))
        s.dur_ns = dur * 1000 if dur >= 0 else -1
        s.tags = dict(d.get("tags") or {})
        s.error = d.get("error")
        return s


class Trace:
    """One trace being recorded on THIS node (entry node or a peer
    serving a propagated context). Span appends are lock-protected —
    HTTP workers and the device executor both record."""

    __slots__ = ("trace_id", "node", "spans", "truncated", "_lock",
                 "root_parent", "sampled", "retain_reason")

    def __init__(self, trace_id: Optional[str] = None,
                 node: str = "", root_parent: Optional[str] = None,
                 sampled: bool = True):
        self.trace_id = trace_id or _new_id()
        self.node = node
        # parent span id carried in from the caller (peer hop); local
        # root spans attach under it so the entry node stitches cleanly
        self.root_parent = root_parent
        self.spans: List[Span] = []
        self.truncated = False
        # tail sampling: a PENDING trace records spans exactly like a
        # sampled one, but only survives into the recorder if the
        # finish-time retention decision (error / shed / slow / coin)
        # keeps it. ``sampled=False`` marks "coin said drop unless the
        # outcome is interesting"; ``retain_reason`` is stamped by
        # Tracer.finish_request for /debug/traces readers.
        self.sampled = sampled
        self.retain_reason: Optional[str] = None
        self._lock = threading.Lock()

    def add(self, sp: Span) -> None:
        with self._lock:
            if len(self.spans) >= MAX_SPANS:
                self.truncated = True
                return
            self.spans.append(sp)

    def absorb(self, spans_json: List[Dict]) -> None:
        """Fold a peer's serialized spans into this trace (the stitch).
        The peer already parented them under the span id we forwarded."""
        with self._lock:
            for d in spans_json:
                if len(self.spans) >= MAX_SPANS:
                    self.truncated = True
                    return
                self.spans.append(Span.from_json(d))

    def spans_json(self) -> List[Dict]:
        with self._lock:
            return [s.to_json() for s in self.spans]

    def to_json(self) -> Dict:
        spans = self.spans_json()
        dur = 0
        for s in spans:
            if s["parent_id"] is None or s["parent_id"] == \
                    self.root_parent:
                dur = max(dur, s["dur_us"])
        d = {"trace_id": self.trace_id, "node": self.node,
             "num_spans": len(spans), "duration_us": dur,
             "truncated": self.truncated, "spans": spans}
        if self.retain_reason is not None:
            d["retained"] = self.retain_reason
        return d


class _NoopSpan:
    """Shared do-nothing context manager: the untraced fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def tag(self, **tags):
        return self


_NOOP = _NoopSpan()


class _LiveSpan:
    """Context manager recording one span into the active trace."""

    __slots__ = ("_trace", "_span", "_prev")

    def __init__(self, trace: Trace, name: str, parent_id: Optional[str],
                 tags: Dict):
        self._trace = trace
        sp = Span(name, _new_id(), parent_id, time.time_ns())
        if tags:
            sp.tags.update(tags)
        self._span = sp

    def __enter__(self) -> Span:
        self._prev = getattr(_state, "parent", None)
        _state.parent = self._span.span_id
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        sp = self._span
        sp.dur_ns = time.time_ns() - sp.start_ns
        if exc is not None and sp.error is None:
            sp.error = f"{type(exc).__name__}: {exc}"
        _state.parent = self._prev
        self._trace.add(sp)
        return False


# -- the thread-local active-trace API ---------------------------------------

def span(name: str, **tags):
    """Open a span under the thread's active trace; no-op (shared
    object, no allocation) when no trace is active. Usable from any
    layer without threading a tracer object through."""
    tr = getattr(_state, "trace", None)
    if tr is None:
        return _NOOP
    return _LiveSpan(tr, name, getattr(_state, "parent", None), tags)


def event(name: str, **tags) -> None:
    """Zero-duration span (a point annotation, e.g. a breaker
    rejection); no-op when no trace is active."""
    tr = getattr(_state, "trace", None)
    if tr is None:
        return
    sp = Span(name, _new_id(), getattr(_state, "parent", None),
              time.time_ns())
    sp.dur_ns = 0
    if tags:
        sp.tags.update(tags)
    tr.add(sp)


def trace_active() -> bool:
    return getattr(_state, "trace", None) is not None


def current_trace() -> Optional[Trace]:
    return getattr(_state, "trace", None)


def capture() -> Optional[Tuple[Trace, Optional[str]]]:
    """Snapshot (trace, parent span id) for reinstalling on another
    thread (the batcher's executor hop); None when untraced."""
    tr = getattr(_state, "trace", None)
    if tr is None:
        return None
    return tr, getattr(_state, "parent", None)


class use:
    """Reinstall a captured trace context on the current thread:
    ``with trace.use(ctx): ...``. ``ctx=None`` is a no-op (so callers
    can pass ``capture()``'s result through unconditionally)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[Tuple[Trace, Optional[str]]]):
        self._ctx = ctx

    def __enter__(self):
        if self._ctx is None:
            return self
        self._prev = (getattr(_state, "trace", None),
                      getattr(_state, "parent", None))
        _state.trace = self._ctx[0]
        _state.parent = self._ctx[1]
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            _state.trace, _state.parent = self._prev
        return False


class activate:
    """Install ``trace`` as the thread's active trace for the scope
    (the per-request entry point; :class:`Tracer` wraps this)."""

    __slots__ = ("_trace", "_prev")

    def __init__(self, trace: Optional[Trace]):
        self._trace = trace

    def __enter__(self) -> Optional[Trace]:
        self._prev = (getattr(_state, "trace", None),
                      getattr(_state, "parent", None))
        _state.trace = self._trace
        _state.parent = self._trace.root_parent \
            if self._trace is not None else None
        return self._trace

    def __exit__(self, *exc):
        _state.trace, _state.parent = self._prev
        return False


# -- wire propagation --------------------------------------------------------

HEADER = "X-Filo-Trace"


def inject_header() -> Optional[str]:
    """``trace_id-parent_span_id-1`` for the active trace (the b3-style
    single header), or None when untraced."""
    tr = getattr(_state, "trace", None)
    if tr is None:
        return None
    parent = getattr(_state, "parent", None) or ""
    return f"{tr.trace_id}-{parent}-1"


def parse_context(value: Optional[str]
                  ) -> Optional[Tuple[str, Optional[str]]]:
    """Parse a propagated context into (trace_id, parent_span_id);
    None on absent/malformed input (malformed context must never fail
    a query)."""
    if not value:
        return None
    parts = str(value).split("-")
    if len(parts) < 1 or not parts[0]:
        return None
    parent = parts[1] if len(parts) > 1 and parts[1] else None
    return parts[0], parent


def spans_wire(trace: Optional[Trace]) -> bytes:
    """Serialized spans for a response envelope (gRPC field / HTTP
    JSON); empty when untraced."""
    if trace is None:
        return b""
    return json.dumps(trace.spans_json(),
                      separators=(",", ":")).encode()


def absorb_spans(spans) -> None:
    """Fold a peer's already-parsed span list (JSON-decoded dicts) into
    the active trace; no-op when untraced or empty."""
    tr = getattr(_state, "trace", None)
    if tr is None or not spans:
        return
    try:
        tr.absorb([d for d in spans if isinstance(d, dict)])
    except (TypeError, ValueError):
        pass


def absorb_wire(buf) -> None:
    """Fold a peer's serialized span list into the active trace;
    tolerant of garbage (a peer's malformed payload must never fail
    the query)."""
    tr = getattr(_state, "trace", None)
    if tr is None or not buf:
        return
    try:
        if isinstance(buf, (bytes, bytearray)):
            buf = buf.decode()
        spans = json.loads(buf)
        if isinstance(spans, list):
            tr.absorb([d for d in spans if isinstance(d, dict)])
    except (ValueError, UnicodeDecodeError):
        pass


# -- the per-server tracer ---------------------------------------------------

class Tracer:
    """Sampling policy + bounded recorder of finished traces.

    One per server process (the HTTP server owns it). ``enabled=False``
    (the default) never starts traces — ``span()`` stays on the no-op
    path everywhere. A propagated context from a caller is always
    honored (the entry node made the sampling decision).

    Sampling is TAIL-based: when tracing is enabled, EVERY fresh
    request records into a cheap pending :class:`Trace`; the sampling
    coin only decides whether an *uninteresting* outcome survives.
    :meth:`finish_request` runs the retention decision on outcome —
    errors, shed/degraded results, and latency above ``slow_ms`` are
    always retained (so slowlog entries always link a live trace), the
    rest keep the ``sample_rate`` coin — so the recorder holds the
    interesting tail instead of a random head. Retained traces are
    additionally handed to the optional ``exporter``."""

    def __init__(self, enabled: bool = False, sample_rate: float = 1.0,
                 max_traces: int = 256, node: str = "",
                 slow_ms: float = 0.0,
                 exporter: Optional["TraceExporter"] = None):
        self.enabled = bool(enabled)
        self.sample_rate = float(sample_rate)
        self.node = node
        self.slow_ms = float(slow_ms)
        self.exporter = exporter
        self._lock = threading.Lock()
        self._max = max(1, int(max_traces))
        # trace_id -> Trace; insertion-ordered ring (oldest evicted)
        self._finished: "OrderedDict[str, Trace]" = OrderedDict()
        self.started = 0
        self.sampled_out = 0
        self.tail_dropped = 0
        # retention-reason counters (snapshot + /metrics)
        self.retained: Dict[str, int] = {
            "sampled": 0, "error": 0, "shed": 0, "slow": 0, "forced": 0}

    def start(self, ctx: Optional[Tuple[str, Optional[str]]] = None,
              force: bool = False) -> Optional[Trace]:
        """A Trace for this request, or None (untraced). ``ctx`` is a
        propagated (trace_id, parent_span_id) from the caller — always
        honored. Fresh requests always get a pending trace when tracing
        is enabled; the ``sample_rate`` coin is flipped HERE but only
        consulted at finish (tail sampling — see class docstring).
        ``force`` (the ``&explain=trace`` opt-in) bypasses both the
        enable flag and the sampler for one request."""
        if ctx is not None:
            self.started += 1
            return Trace(ctx[0], node=self.node, root_parent=ctx[1])
        if not force:
            if not self.enabled:
                return None
            if self.sample_rate < 1.0 \
                    and random.random() >= self.sample_rate:
                # coin says drop — but keep recording: an error/shed/
                # slow outcome at finish overrides the coin
                self.sampled_out += 1
                self.started += 1
                return Trace(node=self.node, sampled=False)
        self.started += 1
        return Trace(node=self.node)

    def finish_request(self, trace: Optional[Trace], *,
                       error: bool = False, shed: bool = False,
                       duration_ms: Optional[float] = None,
                       force: bool = False) -> bool:
        """The tail-retention decision for an entry-node request trace:
        record it iff the outcome is interesting (error / QoS shed /
        above ``slow_ms``) or the start-time coin already kept it (or
        ``force`` — the explain path). Returns True when retained, so
        the caller can link the trace id (slowlog, exemplars) only to
        traces that actually resolve in ``/debug/traces``."""
        if trace is None:
            return False
        slow = (self.slow_ms > 0.0 and duration_ms is not None
                and duration_ms >= self.slow_ms)
        if error:
            reason = "error"
        elif shed:
            reason = "shed"
        elif slow:
            reason = "slow"
        elif force:
            reason = "forced"
        elif trace.sampled:
            reason = "sampled"
        else:
            with self._lock:
                self.tail_dropped += 1
            return False
        trace.retain_reason = reason
        with self._lock:
            self.retained[reason] = self.retained.get(reason, 0) + 1
        self.finish(trace)
        return True

    def finish(self, trace: Optional[Trace]) -> None:
        """Record a completed ENTRY-NODE trace in the ring buffer (peer
        hops ship their spans back instead of recording locally).
        Unconditional — callers wanting tail retention go through
        :meth:`finish_request`."""
        if trace is None:
            return
        with self._lock:
            self._finished[trace.trace_id] = trace
            self._finished.move_to_end(trace.trace_id)
            while len(self._finished) > self._max:
                self._finished.popitem(last=False)
        exp = self.exporter
        if exp is not None:
            exp.enqueue(trace)

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            return self._finished.get(trace_id)

    def recent(self, limit: int = 50) -> List[Trace]:
        with self._lock:
            out = list(self._finished.values())
        return out[-max(1, int(limit)):][::-1]

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            stored = len(self._finished)
            retained = dict(self.retained)
            tail_dropped = self.tail_dropped
        return {"enabled": int(self.enabled), "started": self.started,
                "sampled_out": self.sampled_out, "stored": stored,
                "tail_dropped": tail_dropped, "retained": retained}


# -- trace export ------------------------------------------------------------

def _otlp_attr(key: str, value) -> Dict:
    """One OTLP KeyValue. Everything non-numeric ships as a string —
    the sink side treats tags as opaque annotations anyway."""
    if isinstance(value, bool):
        return {"key": key, "value": {"boolValue": value}}
    if isinstance(value, int):
        return {"key": key, "value": {"intValue": str(value)}}
    if isinstance(value, float):
        return {"key": key, "value": {"doubleValue": value}}
    return {"key": key, "value": {"stringValue": str(value)}}


def _otlp_span(trace: Trace, d: Dict) -> Dict:
    """One serialized span (``Span.to_json`` form) as an OTLP/JSON
    span. Our ids are 64-bit hex: the 128-bit OTLP traceId is
    zero-padded, spanId ships as-is."""
    start_ns = int(d.get("start_us", 0)) * 1000
    dur_us = int(d.get("dur_us", -1))
    out = {
        "traceId": str(trace.trace_id).zfill(32),
        "spanId": str(d.get("span_id", "")).zfill(16),
        "name": str(d.get("name", "?")),
        "kind": 1,      # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(start_ns + max(0, dur_us) * 1000),
    }
    parent = d.get("parent_id")
    if parent:
        out["parentSpanId"] = str(parent).zfill(16)
    attrs = [_otlp_attr(k, v)
             for k, v in sorted((d.get("tags") or {}).items())]
    if attrs:
        out["attributes"] = attrs
    if d.get("error"):
        out["status"] = {"code": 2, "message": str(d["error"])}
    return out


def otlp_payload(traces: List[Trace], service: str = "filodb-tpu"
                 ) -> Dict:
    """An OTLP/JSON ``ExportTraceServiceRequest``-shaped body for a
    batch of finished traces (one resourceSpans entry per node)."""
    by_node: "Dict[str, List[Trace]]" = {}
    for tr in traces:
        by_node.setdefault(tr.node or "", []).append(tr)
    resource_spans = []
    for node in sorted(by_node):
        spans = []
        for tr in by_node[node]:
            for d in tr.spans_json():
                spans.append(_otlp_span(tr, d))
        res_attrs = [_otlp_attr("service.name", service)]
        if node:
            res_attrs.append(_otlp_attr("filodb.node", node))
        resource_spans.append({
            "resource": {"attributes": res_attrs},
            "scopeSpans": [{"scope": {"name": "filodb_tpu.obs.trace"},
                            "spans": spans}],
        })
    return {"resourceSpans": resource_spans}


def _http_post_json(url: str, body: bytes, timeout_s: float) -> int:
    """Default transport: POST the OTLP/JSON body; any transport-layer
    failure (or a 5xx from the sink) raises TransportError so
    ``resilient_call`` retries and the breaker counts it."""
    from filodb_tpu.parallel.resilience import TransportError
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return int(resp.status)
    except urllib.error.HTTPError as e:
        if e.code >= 500:
            raise TransportError(f"trace sink {url}: HTTP {e.code}")
        return int(e.code)      # 4xx: the sink answered; don't retry
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise TransportError(f"trace sink {url}: {e}")


@guarded_by("_lock", "_queue", "enqueued", "dropped", "batches",
            "spans_exported", "failures")
class TraceExporter:
    """Bounded background OTLP/JSON trace exporter (a declared thread
    root).

    Retained traces are enqueued by :meth:`Tracer.finish` (drop-oldest
    past ``queue_max`` — export lag must never block or balloon the
    serving path) and a daemon thread flushes batches to the configured
    sink through :func:`resilient_call`, so the sink gets the full
    breaker + backoff + deadline stack and a dead sink costs one
    breaker probe per reset period instead of a hung serving node."""

    def __init__(self, url: str, *, batch_max: int = 64,
                 interval_s: float = 2.0, queue_max: int = 1024,
                 timeout_s: float = 5.0, service: str = "filodb-tpu",
                 transport: Optional[
                     Callable[[str, bytes, float], int]] = None,
                 breakers=None, retry=None):
        self.url = str(url)
        self.batch_max = max(1, int(batch_max))
        self.interval_s = max(0.05, float(interval_s))
        self.queue_max = max(1, int(queue_max))
        self.timeout_s = float(timeout_s)
        self.service = service
        self._transport = transport or _http_post_json
        self._breakers = breakers
        self._retry = retry
        self._lock = threading.Lock()
        self._queue: "deque[Trace]" = deque()
        self.enqueued = 0
        self.dropped = 0
        self.batches = 0
        self.spans_exported = 0
        self.failures = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counter families: the exporter only exists when an export URL
        # is configured, so registering here never perturbs a default
        # /metrics exposition
        from filodb_tpu.obs import metrics as obs_metrics
        reg = obs_metrics.GLOBAL_REGISTRY
        self._m_batches = reg.counter(
            "filodb_trace_export_batches_total",
            "Trace batches successfully POSTed to the export sink")
        self._m_spans = reg.counter(
            "filodb_trace_export_spans_total",
            "Spans shipped to the trace export sink")
        self._m_dropped = reg.counter(
            "filodb_trace_export_dropped_total",
            "Retained traces dropped before export (queue saturation)")
        self._m_failures = reg.counter(
            "filodb_trace_export_failures_total",
            "Export batches abandoned after breaker/retry gave up")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "TraceExporter":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="trace-exporter")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- producer side -----------------------------------------------------
    def enqueue(self, trace: Trace) -> None:
        """Hand a retained trace to the exporter; never blocks. Oldest
        queued traces are evicted (and counted) past ``queue_max``."""
        with self._lock:
            while len(self._queue) >= self.queue_max:
                self._queue.popleft()
                self.dropped += 1
                self._m_dropped.inc()
            self._queue.append(trace)
            self.enqueued += 1
            full = len(self._queue) >= self.batch_max
        if full:
            self._wake.set()

    # -- exporter loop -----------------------------------------------------
    @thread_root("trace-exporter")
    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval_s)
            self._wake.clear()
            try:
                self.flush()
            except Exception:   # noqa: BLE001 — export must not die
                pass
        try:
            self.flush()        # final drain on shutdown
        except Exception:       # noqa: BLE001
            pass

    def flush(self) -> int:
        """Drain the queue in ``batch_max`` bites; returns spans
        shipped. A batch that exhausts retries (or meets an open
        breaker) is dropped and counted — export is best-effort by
        contract."""
        from filodb_tpu.parallel.resilience import (QueryError,
                                                    resilient_call)
        shipped = 0
        while True:
            with self._lock:
                if not self._queue:
                    return shipped
                batch = [self._queue.popleft()
                         for _ in range(min(self.batch_max,
                                            len(self._queue)))]
            body = json.dumps(otlp_payload(batch, self.service),
                              separators=(",", ":")).encode()
            nspans = sum(len(tr.spans) for tr in batch)
            try:
                resilient_call(
                    lambda t: self._transport(self.url, body, t),
                    key=f"trace-export:{self.url}",
                    node_id="trace-export",
                    timeout_s=self.timeout_s,
                    retry=self._retry, breakers=self._breakers)
            except QueryError:
                with self._lock:
                    self.failures += 1
                self._m_failures.inc()
                continue
            with self._lock:
                self.batches += 1
                self.spans_exported += nspans
            self._m_batches.inc()
            self._m_spans.inc(nspans)
            shipped += nspans

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"url": self.url, "queued": len(self._queue),
                    "enqueued": self.enqueued, "dropped": self.dropped,
                    "batches": self.batches,
                    "spans_exported": self.spans_exported,
                    "failures": self.failures, "running": self.running}
