"""Lightweight distributed tracing for the serving pipeline.

Dapper-style propagated trace context: an entry node starts a trace
(sampled), every stage opens spans through the :func:`span` context
manager, and remote hops forward ``trace_id`` + the parent span id on
the wire (HTTP: the ``X-Filo-Trace`` header; gRPC: dedicated fields in
RawRequest/ExecRequest). The PEER records its spans locally and ships
them back in the response envelope, so the entry node's recorder holds
one stitched trace covering every hop — the standard tool for
attributing tail latency in a fan-out system.

Design constraints:

  * ~zero cost when no trace is active: ``span()`` reads one
    thread-local attribute and returns a shared no-op context manager.
    No allocation, no clock read, no string formatting happens on the
    untraced path — disabled-tracing responses stay byte-identical and
    the bench overhead stays within noise.
  * spans may be recorded from multiple threads (HTTP workers, the
    batcher's device-executor thread): the active trace is carried in a
    thread-local and can be captured/reinstalled across thread hops
    (:func:`capture` / :func:`use` — the micro-batcher does this for
    closures it runs on the executor thread).
  * bounded memory: a trace stops recording past ``MAX_SPANS`` (a
    runaway fan-out can't balloon the ring buffer), and the
    :class:`Tracer`'s recorder keeps the last N finished traces.
"""

from __future__ import annotations

import itertools
import json
import random
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

# spans per trace cap: a 256-shard fan-out with retries stays well under
# this; anything bigger is a runaway and gets truncated (tagged).
MAX_SPANS = 512

_ids = itertools.count(1)
_state = threading.local()


def _new_id() -> str:
    # 64-bit random hex; cheap, collision-safe at ring-buffer scale
    return f"{random.getrandbits(64):016x}"


class Span:
    """One timed operation inside a trace. Created via :func:`span`;
    mutate tags through ``tag()`` while open."""

    __slots__ = ("name", "span_id", "parent_id", "start_ns", "dur_ns",
                 "tags", "error")

    def __init__(self, name: str, span_id: str, parent_id: Optional[str],
                 start_ns: int):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.dur_ns = -1            # -1 = still open
        self.tags: Dict[str, object] = {}
        self.error: Optional[str] = None

    def tag(self, **tags) -> "Span":
        self.tags.update(tags)
        return self

    def to_json(self) -> Dict:
        d = {"name": self.name, "span_id": self.span_id,
             "parent_id": self.parent_id,
             "start_us": self.start_ns // 1000,
             "dur_us": self.dur_ns // 1000 if self.dur_ns >= 0 else -1}
        if self.tags:
            d["tags"] = dict(self.tags)
        if self.error:
            d["error"] = self.error
        return d

    @classmethod
    def from_json(cls, d: Dict) -> "Span":
        s = cls(d.get("name", "?"), d.get("span_id", "?"),
                d.get("parent_id"), int(d.get("start_us", 0)) * 1000)
        dur = int(d.get("dur_us", -1))
        s.dur_ns = dur * 1000 if dur >= 0 else -1
        s.tags = dict(d.get("tags") or {})
        s.error = d.get("error")
        return s


class Trace:
    """One trace being recorded on THIS node (entry node or a peer
    serving a propagated context). Span appends are lock-protected —
    HTTP workers and the device executor both record."""

    __slots__ = ("trace_id", "node", "spans", "truncated", "_lock",
                 "root_parent")

    def __init__(self, trace_id: Optional[str] = None,
                 node: str = "", root_parent: Optional[str] = None):
        self.trace_id = trace_id or _new_id()
        self.node = node
        # parent span id carried in from the caller (peer hop); local
        # root spans attach under it so the entry node stitches cleanly
        self.root_parent = root_parent
        self.spans: List[Span] = []
        self.truncated = False
        self._lock = threading.Lock()

    def add(self, sp: Span) -> None:
        with self._lock:
            if len(self.spans) >= MAX_SPANS:
                self.truncated = True
                return
            self.spans.append(sp)

    def absorb(self, spans_json: List[Dict]) -> None:
        """Fold a peer's serialized spans into this trace (the stitch).
        The peer already parented them under the span id we forwarded."""
        with self._lock:
            for d in spans_json:
                if len(self.spans) >= MAX_SPANS:
                    self.truncated = True
                    return
                self.spans.append(Span.from_json(d))

    def spans_json(self) -> List[Dict]:
        with self._lock:
            return [s.to_json() for s in self.spans]

    def to_json(self) -> Dict:
        spans = self.spans_json()
        dur = 0
        for s in spans:
            if s["parent_id"] is None or s["parent_id"] == \
                    self.root_parent:
                dur = max(dur, s["dur_us"])
        return {"trace_id": self.trace_id, "node": self.node,
                "num_spans": len(spans), "duration_us": dur,
                "truncated": self.truncated, "spans": spans}


class _NoopSpan:
    """Shared do-nothing context manager: the untraced fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def tag(self, **tags):
        return self


_NOOP = _NoopSpan()


class _LiveSpan:
    """Context manager recording one span into the active trace."""

    __slots__ = ("_trace", "_span", "_prev")

    def __init__(self, trace: Trace, name: str, parent_id: Optional[str],
                 tags: Dict):
        self._trace = trace
        sp = Span(name, _new_id(), parent_id, time.time_ns())
        if tags:
            sp.tags.update(tags)
        self._span = sp

    def __enter__(self) -> Span:
        self._prev = getattr(_state, "parent", None)
        _state.parent = self._span.span_id
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        sp = self._span
        sp.dur_ns = time.time_ns() - sp.start_ns
        if exc is not None and sp.error is None:
            sp.error = f"{type(exc).__name__}: {exc}"
        _state.parent = self._prev
        self._trace.add(sp)
        return False


# -- the thread-local active-trace API ---------------------------------------

def span(name: str, **tags):
    """Open a span under the thread's active trace; no-op (shared
    object, no allocation) when no trace is active. Usable from any
    layer without threading a tracer object through."""
    tr = getattr(_state, "trace", None)
    if tr is None:
        return _NOOP
    return _LiveSpan(tr, name, getattr(_state, "parent", None), tags)


def event(name: str, **tags) -> None:
    """Zero-duration span (a point annotation, e.g. a breaker
    rejection); no-op when no trace is active."""
    tr = getattr(_state, "trace", None)
    if tr is None:
        return
    sp = Span(name, _new_id(), getattr(_state, "parent", None),
              time.time_ns())
    sp.dur_ns = 0
    if tags:
        sp.tags.update(tags)
    tr.add(sp)


def trace_active() -> bool:
    return getattr(_state, "trace", None) is not None


def current_trace() -> Optional[Trace]:
    return getattr(_state, "trace", None)


def capture() -> Optional[Tuple[Trace, Optional[str]]]:
    """Snapshot (trace, parent span id) for reinstalling on another
    thread (the batcher's executor hop); None when untraced."""
    tr = getattr(_state, "trace", None)
    if tr is None:
        return None
    return tr, getattr(_state, "parent", None)


class use:
    """Reinstall a captured trace context on the current thread:
    ``with trace.use(ctx): ...``. ``ctx=None`` is a no-op (so callers
    can pass ``capture()``'s result through unconditionally)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[Tuple[Trace, Optional[str]]]):
        self._ctx = ctx

    def __enter__(self):
        if self._ctx is None:
            return self
        self._prev = (getattr(_state, "trace", None),
                      getattr(_state, "parent", None))
        _state.trace = self._ctx[0]
        _state.parent = self._ctx[1]
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            _state.trace, _state.parent = self._prev
        return False


class activate:
    """Install ``trace`` as the thread's active trace for the scope
    (the per-request entry point; :class:`Tracer` wraps this)."""

    __slots__ = ("_trace", "_prev")

    def __init__(self, trace: Optional[Trace]):
        self._trace = trace

    def __enter__(self) -> Optional[Trace]:
        self._prev = (getattr(_state, "trace", None),
                      getattr(_state, "parent", None))
        _state.trace = self._trace
        _state.parent = self._trace.root_parent \
            if self._trace is not None else None
        return self._trace

    def __exit__(self, *exc):
        _state.trace, _state.parent = self._prev
        return False


# -- wire propagation --------------------------------------------------------

HEADER = "X-Filo-Trace"


def inject_header() -> Optional[str]:
    """``trace_id-parent_span_id-1`` for the active trace (the b3-style
    single header), or None when untraced."""
    tr = getattr(_state, "trace", None)
    if tr is None:
        return None
    parent = getattr(_state, "parent", None) or ""
    return f"{tr.trace_id}-{parent}-1"


def parse_context(value: Optional[str]
                  ) -> Optional[Tuple[str, Optional[str]]]:
    """Parse a propagated context into (trace_id, parent_span_id);
    None on absent/malformed input (malformed context must never fail
    a query)."""
    if not value:
        return None
    parts = str(value).split("-")
    if len(parts) < 1 or not parts[0]:
        return None
    parent = parts[1] if len(parts) > 1 and parts[1] else None
    return parts[0], parent


def spans_wire(trace: Optional[Trace]) -> bytes:
    """Serialized spans for a response envelope (gRPC field / HTTP
    JSON); empty when untraced."""
    if trace is None:
        return b""
    return json.dumps(trace.spans_json(),
                      separators=(",", ":")).encode()


def absorb_spans(spans) -> None:
    """Fold a peer's already-parsed span list (JSON-decoded dicts) into
    the active trace; no-op when untraced or empty."""
    tr = getattr(_state, "trace", None)
    if tr is None or not spans:
        return
    try:
        tr.absorb([d for d in spans if isinstance(d, dict)])
    except (TypeError, ValueError):
        pass


def absorb_wire(buf) -> None:
    """Fold a peer's serialized span list into the active trace;
    tolerant of garbage (a peer's malformed payload must never fail
    the query)."""
    tr = getattr(_state, "trace", None)
    if tr is None or not buf:
        return
    try:
        if isinstance(buf, (bytes, bytearray)):
            buf = buf.decode()
        spans = json.loads(buf)
        if isinstance(spans, list):
            tr.absorb([d for d in spans if isinstance(d, dict)])
    except (ValueError, UnicodeDecodeError):
        pass


# -- the per-server tracer ---------------------------------------------------

class Tracer:
    """Sampling policy + bounded recorder of finished traces.

    One per server process (the HTTP server owns it). ``enabled=False``
    (the default) never starts traces — ``span()`` stays on the no-op
    path everywhere. A propagated context from a caller is always
    honored (the entry node made the sampling decision)."""

    def __init__(self, enabled: bool = False, sample_rate: float = 1.0,
                 max_traces: int = 256, node: str = ""):
        self.enabled = bool(enabled)
        self.sample_rate = float(sample_rate)
        self.node = node
        self._lock = threading.Lock()
        self._max = max(1, int(max_traces))
        # trace_id -> Trace; insertion-ordered ring (oldest evicted)
        self._finished: "OrderedDict[str, Trace]" = OrderedDict()
        self.started = 0
        self.sampled_out = 0

    def start(self, ctx: Optional[Tuple[str, Optional[str]]] = None,
              force: bool = False) -> Optional[Trace]:
        """A Trace for this request, or None (untraced). ``ctx`` is a
        propagated (trace_id, parent_span_id) from the caller — always
        honored. Fresh requests sample at ``sample_rate``; ``force``
        (the ``&explain=trace`` opt-in) bypasses both the enable flag
        and the sampler for one request."""
        if ctx is not None:
            self.started += 1
            return Trace(ctx[0], node=self.node, root_parent=ctx[1])
        if not force:
            if not self.enabled:
                return None
            if self.sample_rate < 1.0 \
                    and random.random() >= self.sample_rate:
                self.sampled_out += 1
                return None
        self.started += 1
        return Trace(node=self.node)

    def finish(self, trace: Optional[Trace]) -> None:
        """Record a completed ENTRY-NODE trace in the ring buffer (peer
        hops ship their spans back instead of recording locally)."""
        if trace is None:
            return
        with self._lock:
            self._finished[trace.trace_id] = trace
            self._finished.move_to_end(trace.trace_id)
            while len(self._finished) > self._max:
                self._finished.popitem(last=False)

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            return self._finished.get(trace_id)

    def recent(self, limit: int = 50) -> List[Trace]:
        with self._lock:
            out = list(self._finished.values())
        return out[-max(1, int(limit)):][::-1]

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            stored = len(self._finished)
        return {"enabled": int(self.enabled), "started": self.started,
                "sampled_out": self.sampled_out, "stored": stored}
