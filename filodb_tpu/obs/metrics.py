"""Fixed-bucket Prometheus histograms + the exposition builder.

The Kamon-histogram surface the reference gets for free: stage
latencies (query total, batcher queue wait, device execute, flush,
ingest append, fsync) are observed into fixed cumulative buckets and
exposed as well-formed ``_bucket``/``_sum``/``_count`` families with
``# HELP``/``# TYPE`` lines, so p50/p95/p99 come out of any Prometheus
scrape instead of being recomputed client-side in bench scripts.

Also home of :class:`ExpositionBuilder`, the family-grouped text-format
writer the ``/metrics`` endpoint uses for EVERY family (gauges and
counters included): one ``# HELP``/``# TYPE`` block per family,
consistent label-value escaping, and a guaranteed absence of duplicate
series.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from filodb_tpu.lint.locks import guarded_by, single_writer

# latency buckets in seconds: sub-ms serving path up to multi-second
# degraded tails (the Prometheus http duration defaults, extended down)
LATENCY_BUCKETS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
# fsync/append: flash-to-spinning-rust-to-stalled-container spread
FSYNC_BUCKETS_S = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0)
# batch occupancy: powers of two up to the batcher's max_batch scale
OCCUPANCY_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0)
# step counts (results-cache cached-steps-served): dashboards range from
# a handful of steps to multi-day grids
STEPS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0, 2048.0)


def _fmt_float(v: float) -> str:
    """Prometheus sample-value text: integral floats print bare."""
    if v == math.inf:
        return "+Inf"
    if v == int(v):
        return str(int(v))
    return repr(float(v))


# an exemplar older than this is replaced by ANY fresh observation —
# "the slowest RECENT fill", not the all-time max
EXEMPLAR_MAX_AGE_S = 60.0


@guarded_by("_lock", "_counts", "_sum", "_count", "_exemplars")
class Histogram:
    """One cumulative fixed-bucket histogram (thread-safe observe).

    ``observe(value, trace_id=...)`` optionally attaches an OpenMetrics
    exemplar to the bucket the value lands in: the (trace_id, value,
    unix ts) triple of the slowest recent fill, so a latency bucket
    links straight to the retained trace that filled it. Exemplars cost
    nothing until the first trace_id-bearing observe and never surface
    in the exposition unless explicitly requested
    (``/metrics?exemplars=1``)."""

    def __init__(self, name: str, help: str,
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        self.name = name
        self.help = help
        self.buckets: Tuple[float, ...] = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"buckets must be sorted/unique: {buckets}")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)   # +Inf tail
        self._sum = 0.0
        self._count = 0
        # per-bucket (trace_id, value, unix_ts); allocated lazily on
        # the first exemplar-bearing observe
        self._exemplars: Optional[List[Optional[Tuple[str, float,
                                                      float]]]] = None

    def observe(self, value: float,
                trace_id: Optional[str] = None) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if trace_id is None:
                return
            if self._exemplars is None:
                self._exemplars = [None] * (len(self.buckets) + 1)
            cur = self._exemplars[i]
            now = time.time()
            if cur is None or value >= cur[1] \
                    or now - cur[2] > EXEMPLAR_MAX_AGE_S:
                self._exemplars[i] = (str(trace_id), float(value), now)

    def exemplars(self) -> List[Optional[Tuple[str, float, float]]]:
        """Per-bucket exemplar snapshot (index-aligned with
        ``snapshot()['counts']``); all-None when never attached."""
        with self._lock:
            if self._exemplars is None:
                return [None] * (len(self.buckets) + 1)
            return list(self._exemplars)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            return {"buckets": self.buckets, "counts": counts,
                    "sum": self._sum, "count": self._count}

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile (what a PromQL
        histogram_quantile would compute); NaN when empty."""
        snap = self.snapshot()
        total = snap["count"]
        if total == 0:
            return math.nan
        rank = q * total
        cum = 0
        lo = 0.0
        for i, c in enumerate(snap["counts"]):
            prev = cum
            cum += c
            if cum >= rank:
                hi = (self.buckets[i] if i < len(self.buckets)
                      else self.buckets[-1])
                if i >= len(self.buckets):
                    return float(self.buckets[-1])
                frac = (rank - prev) / c if c else 0.0
                return lo + (hi - lo) * frac
            lo = self.buckets[i] if i < len(self.buckets) else lo
        return float(self.buckets[-1])


@guarded_by("_lock", "_series")
class CounterFamily:
    """Labeled monotone counter family living in the registry (the
    counter analogue of :class:`Histogram`): ``inc()`` from any thread,
    ``series()`` snapshots for the exposition walk."""

    def __init__(self, name: str, help: str):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        # sorted (key, value) label tuple -> running total
        self._series: Dict[Tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def series(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            items = list(self._series.items())
        return [(dict(k), v) for k, v in items]


@guarded_by("_lock", "_series")
class GaugeFamily:
    """Labeled gauge family living in the registry (``set()`` replaces
    the labeled series' value)."""

    def __init__(self, name: str, help: str):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[Tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            self._series[key] = float(value)

    def series(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            items = list(self._series.items())
        return [(dict(k), v) for k, v in items]


class MetricsRegistry:
    """Name-keyed metric-family registry. One process-global instance
    (:data:`GLOBAL_REGISTRY`) serves the deep layers (batcher, ingest
    stream, device dispatch) that have no natural path to the server
    object; the /metrics endpoint exposes it.

    Besides histograms it holds labeled counter/gauge families and
    *collectors* — callables invoked at exposition-build time that
    sample external state (the process collector reads /proc; the
    device profiler walks its executable table). The registry is the
    walkable surface the self-monitoring pipeline snapshots in-process
    (obs/selfmon.py), so anything registered here is automatically a
    PromQL-queryable series once ``--self-monitor`` is on."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: Dict[str, Histogram] = {}
        self._counters: Dict[str, CounterFamily] = {}
        self._gauges: Dict[str, GaugeFamily] = {}
        self._collectors: List = []

    def histogram(self, name: str, help: str,
                  buckets: Sequence[float] = LATENCY_BUCKETS_S
                  ) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = Histogram(name, help, buckets)
                self._hists[name] = h
            return h

    def get(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._hists.get(name)

    def histograms(self) -> List[Histogram]:
        with self._lock:
            return list(self._hists.values())

    def counter(self, name: str, help: str) -> CounterFamily:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = CounterFamily(name, help)
                self._counters[name] = c
            return c

    def gauge(self, name: str, help: str) -> GaugeFamily:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = GaugeFamily(name, help)
                self._gauges[name] = g
            return g

    def register_collector(self, fn) -> None:
        """Register ``fn(builder: ExpositionBuilder)`` to be called at
        every exposition build (idempotent by function identity)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def collect_into(self, builder: "ExpositionBuilder",
                     exemplars: bool = False) -> None:
        """Walk the whole registry into ``builder``: counter + gauge
        families, registered collectors, then the histograms (sorted by
        name, matching the /metrics layout). ``exemplars=True``
        (the content-negotiated ``/metrics?exemplars=1``) attaches each
        histogram bucket's OpenMetrics exemplar."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            collectors = list(self._collectors)
            hists = list(self._hists.values())
        for c in sorted(counters, key=lambda c: c.name):
            for labels, v in c.series():
                builder.sample(c.name, labels, _fmt_float(v),
                               mtype="counter", help=c.help)
        for g in sorted(gauges, key=lambda g: g.name):
            for labels, v in g.series():
                builder.sample(g.name, labels, _fmt_float(v),
                               mtype="gauge", help=g.help)
        for fn in collectors:
            try:
                fn(builder)
            except Exception:   # noqa: BLE001 — a collector must never
                pass            # fail the scrape
        for h in sorted(hists, key=lambda h: h.name):
            builder.histogram(h, exemplars=exemplars)

    def reset(self) -> None:
        """Test hook: drop all registered families. Collectors are
        WIRING, not state — they survive a reset (the device profiler
        and process collector register once per process)."""
        with self._lock:
            self._hists.clear()
            self._counters.clear()
            self._gauges.clear()


GLOBAL_REGISTRY = MetricsRegistry()


def observe(name: str, help: str, value: float,
            buckets: Sequence[float] = LATENCY_BUCKETS_S,
            trace_id: Optional[str] = None) -> None:
    """One-line observe into the global registry; ``trace_id`` attaches
    an exemplar (the metric→trace link) to the landing bucket."""
    GLOBAL_REGISTRY.histogram(name, help, buckets).observe(
        value, trace_id=trace_id)


class timed:
    """``with metrics.timed("filodb_x_seconds", "help"):`` — observes
    the elapsed wall seconds into the global registry on exit."""

    __slots__ = ("_name", "_help", "_buckets", "_t0")

    def __init__(self, name: str, help: str,
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        self._name = name
        self._help = help
        self._buckets = buckets

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        observe(self._name, self._help,
                time.perf_counter() - self._t0, self._buckets)
        return False


# -- exposition --------------------------------------------------------------

def escape_label(v: object) -> str:
    """Prometheus text-format label-value escaping: backslash, quote,
    newline (the one escaping rule, applied to EVERY label value)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def format_exemplar(ex: Optional[Tuple[str, float, float]]
                    ) -> Optional[str]:
    """OpenMetrics exemplar suffix text for a (trace_id, value, ts)
    triple — the part after ``# `` on a sample line::

        {trace_id="8ff60ae4"} 0.053 1700000000.123

    None passes through (no exemplar on this bucket)."""
    if ex is None:
        return None
    trace_id, value, ts = ex
    return (f'{{trace_id="{escape_label(trace_id)}"}} '
            f"{_fmt_float(value)} {round(float(ts), 3)}")


@single_writer("an ExpositionBuilder is constructed, filled, and "
               "rendered by ONE request/scrape thread; instances are "
               "never shared (each /metrics render builds its own)")
class ExpositionBuilder:
    """Family-grouped Prometheus text-format writer.

    Samples accumulate per family; ``render()`` emits one
    ``# HELP``/``# TYPE`` block per family followed by its samples,
    with duplicate series (same name + label set) dropped
    deterministically (first writer wins) so the exposition always
    parses."""

    def __init__(self):
        # family -> (type, help, [(name, labels_tuple, value_str,
        #                          exemplar_suffix_or_None)])
        self._families: "Dict[str, Tuple[str, str, List]]" = {}
        self._order: List[str] = []

    def declare(self, name: str, mtype: str, help: str) -> None:
        if name not in self._families:
            self._families[name] = (mtype, help, [])
            self._order.append(name)

    def sample(self, name: str, labels: Dict[str, object], value,
               mtype: str = "gauge", help: str = "",
               family: Optional[str] = None,
               exemplar: Optional[str] = None) -> None:
        """Add one sample. ``family`` overrides the HELP/TYPE grouping
        key for histogram children (``x_bucket`` groups under ``x``).
        ``exemplar`` is a pre-rendered OpenMetrics exemplar suffix (the
        text after ``# `` — e.g. ``{trace_id="ab12"} 0.053 1700.2``)
        appended verbatim at render time; it is never part of the
        series identity."""
        fam = family or name
        if fam not in self._families:
            self.declare(fam, mtype,
                         help or f"FiloDB metric {fam}")
        self._families[fam][2].append(
            (name, tuple(sorted((str(k), str(v))
                                for k, v in labels.items())), value,
             exemplar))

    def histogram(self, h: Histogram,
                  labels: Optional[Dict[str, object]] = None,
                  exemplars: bool = False) -> None:
        labels = labels or {}
        snap = h.snapshot()
        ex = h.exemplars() if exemplars \
            else [None] * (len(snap["buckets"]) + 1)
        self.declare(h.name, "histogram", h.help)
        cum = 0
        for i, (b, c) in enumerate(zip(snap["buckets"],
                                       snap["counts"])):
            cum += c
            self.sample(h.name + "_bucket",
                        {**labels, "le": _fmt_float(b)}, cum,
                        family=h.name,
                        exemplar=format_exemplar(ex[i]))
        cum += snap["counts"][-1]
        self.sample(h.name + "_bucket", {**labels, "le": "+Inf"}, cum,
                    family=h.name, exemplar=format_exemplar(ex[-1]))
        self.sample(h.name + "_sum", labels, snap["sum"],
                    family=h.name)
        self.sample(h.name + "_count", labels, snap["count"],
                    family=h.name)

    def families(self):
        """Structured walk of the accumulated exposition — the in-process
        alternative to rendering text and parsing it back (what the
        self-monitoring pipeline does every tick). Yields
        ``(family, mtype, help, samples)`` where each sample is
        ``(sample_name, labels_tuple, value)``; ``labels_tuple`` is the
        sorted ``((key, value), ...)`` form and duplicate series are
        dropped exactly like :meth:`render` drops them (first writer
        wins), so the walk and the text agree sample-for-sample."""
        seen: set = set()
        for fam in self._order:
            mtype, help, samples = self._families[fam]
            if not samples:
                continue
            out = []
            for name, labels, value, _ex in samples:
                key = (name, labels)
                if key in seen:
                    continue
                seen.add(key)
                out.append((name, labels, value))
            yield fam, mtype, help, out

    def render(self) -> str:
        lines: List[str] = []
        seen: set = set()
        for fam in self._order:
            mtype, help, samples = self._families[fam]
            if not samples:
                continue
            lines.append(f"# HELP {fam} {escape_help(help)}")
            lines.append(f"# TYPE {fam} {mtype}")
            for name, labels, value, ex in samples:
                key = (name, labels)
                if key in seen:
                    continue        # no duplicate series, ever
                seen.add(key)
                if labels:
                    lbl = ",".join(f'{k}="{escape_label(v)}"'
                                   for k, v in labels)
                    line = f"{name}{{{lbl}}} {value}"
                else:
                    line = f"{name} {value}"
                if ex:
                    line += f" # {ex}"
                lines.append(line)
        return "\n".join(lines) + "\n"


# -- multi-worker aggregation ------------------------------------------------

_LABELS_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"') \
        .replace("\\\\", "\\")


def parse_exposition(text: str,
                     help_sink: Optional[Dict[str, str]] = None,
                     exemplar_sink: Optional[Dict[Tuple, str]] = None
                     ) -> "List[Tuple[str, str, str, Dict[str, str], str]]":
    """Parse Prometheus text format into
    ``(family, mtype, sample_name, labels, value)`` rows (family = the
    HELP/TYPE grouping name, so ``x_bucket`` rows carry family ``x``).
    ``help_sink`` (optional) collects each family's HELP text.
    ``exemplar_sink`` (optional) collects OpenMetrics exemplar suffixes
    keyed by ``(sample_name, sorted labels tuple)``; without a sink
    exemplars are stripped, so every consumer (validators, selfmon,
    aggregation) sees plain samples. Tolerant of unknown lines
    (skipped), so a worker running newer code than its supervisor still
    aggregates."""
    out = []
    mtypes: Dict[str, str] = {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP "):
            if help_sink is not None:
                parts = ln.split(" ", 3)
                if len(parts) == 4:
                    help_sink.setdefault(parts[2], parts[3])
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split()
            if len(parts) >= 4:
                mtypes[parts[2]] = parts[3]
            continue
        if ln.startswith("#"):
            continue
        # OpenMetrics exemplar suffix: `series value # {labels} v ts`.
        # Right-most ``" # {"`` anchors the split, so label values
        # containing a bare " # " stay intact (the suffix itself never
        # contains the anchor).
        exemplar = None
        if " # {" in ln:
            ln, _, rest = ln.rpartition(" # {")
            exemplar = "{" + rest
        name_part, _, value = ln.rpartition(" ")
        if not name_part:
            continue
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            labels = {k: _unescape_label(v)
                      for k, v in _LABELS_RE.findall(
                          rest.rsplit("}", 1)[0])}
        else:
            name, labels = name_part, {}
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and mtypes.get(base) == "histogram":
                fam = base
                break
        if exemplar is not None and exemplar_sink is not None:
            exemplar_sink[(name, tuple(sorted(labels.items())))] = \
                exemplar
        out.append((fam, mtypes.get(fam, ""), name, labels, value))
    return out


def merge_expositions(by_worker: "Dict[str, str]",
                      help_table: Optional[Dict[str, str]] = None) -> str:
    """The supervisor's ``/metrics`` aggregation: each worker's
    exposition re-emitted with a ``worker`` label injected into every
    sample, one HELP/TYPE block per family across all workers. Workers
    stay individually scrapeable on their private ports; this is the
    one-target view (per-worker batcher occupancy, qps, cache hit
    ratios side by side)."""
    b = ExpositionBuilder()
    helps: Dict[str, str] = dict(help_table or {})
    exemplars: Dict[str, Dict[Tuple, str]] = {w: {} for w in by_worker}
    parsed = {w: parse_exposition(by_worker[w], help_sink=helps,
                                  exemplar_sink=exemplars[w])
              for w in by_worker}
    for worker in sorted(parsed, key=str):
        for fam, mtype, name, labels, value in parsed[worker]:
            if not mtype:
                mtype = "counter" if fam.endswith("_total") else "gauge"
            # a sample that ALREADY carries a worker label keeps it:
            # self-monitoring stamps internal series with their origin
            # worker, and re-merging a merged exposition must be a
            # no-op (merge idempotence — supervisor-of-supervisor
            # chains and re-scraped aggregates stay stable)
            lbl = dict(labels)
            lbl.setdefault("worker", str(worker))
            # a worker's exemplar suffix rides its sample through the
            # merge unmangled (keyed on the PRE-injection identity, so
            # re-merging keyed on the already-labeled series also hits)
            ex = exemplars[worker].get(
                (name, tuple(sorted(labels.items()))))
            b.sample(name, lbl, value, mtype=mtype,
                     help=helps.get(fam, f"FiloDB metric {fam}"),
                     family=fam, exemplar=ex)
    return b.render()


def validate_histogram_families(text: str) -> List[str]:
    """Registry-wide histogram self-consistency validator over a full
    text exposition. For every family declared ``histogram`` (per label
    set, ``le`` excluded) it checks:

      * bucket counts are cumulative (non-decreasing in ``le`` order),
      * the ``+Inf`` bucket equals ``_count``,
      * ``_sum`` and ``_count`` are both emitted.

    Returns a list of human-readable violations (empty = clean). Run
    as a tier-1 test over the live exposition AND by the supervisor
    merge tests — a histogram that fails any of these breaks
    ``histogram_quantile`` silently downstream."""
    out: List[str] = []
    # (family, labels-minus-le) -> {"buckets": [(le, v)], "count": v,
    #                               "sum": present}
    groups: Dict[Tuple, Dict] = {}
    for fam, mtype, name, labels, value in parse_exposition(text):
        if mtype != "histogram":
            continue
        base_labels = tuple(sorted((k, v) for k, v in labels.items()
                                   if k != "le"))
        g = groups.setdefault((fam, base_labels),
                              {"buckets": [], "count": None,
                               "sum": False})
        try:
            v = float(str(value).replace("+Inf", "inf"))
        except ValueError:
            out.append(f"{fam}{dict(base_labels)}: unparseable value "
                       f"{value!r} on {name}")
            continue
        if name == fam + "_bucket":
            try:
                le = float(str(labels.get("le", "")).replace(
                    "+Inf", "inf"))
            except ValueError:
                out.append(f"{fam}{dict(base_labels)}: bad le "
                           f"{labels.get('le')!r}")
                continue
            g["buckets"].append((le, v))
        elif name == fam + "_count":
            g["count"] = v
        elif name == fam + "_sum":
            g["sum"] = True
    for (fam, base_labels), g in sorted(groups.items(), key=str):
        where = f"{fam}{dict(base_labels)}"
        buckets = sorted(g["buckets"])
        if not buckets:
            out.append(f"{where}: histogram family with no _bucket "
                       f"samples")
            continue
        vals = [v for _le, v in buckets]
        if vals != sorted(vals):
            out.append(f"{where}: bucket counts are not cumulative")
        if buckets[-1][0] != math.inf:
            out.append(f"{where}: no +Inf bucket")
        if g["count"] is None:
            out.append(f"{where}: _count not emitted")
        elif buckets[-1][0] == math.inf and buckets[-1][1] != g["count"]:
            out.append(f"{where}: +Inf bucket {buckets[-1][1]} != "
                       f"_count {g['count']}")
        if not g["sum"]:
            out.append(f"{where}: _sum not emitted")
    return out
