"""Fixed-bucket Prometheus histograms + the exposition builder.

The Kamon-histogram surface the reference gets for free: stage
latencies (query total, batcher queue wait, device execute, flush,
ingest append, fsync) are observed into fixed cumulative buckets and
exposed as well-formed ``_bucket``/``_sum``/``_count`` families with
``# HELP``/``# TYPE`` lines, so p50/p95/p99 come out of any Prometheus
scrape instead of being recomputed client-side in bench scripts.

Also home of :class:`ExpositionBuilder`, the family-grouped text-format
writer the ``/metrics`` endpoint uses for EVERY family (gauges and
counters included): one ``# HELP``/``# TYPE`` block per family,
consistent label-value escaping, and a guaranteed absence of duplicate
series.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from filodb_tpu.lint.locks import guarded_by, single_writer

# latency buckets in seconds: sub-ms serving path up to multi-second
# degraded tails (the Prometheus http duration defaults, extended down)
LATENCY_BUCKETS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
# fsync/append: flash-to-spinning-rust-to-stalled-container spread
FSYNC_BUCKETS_S = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0)
# batch occupancy: powers of two up to the batcher's max_batch scale
OCCUPANCY_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0)
# step counts (results-cache cached-steps-served): dashboards range from
# a handful of steps to multi-day grids
STEPS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0, 2048.0)


def _fmt_float(v: float) -> str:
    """Prometheus sample-value text: integral floats print bare."""
    if v == math.inf:
        return "+Inf"
    if v == int(v):
        return str(int(v))
    return repr(float(v))


@guarded_by("_lock", "_counts", "_sum", "_count")
class Histogram:
    """One cumulative fixed-bucket histogram (thread-safe observe)."""

    def __init__(self, name: str, help: str,
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        self.name = name
        self.help = help
        self.buckets: Tuple[float, ...] = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"buckets must be sorted/unique: {buckets}")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)   # +Inf tail
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            return {"buckets": self.buckets, "counts": counts,
                    "sum": self._sum, "count": self._count}

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile (what a PromQL
        histogram_quantile would compute); NaN when empty."""
        snap = self.snapshot()
        total = snap["count"]
        if total == 0:
            return math.nan
        rank = q * total
        cum = 0
        lo = 0.0
        for i, c in enumerate(snap["counts"]):
            prev = cum
            cum += c
            if cum >= rank:
                hi = (self.buckets[i] if i < len(self.buckets)
                      else self.buckets[-1])
                if i >= len(self.buckets):
                    return float(self.buckets[-1])
                frac = (rank - prev) / c if c else 0.0
                return lo + (hi - lo) * frac
            lo = self.buckets[i] if i < len(self.buckets) else lo
        return float(self.buckets[-1])


class MetricsRegistry:
    """Name-keyed histogram registry. One process-global instance
    (:data:`GLOBAL_REGISTRY`) serves the deep layers (batcher, ingest
    stream, device dispatch) that have no natural path to the server
    object; the /metrics endpoint exposes it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: Dict[str, Histogram] = {}

    def histogram(self, name: str, help: str,
                  buckets: Sequence[float] = LATENCY_BUCKETS_S
                  ) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = Histogram(name, help, buckets)
                self._hists[name] = h
            return h

    def get(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._hists.get(name)

    def histograms(self) -> List[Histogram]:
        with self._lock:
            return list(self._hists.values())

    def reset(self) -> None:
        """Test hook: drop all registered histograms."""
        with self._lock:
            self._hists.clear()


GLOBAL_REGISTRY = MetricsRegistry()


def observe(name: str, help: str, value: float,
            buckets: Sequence[float] = LATENCY_BUCKETS_S) -> None:
    """One-line observe into the global registry."""
    GLOBAL_REGISTRY.histogram(name, help, buckets).observe(value)


class timed:
    """``with metrics.timed("filodb_x_seconds", "help"):`` — observes
    the elapsed wall seconds into the global registry on exit."""

    __slots__ = ("_name", "_help", "_buckets", "_t0")

    def __init__(self, name: str, help: str,
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        self._name = name
        self._help = help
        self._buckets = buckets

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        observe(self._name, self._help,
                time.perf_counter() - self._t0, self._buckets)
        return False


# -- exposition --------------------------------------------------------------

def escape_label(v: object) -> str:
    """Prometheus text-format label-value escaping: backslash, quote,
    newline (the one escaping rule, applied to EVERY label value)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


@single_writer("an ExpositionBuilder is constructed, filled, and "
               "rendered by ONE request/scrape thread; instances are "
               "never shared (each /metrics render builds its own)")
class ExpositionBuilder:
    """Family-grouped Prometheus text-format writer.

    Samples accumulate per family; ``render()`` emits one
    ``# HELP``/``# TYPE`` block per family followed by its samples,
    with duplicate series (same name + label set) dropped
    deterministically (first writer wins) so the exposition always
    parses."""

    def __init__(self):
        # family -> (type, help, [(labels_tuple, value_str)])
        self._families: "Dict[str, Tuple[str, str, List]]" = {}
        self._order: List[str] = []

    def declare(self, name: str, mtype: str, help: str) -> None:
        if name not in self._families:
            self._families[name] = (mtype, help, [])
            self._order.append(name)

    def sample(self, name: str, labels: Dict[str, object], value,
               mtype: str = "gauge", help: str = "",
               family: Optional[str] = None) -> None:
        """Add one sample. ``family`` overrides the HELP/TYPE grouping
        key for histogram children (``x_bucket`` groups under ``x``)."""
        fam = family or name
        if fam not in self._families:
            self.declare(fam, mtype,
                         help or f"FiloDB metric {fam}")
        self._families[fam][2].append(
            (name, tuple(sorted((str(k), str(v))
                                for k, v in labels.items())), value))

    def histogram(self, h: Histogram,
                  labels: Optional[Dict[str, object]] = None) -> None:
        labels = labels or {}
        snap = h.snapshot()
        self.declare(h.name, "histogram", h.help)
        cum = 0
        for b, c in zip(snap["buckets"], snap["counts"]):
            cum += c
            self.sample(h.name + "_bucket",
                        {**labels, "le": _fmt_float(b)}, cum,
                        family=h.name)
        cum += snap["counts"][-1]
        self.sample(h.name + "_bucket", {**labels, "le": "+Inf"}, cum,
                    family=h.name)
        self.sample(h.name + "_sum", labels, snap["sum"],
                    family=h.name)
        self.sample(h.name + "_count", labels, snap["count"],
                    family=h.name)

    def render(self) -> str:
        lines: List[str] = []
        seen: set = set()
        for fam in self._order:
            mtype, help, samples = self._families[fam]
            if not samples:
                continue
            lines.append(f"# HELP {fam} {escape_help(help)}")
            lines.append(f"# TYPE {fam} {mtype}")
            for name, labels, value in samples:
                key = (name, labels)
                if key in seen:
                    continue        # no duplicate series, ever
                seen.add(key)
                if labels:
                    lbl = ",".join(f'{k}="{escape_label(v)}"'
                                   for k, v in labels)
                    lines.append(f"{name}{{{lbl}}} {value}")
                else:
                    lines.append(f"{name} {value}")
        return "\n".join(lines) + "\n"


# -- multi-worker aggregation ------------------------------------------------

_LABELS_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"') \
        .replace("\\\\", "\\")


def parse_exposition(text: str,
                     help_sink: Optional[Dict[str, str]] = None
                     ) -> "List[Tuple[str, str, str, Dict[str, str], str]]":
    """Parse Prometheus text format into
    ``(family, mtype, sample_name, labels, value)`` rows (family = the
    HELP/TYPE grouping name, so ``x_bucket`` rows carry family ``x``).
    ``help_sink`` (optional) collects each family's HELP text.
    Tolerant of unknown lines (skipped), so a worker running newer code
    than its supervisor still aggregates."""
    out = []
    mtypes: Dict[str, str] = {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP "):
            if help_sink is not None:
                parts = ln.split(" ", 3)
                if len(parts) == 4:
                    help_sink.setdefault(parts[2], parts[3])
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split()
            if len(parts) >= 4:
                mtypes[parts[2]] = parts[3]
            continue
        if ln.startswith("#"):
            continue
        name_part, _, value = ln.rpartition(" ")
        if not name_part:
            continue
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            labels = {k: _unescape_label(v)
                      for k, v in _LABELS_RE.findall(
                          rest.rsplit("}", 1)[0])}
        else:
            name, labels = name_part, {}
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and mtypes.get(base) == "histogram":
                fam = base
                break
        out.append((fam, mtypes.get(fam, ""), name, labels, value))
    return out


def merge_expositions(by_worker: "Dict[str, str]",
                      help_table: Optional[Dict[str, str]] = None) -> str:
    """The supervisor's ``/metrics`` aggregation: each worker's
    exposition re-emitted with a ``worker`` label injected into every
    sample, one HELP/TYPE block per family across all workers. Workers
    stay individually scrapeable on their private ports; this is the
    one-target view (per-worker batcher occupancy, qps, cache hit
    ratios side by side)."""
    b = ExpositionBuilder()
    helps: Dict[str, str] = dict(help_table or {})
    parsed = {w: parse_exposition(by_worker[w], help_sink=helps)
              for w in by_worker}
    for worker in sorted(parsed, key=str):
        for fam, mtype, name, labels, value in parsed[worker]:
            if not mtype:
                mtype = "counter" if fam.endswith("_total") else "gauge"
            b.sample(name, {**labels, "worker": str(worker)}, value,
                     mtype=mtype,
                     help=helps.get(fam, f"FiloDB metric {fam}"),
                     family=fam)
    return b.render()
