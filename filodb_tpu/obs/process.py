"""Host/process-level collector for the global metrics registry.

The reference gets process metrics for free from the JVM's Kamon
system-metrics module; a CPython process has to read /proc itself.
Registered as a registry collector (``register_process_collector``), so
every exposition build — the /metrics scrape AND the self-monitoring
registry walk — carries host-level series from day one:

  filodb_process_resident_memory_bytes   RSS from /proc/self/statm
  filodb_process_virtual_memory_bytes    VSZ from /proc/self/statm
  filodb_process_open_fds                open descriptors (/proc/self/fd)
  filodb_process_threads                 live interpreter threads
  filodb_process_gc_collections_total    per-generation GC collections
  filodb_process_uptime_seconds          seconds since process start
  filodb_build_info                      constant 1 with version labels

Everything degrades gracefully off Linux (missing /proc reads emit
nothing rather than failing the scrape)."""

from __future__ import annotations

import gc
import os
import sys
import threading
import time

# process start approximated at first import of the obs layer — the
# server imports it during startup, so the error is milliseconds
_START_MONOTONIC = time.monotonic()

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

# bumped per release line; surfaced as filodb_build_info{version=...}
BUILD_VERSION = "0.11.0"


def _statm():
    try:
        with open("/proc/self/statm") as f:
            parts = f.read().split()
        return int(parts[0]) * _PAGE, int(parts[1]) * _PAGE  # vsz, rss
    except (OSError, ValueError, IndexError):
        return None, None


def _open_fds():
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def collect_process(builder) -> None:
    """The collector body: sample current process state into an
    ExpositionBuilder (called per exposition build)."""
    vsz, rss = _statm()
    if rss is not None:
        builder.sample("filodb_process_resident_memory_bytes", {}, rss,
                       help="Resident set size in bytes "
                            "(/proc/self/statm)")
    if vsz is not None:
        builder.sample("filodb_process_virtual_memory_bytes", {}, vsz,
                       help="Virtual memory size in bytes "
                            "(/proc/self/statm)")
    fds = _open_fds()
    if fds is not None:
        builder.sample("filodb_process_open_fds", {}, fds,
                       help="Open file descriptors (/proc/self/fd)")
    builder.sample("filodb_process_threads", {},
                   threading.active_count(),
                   help="Live Python threads in this process")
    for gen, st in enumerate(gc.get_stats()):
        builder.sample("filodb_process_gc_collections_total",
                       {"generation": str(gen)},
                       int(st.get("collections", 0)), mtype="counter",
                       help="Garbage-collector collections per "
                            "generation")
    builder.sample("filodb_process_uptime_seconds", {},
                   round(time.monotonic() - _START_MONOTONIC, 3),
                   help="Seconds since the obs layer was imported "
                        "(process startup)")
    builder.sample(
        "filodb_build_info",
        {"version": BUILD_VERSION,
         "python": "%d.%d.%d" % sys.version_info[:3]},
        1,
        help="Constant 1; build/runtime identity rides the labels")


def register_process_collector(registry=None) -> None:
    """Idempotently attach the process collector to ``registry``
    (default: the global registry)."""
    from filodb_tpu.obs import metrics as obs_metrics
    reg = registry if registry is not None else obs_metrics.GLOBAL_REGISTRY
    reg.register_collector(collect_process)
