"""Process-global structured event ring.

Counters say HOW OFTEN something happened; this ring says WHAT — the
durable-tier corruption events, quarantine actions, and integrity
degradations carry a file path, an offset, and a reason that no metric
label set should hold (unbounded cardinality). The ring is bounded,
lock-guarded, and surfaced at ``/debug/events`` (newest first), so an
operator chasing a ``filodb_storage_corruption_total`` bump lands on
the exact byte range and file within one request.

The rules engine keeps its own alert-transition ring (rules/engine.py)
— that one is per-engine protocol state; this one is the
process-global operational journal."""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from filodb_tpu.lint.locks import guarded_by


@guarded_by("_lock", "_ring", "_seq")
class EventRing:
    """Bounded ring of structured events (dicts), newest kept."""

    def __init__(self, capacity: int = 512):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(capacity))
        self._seq = 0

    def emit(self, kind: str, **fields) -> Dict:
        ev = {"kind": str(kind), "time": time.time(), **fields}
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
        return ev

    def snapshot(self, limit: int = 100, kind: Optional[str] = None
                 ) -> List[Dict]:
        """Newest-first snapshot, optionally filtered by kind."""
        with self._lock:
            evs = list(self._ring)
        evs.reverse()
        if kind is not None:
            evs = [e for e in evs if e.get("kind") == kind]
        return evs[:max(0, int(limit))]

    def count(self, kind: Optional[str] = None) -> int:
        with self._lock:
            evs = list(self._ring)
        if kind is None:
            return len(evs)
        return sum(1 for e in evs if e.get("kind") == kind)

    def clear(self) -> None:
        """Test hook."""
        with self._lock:
            self._ring.clear()


GLOBAL_EVENTS = EventRing()


def emit(kind: str, **fields) -> Dict:
    """Emit one event onto the process-global ring."""
    return GLOBAL_EVENTS.emit(kind, **fields)


def snapshot(limit: int = 100, kind: Optional[str] = None) -> List[Dict]:
    return GLOBAL_EVENTS.snapshot(limit=limit, kind=kind)
