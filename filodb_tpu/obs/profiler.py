"""In-process wall-clock sampling profiler (the SimpleProfiler
analogue).

The reference ships a built-in sampling profiler that periodically
walks every thread's stack and writes top-method reports
(standalone/SimpleProfiler.java); ours closes the same gap for the
jax_graft node. A declared thread root wakes at a configurable hz,
walks ``sys._current_frames()``, and attributes each thread's stack to
the ``lint/threads.py`` thread-root registry — so a sample lands on
"http-handler" vs "batcher-executor" vs "ingest-driver" vs
"rules-eval" even when the OS thread name is an unhelpful stdlib
``Thread-17 (process_request_thread)``. Attribution walks frames
outermost-first and matches ``(module, function)`` against every
registered ``@thread_root`` (Python 3.10: there is no
``co_qualname``, so the registry's qualname leaf is the match key),
falling back to thread-name prefix matching for roots whose entry
frame has already returned.

Aggregation is a bounded folded-stack table (flamegraph-ready:
``root;mod.fn;mod.fn2 count`` per line) plus a per-``(root, leaf)``
self-time table. The profiler serves both through
``/debug/profile?seconds=N`` (folded text or JSON top-self-time) and
exports top-N self-time as registry gauges
(``filodb_profile_self_seconds_total{root,func}``) so selfmon makes
the profile a PromQL query.

Cost model: one tick touches every live thread's frame chain — tens of
microseconds at our thread counts — so the default 29 hz duty cycle
stays far under 1%. Everything is OFF by default and the profiler
registers no metric families until started, keeping the default
``/metrics`` byte-identical.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import THREAD_ROOTS, thread_root
from filodb_tpu.obs import metrics as obs_metrics

# sampling clamps: below 1 hz the profile is useless, above 250 hz the
# sampler itself becomes the workload
MIN_HZ, MAX_HZ = 1.0, 250.0
# frames kept per folded stack (innermost truncated past this — deep
# recursion can't balloon the key strings)
MAX_DEPTH = 48
# /debug/profile?seconds=N window clamp (a handler thread blocks for
# the window; keep it bounded)
MAX_WINDOW_S = 30.0

UNATTRIBUTED = "(unattributed)"
OVERFLOW_KEY = "(overflow)"

_TICK_HELP = "Wall seconds per profiler sampling tick"
_TICK_BUCKETS = (0.00001, 0.000025, 0.00005, 0.0001, 0.00025, 0.0005,
                 0.001, 0.0025, 0.005, 0.01, 0.025)


def _root_tables() -> Tuple[Dict[Tuple[str, str], str], List[Tuple[str, str]]]:
    """Attribution tables from the live ``@thread_root`` registry:
    ``(module, function-leaf) -> display name`` for frame matching,
    plus ``(display name, name prefix)`` pairs for the thread-name
    fallback. Rebuilt per tick — the registry only grows at import
    time, but lazily imported modules may register roots after the
    profiler starts."""
    frames: Dict[Tuple[str, str], str] = {}
    names: List[Tuple[str, str]] = []
    for qual, info in THREAD_ROOTS.items():
        leaf = qual.rsplit(".", 1)[-1]
        frames[(info["module"], leaf)] = info["name"]
        names.append((info["name"], info["name"].split("-")[0]))
    return frames, names


@guarded_by("_lock", "_folded", "_self", "_samples", "_attributed",
            "_ticks", "_dropped_stacks", "_started_monotonic")
class SamplingProfiler:
    """Bounded wall-clock sampling profiler (a declared thread root).

    ``start()`` launches the sampler daemon; ``snapshot()`` /
    ``folded_text()`` / ``report()`` read the aggregate; ``window()``
    diffs the aggregate across a wall-clock window for
    ``/debug/profile?seconds=N``; ``sample_burst()`` runs inline
    sampling for the same endpoint when the daemon is off."""

    def __init__(self, hz: float = 29.0, max_stacks: int = 4096,
                 top_n: int = 20):
        self.hz = min(MAX_HZ, max(MIN_HZ, float(hz)))
        self.period_s = 1.0 / self.hz
        self.max_stacks = max(64, int(max_stacks))
        self.top_n = max(1, int(top_n))
        self._lock = threading.Lock()
        # folded stack ("root;mod.fn;...") -> sample count
        self._folded: Dict[str, int] = {}
        # (root, leaf "mod.fn") -> sample count (self time = n/hz)
        self._self: Dict[Tuple[str, str], int] = {}
        self._samples = 0           # thread-stacks sampled
        self._attributed = 0        # ... attributed to a known root
        self._ticks = 0
        self._dropped_stacks = 0    # folded keys refused at max_stacks
        self._started_monotonic: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # metric families are created on start(), not here: a
        # constructed-but-unstarted profiler must leave /metrics
        # byte-identical (histograms always render once registered)
        self._m_self: Optional[obs_metrics.GaugeFamily] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "SamplingProfiler":
        if self.running:
            return self
        reg = obs_metrics.GLOBAL_REGISTRY
        self._m_self = reg.gauge(
            "filodb_profile_self_seconds_total",
            "Sampled wall self-time per thread root and function "
            "(top-N, cumulative since profiler start)")
        self._stop.clear()
        with self._lock:
            self._started_monotonic = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="profiler-sampler")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @thread_root("profiler-sampler")
    def _run(self) -> None:
        # drift-corrected cadence: sleep to the next tick boundary so
        # the duty cycle stays hz * tick_cost regardless of tick cost
        next_t = time.monotonic() + self.period_s
        while not self._stop.wait(max(0.0, next_t - time.monotonic())):
            next_t += self.period_s
            t0 = time.perf_counter()
            try:
                self.tick()
            except Exception:   # noqa: BLE001 — profiling must not die
                pass
            obs_metrics.observe("filodb_profiler_tick_seconds",
                                _TICK_HELP,
                                time.perf_counter() - t0,
                                _TICK_BUCKETS)
            if self._m_self is not None:
                self._export_top()

    # -- one sampling tick -------------------------------------------------
    def tick(self) -> int:
        """Sample every live thread once; returns stacks recorded.
        Public for tests and for inline burst sampling."""
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames_tab, name_tab = _root_tables()
        recorded = []
        for ident, frame in list(sys._current_frames().items()):
            if ident == me:
                continue        # never profile the profiler
            stack: List[Tuple[str, str]] = []
            f = frame
            while f is not None and len(stack) < MAX_DEPTH:
                stack.append((f.f_globals.get("__name__", "?"),
                              f.f_code.co_name))
                f = f.f_back
            if not stack:
                continue
            stack.reverse()     # outermost first (folded order)
            root = None
            top = 0
            for i, key in enumerate(stack):
                hit = frames_tab.get(key)
                if hit is not None:
                    root, top = hit, i
                    break
            if root is None:
                tname = names.get(ident, "")
                for disp, prefix in name_tab:
                    if disp in tname or (prefix and
                                         tname.startswith(prefix)):
                        root = disp
                        break
            if root is None:
                root = UNATTRIBUTED
            folded = root + ";" + ";".join(
                f"{m}.{fn}" for m, fn in stack[top:])
            leaf = "{}.{}".format(*stack[-1])
            recorded.append((folded, root, leaf))
        with self._lock:
            for folded, root, leaf in recorded:
                if folded in self._folded:
                    self._folded[folded] += 1
                elif len(self._folded) < self.max_stacks:
                    self._folded[folded] = 1
                else:
                    self._dropped_stacks += 1
                    key = root + ";" + OVERFLOW_KEY
                    self._folded[key] = self._folded.get(key, 0) + 1
                self._self[(root, leaf)] = \
                    self._self.get((root, leaf), 0) + 1
                self._samples += 1
                if root != UNATTRIBUTED:
                    self._attributed += 1
            self._ticks += 1
        return len(recorded)

    def _export_top(self) -> None:
        """Top-N self-time into the gauge family (computed under the
        lock, set outside it — GaugeFamily has its own lock and the
        canonical order keeps profiler locks leaf-only)."""
        with self._lock:
            top = sorted(self._self.items(), key=lambda kv: -kv[1])
            top = top[:self.top_n]
        m = self._m_self
        if m is None:
            return
        for (root, leaf), n in top:
            m.set(round(n * self.period_s, 6), root=root, func=leaf)

    # -- read side ---------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            dur = (time.monotonic() - self._started_monotonic) \
                if self._started_monotonic is not None else 0.0
            return {"running": self.running, "hz": self.hz,
                    "ticks": self._ticks, "samples": self._samples,
                    "attributed": self._attributed,
                    "attribution_fraction": round(
                        self._attributed / self._samples, 4)
                    if self._samples else 1.0,
                    "distinct_stacks": len(self._folded),
                    "dropped_stacks": self._dropped_stacks,
                    "duration_s": round(dur, 3)}

    def tables(self) -> Tuple[Dict[str, int], Dict[Tuple[str, str], int]]:
        with self._lock:
            return dict(self._folded), dict(self._self)

    def folded_text(self,
                    folded: Optional[Dict[str, int]] = None) -> str:
        """The flamegraph input format: one ``stack count`` line per
        distinct folded stack, sorted for determinism."""
        if folded is None:
            folded, _ = self.tables()
        return "".join(f"{k} {v}\n" for k, v in sorted(folded.items()))

    def report(self, folded: Optional[Dict[str, int]] = None,
               selfs: Optional[Dict[Tuple[str, str], int]] = None,
               window_s: Optional[float] = None) -> Dict[str, object]:
        """JSON top-self-time report over the cumulative aggregate (or
        an explicit windowed slice from :meth:`window`)."""
        if folded is None or selfs is None:
            folded, selfs = self.tables()
        samples = sum(selfs.values())
        attributed = sum(n for (root, _), n in selfs.items()
                         if root != UNATTRIBUTED)
        roots: Dict[str, int] = {}
        for (root, _), n in selfs.items():
            roots[root] = roots.get(root, 0) + n
        top = [{"root": root, "func": leaf, "samples": n,
                "self_seconds": round(n * self.period_s, 6)}
               for (root, leaf), n in
               sorted(selfs.items(), key=lambda kv: (-kv[1], kv[0]))
               [:self.top_n]]
        out = dict(self.snapshot())
        out.update({
            "samples": samples,
            "attributed": attributed,
            "attribution_fraction": round(attributed / samples, 4)
            if samples else 1.0,
            "roots": {k: roots[k] for k in sorted(roots)},
            "top_self": top,
        })
        if window_s is not None:
            out["window_s"] = round(window_s, 3)
        return out

    # -- windowed collection (/debug/profile?seconds=N) --------------------
    def window(self, seconds: float
               ) -> Tuple[Dict[str, int], Dict[Tuple[str, str], int]]:
        """Block for ``seconds`` (clamped) and return the folded/self
        deltas the running sampler accumulated in that window."""
        seconds = min(MAX_WINDOW_S, max(0.0, float(seconds)))
        f0, s0 = self.tables()
        if seconds > 0.0:
            time.sleep(seconds)
        f1, s1 = self.tables()
        folded = {k: v - f0.get(k, 0) for k, v in f1.items()
                  if v - f0.get(k, 0) > 0}
        selfs = {k: v - s0.get(k, 0) for k, v in s1.items()
                 if v - s0.get(k, 0) > 0}
        return folded, selfs

    def sample_burst(self, seconds: float
                     ) -> Tuple[Dict[str, int], Dict[Tuple[str, str], int]]:
        """Inline sampling loop for when the daemon is off: the calling
        (handler) thread IS the sampler for the window, then the burst
        is removed from the cumulative aggregate so an off profiler
        stays empty between requests."""
        seconds = min(MAX_WINDOW_S, max(0.0, float(seconds)))
        f0, s0 = self.tables()
        deadline = time.monotonic() + seconds
        self.tick()
        while time.monotonic() < deadline:
            time.sleep(self.period_s)
            self.tick()
        f1, s1 = self.tables()
        folded = {k: v - f0.get(k, 0) for k, v in f1.items()
                  if v - f0.get(k, 0) > 0}
        selfs = {k: v - s0.get(k, 0) for k, v in s1.items()
                 if v - s0.get(k, 0) > 0}
        with self._lock:
            self._folded, self._self = f0, s0
            self._samples = sum(s0.values())
            self._attributed = sum(n for (r, _), n in s0.items()
                                   if r != UNATTRIBUTED)
        return folded, selfs
