"""The shared write-back rail: derived series re-enter the node through
the NORMAL ingest path.

Two standing background loops produce derived series today — the
self-monitoring loop (obs/selfmon.py: the full metrics surface every
tick) and the recording-rules engine (filodb_tpu/rules: rule outputs +
synthetic ``ALERTS`` state series). Both need exactly the same plumbing:
build :class:`~filodb_tpu.core.record.RecordBuilder` containers from
``(schema, labels, timestamp, value)`` samples and push them through the
normal ingest path — durable WAL append + ingestion-driver replay when a
stream is wired (derived series survive restarts), direct shard ingest +
explicit flush otherwise (so the ingest watermark, the results cache's
freshness input, still advances).

Factored here so the rail exists ONCE: one RecordBuilder per writer
root, single-writer by construction (each standing loop owns its own
instance), identical durability semantics for every producer.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.lint.locks import single_writer

# sample-name suffixes that are cumulative (monotone) series: they
# ingest under the counter schema so rate()/increase() get counter
# semantics (reset correction) — everything else is a gauge snapshot
COUNTER_SUFFIXES = ("_total", "_bucket", "_count", "_sum")


def schema_for_sample(family_type: str, sample_name: str) -> str:
    """Ingest schema for one derived sample: counters (and histogram
    children / counter-suffixed names) take the counter schema so
    ``rate()`` gets reset correction; everything else is a gauge."""
    if family_type == "counter":
        return "prom-counter"
    if family_type == "histogram" or sample_name.endswith(
            COUNTER_SUFFIXES):
        return "prom-counter"
    return "gauge"


@single_writer("an IngestWriteBack is owned by ONE standing background "
               "loop (the selfmon tick, the rules scheduler); each loop "
               "constructs and drives its own instance — instances are "
               "never shared across threads")
class IngestWriteBack:
    """One producer's write-back rail into an internal dataset shard.

    ``write()`` builds containers from samples and hands them to the
    durable stream when one is wired (the ingestion driver replays them
    into the memstore — the full WAL path, recovery included) or
    straight to ``shard.ingest`` otherwise. ``flush()`` advances the
    direct-ingest shard's watermark; it is a no-op in durable mode
    (the driver owns the flush cadence there)."""

    def __init__(self, shard, schemas=None, stream=None):
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        self.shard = shard
        self.schemas = schemas or DEFAULT_SCHEMAS
        self.stream = stream
        self.samples_written = 0

    @property
    def durable(self) -> bool:
        return self.stream is not None

    def write(self, samples: Iterable[Tuple[str, dict, int, float]]
              ) -> int:
        """Ingest ``(schema_name, labels, timestamp_ms, value)`` samples
        through the normal path; returns the number written."""
        rb = RecordBuilder(self.schemas)
        n = 0
        for schema_name, labels, ts_ms, value in samples:
            rb.add_sample(schema_name, labels, int(ts_ms), float(value))
            n += 1
        for cont in rb.containers():
            if self.stream is not None:
                # durable WAL first; the ingestion driver replays it
                # into the memstore (recovery-safe, group-commit fsync)
                self.stream.append(cont)
            else:
                self.shard.ingest(cont)
        self.samples_written += n
        return n

    def flush(self) -> None:
        """Direct-ingest mode: flush so the ingest watermark (the
        results cache's freshness input) advances like any shard. In
        durable mode the driver flushes on its own cadence."""
        if self.stream is None:
            self.shard.flush_all()
