"""Self-monitoring: the node ingests its own metrics as a first-class
tenant.

FiloDB is a Prometheus-compatible TSDB whose canonical deployment
monitors itself with itself — yet our ``/metrics`` was exposition-only.
This module closes the loop: on ``--self-monitor``, a per-process
background loop periodically snapshots the whole metrics surface
**in-process** (no HTTP scrape: it asks the server for its
:class:`~filodb_tpu.obs.metrics.ExpositionBuilder` and walks
``families()`` structurally), converts every counter/gauge/histogram
sample to ingest records via the normal
:class:`~filodb_tpu.core.record.RecordBuilder`, and pushes them through
the NORMAL ingest path into a reserved internal dataset — WAL append,
ingestion-driver replay, memstore, flush, and (when configured)
downsampling all exercise it, and the series come back out through the
ordinary PromQL endpoints::

    /promql/__selfmon__/api/v1/query_range?query=
        rate(filodb_executable_recompiles_total[5m])

Design points:

* **Reserved tenant** — internal series are tagged
  ``_ws_ = "__selfmon__"``; queries under that tenant ride the
  background priority class and charge FORCED (like fan-out legs), so
  self-telemetry can neither crowd out user queries nor bounce off a
  drained admission bucket (standing rule evaluation must never
  starve — the write-back rail ROADMAP 2's recording rules ride).
* **Cardinality isolation** — the internal dataset gets its own
  shard(s) with their own :class:`CardinalityTracker`/``TagIndex``
  (both are per-shard by construction), so internal series never touch
  user-dataset cardinality accounting or quotas.
* **Freshness** — the internal shard is a normal shard: its ingest
  watermark advances with every flush, so the results cache's
  freshness horizon is sound for self-queries exactly as for user
  queries; the loop additionally surfaces its own watermark
  (last-tick age, samples/tick) as gauges — which it then ingests,
  naturally.
* **Fleet** — under the supervisor every worker runs its own loop over
  its own internal shard (shard number = worker ordinal, so shared
  data/stream dirs never collide) and stamps a ``worker`` label on
  every internal series; the supervisor's merged view preserves it
  (merge idempotence keeps an existing worker label).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.obs.writeback import (COUNTER_SUFFIXES, IngestWriteBack,
                                      schema_for_sample)

# reserved identifiers: the internal dataset name doubles as the
# reserved tenant (workspace) internal series are tagged with
SELFMON_DATASET = "__selfmon__"
SELFMON_TENANT = "__selfmon__"

# back-compat aliases: the schema heuristic moved to obs/writeback.py
# (the factored write-back rail shared with the rules engine)
_COUNTER_SUFFIXES = COUNTER_SUFFIXES
_schema_for = schema_for_sample

_TICK_HELP = "Wall seconds per self-monitoring collect+ingest tick"


@guarded_by("_lock", "ticks", "samples_ingested", "series_last_tick",
            "errors", "last_tick_monotonic", "last_tick_s")
class SelfMonitor:
    """The per-process self-monitoring loop (a declared thread root).

    ``exposition_source()`` returns an ExpositionBuilder holding the
    full metrics surface (the HTTP server's ``build_exposition``);
    records flow to ``stream.append`` when a durable stream is wired
    (the ingestion driver then replays them — the full WAL path) or
    straight into ``shard.ingest`` + periodic flush otherwise."""

    def __init__(self, exposition_source, shard,
                 schemas=None, stream=None,
                 interval_s: float = 5.0,
                 node: str = "", worker_id: Optional[int] = None,
                 flush_every_ticks: int = 4):
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        self.exposition_source = exposition_source
        self.shard = shard
        self.schemas = schemas or DEFAULT_SCHEMAS
        self.stream = stream
        # the factored write-back rail (obs/writeback.py): this loop is
        # its single writer; the rules engine drives its own instance
        self.writeback = IngestWriteBack(shard, schemas=self.schemas,
                                         stream=stream)
        self.interval_s = float(interval_s)
        self.node = node or ""
        self.worker_id = worker_id
        self.flush_every_ticks = max(1, int(flush_every_ticks))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.ticks = 0
        self.samples_ingested = 0
        self.series_last_tick = 0
        self.errors = 0
        self.last_tick_monotonic: Optional[float] = None
        self.last_tick_s = 0.0
        # the loop's own families ride the registry, so the NEXT tick
        # ingests this tick's health — the loop monitors itself too
        reg = obs_metrics.GLOBAL_REGISTRY
        self._m_ticks = reg.counter(
            "filodb_selfmon_ticks_total",
            "Self-monitoring collect+ingest ticks completed")
        self._m_samples = reg.counter(
            "filodb_selfmon_samples_ingested_total",
            "Metric samples self-ingested into the internal dataset")
        self._m_errors = reg.counter(
            "filodb_selfmon_errors_total",
            "Self-monitoring ticks that raised (collection continues)")
        self._m_series = reg.gauge(
            "filodb_selfmon_series_last_tick",
            "Distinct internal series written by the last tick")
        reg.register_collector(self._collect_age)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "SelfMonitor":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="selfmon-loop")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _collect_age(self, builder) -> None:
        # sample straight into the CURRENT build (a gauge family set
        # here would only surface in the NEXT exposition — racy when a
        # scrape lands between the first completed tick and the next
        # build's collector phase)
        with self._lock:
            last = self.last_tick_monotonic
        if last is not None:
            builder.sample(
                "filodb_selfmon_last_tick_age_seconds", {},
                round(time.monotonic() - last, 3), mtype="gauge",
                help="Seconds since the last completed self-monitoring "
                     "tick (the loop's own freshness watermark)")

    @thread_root("selfmon-loop")
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.collect_once()
            except Exception:   # noqa: BLE001 — telemetry must not die
                with self._lock:
                    self.errors += 1
                self._m_errors.inc()

    # -- one tick ----------------------------------------------------------
    def collect_once(self, now_ms: Optional[int] = None) -> int:
        """Snapshot the registry walk and ingest every sample; returns
        the number of samples written. Public for tests and for an
        eager first tick at startup."""
        t0 = time.perf_counter()
        if now_ms is None:
            now_ms = int(time.time() * 1000)
        builder = self.exposition_source()
        out = []
        series: set = set()
        for fam, mtype, _help, samples in builder.families():
            for name, labels_tuple, value in samples:
                try:
                    v = float(str(value).replace("+Inf", "inf")
                              .replace("NaN", "nan"))
                except (TypeError, ValueError):
                    continue
                labels: Dict[str, str] = {
                    "_ws_": SELFMON_TENANT,
                    "_ns_": self.node or "node",
                    "_metric_": name,
                }
                for k, lv in labels_tuple:
                    if k not in labels:
                        labels[k] = lv
                if self.worker_id is not None:
                    labels.setdefault("worker", str(self.worker_id))
                out.append((schema_for_sample(mtype, name), labels,
                            now_ms, v))
                series.add((name, labels_tuple))
        n = self.writeback.write(out)
        with self._lock:
            self.ticks += 1
            self.samples_ingested += n
            self.series_last_tick = len(series)
            self.last_tick_monotonic = time.monotonic()
            self.last_tick_s = time.perf_counter() - t0
            ticks = self.ticks
        if self.stream is None and ticks % self.flush_every_ticks == 0:
            # direct-ingest mode: flush so the ingest watermark (the
            # results cache's freshness input) advances like any shard
            self.writeback.flush()
        self._m_ticks.inc()
        self._m_samples.inc(n)
        self._m_series.set(len(series))
        obs_metrics.observe("filodb_selfmon_tick_seconds", _TICK_HELP,
                            time.perf_counter() - t0)
        return n

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"ticks": self.ticks,
                    "samples_ingested": self.samples_ingested,
                    "series_last_tick": self.series_last_tick,
                    "errors": self.errors,
                    "last_tick_s": round(self.last_tick_s, 6),
                    "interval_s": self.interval_s,
                    "alive": self.alive}
