"""Device compile/cost profiling: executable-level telemetry for every
kernel build site.

The device layer was a black box: the dispatch tables counted hits and
misses, but nothing recorded WHAT was compiled, how long each build
took per executable, how often shape churn forced retraces, or what
the lowered program actually costs (FLOPs / bytes accessed from XLA's
``cost_analysis``). This module is the registry behind three surfaces:

  * **/metrics families** (via a global-registry collector, therefore
    also self-ingested and PromQL-queryable once ``--self-monitor`` is
    on — "recompiles in the last 5m" becomes a query):

      filodb_executable_builds_total{site,bucket}      compile events
      filodb_executable_recompiles_total{site,bucket}  shape-churn
                                                       retraces past the
                                                       first build
      filodb_executable_flops{site,executable}         cost_analysis
      filodb_executable_bytes_accessed{site,executable}
      filodb_executables                               live entries

  * **``&explain=analyze``** — per-query device stats: which
    executables the query's dispatches ran (identity + disposition
    from trace events the profiled call sites emit), each with its
    cost-analysis numbers.

  * **:class:`ProfiledExecutable`** — the wrapper the tilestore
    dispatch tables cache. On a table miss the builder lowers +
    compiles the jitted callable AOT (``fn.lower(*args).compile()``)
    — that IS the first call's compile, not an extra one — captures
    ``cost_analysis()`` from the compiled program, and keeps the
    compiled executable as the primary dispatch for the build shape.
    Calls with a different shape signature fall back to the jitted
    callable (whose own cache handles them) and count as recompiles
    per new signature.

Packed/mesh kernels (module-level ``jax.jit`` with static argnames)
register *lazy* cost probes instead: the call site records the abstract
signature (ShapeDtypeStructs + statics) on first sight, and
:meth:`DeviceProfiler.ensure_cost` lowers + compiles it on demand —
the first ``&explain=analyze`` touching that executable pays the probe
compile; serving dispatches never do.

Everything here is allocation-free on the hot path when untraced:
per-dispatch accounting is one small critical section (the same cost
class as the existing dispatch-table hit counters).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.obs import trace as obs_trace

# trace event name the profiled call sites emit per dispatch; the
# analyze payload collects these to attribute executables to a query
EXEC_EVENT = "executable"

# cache inventory (graftlint): the profiler's entry table (and the AOT
# Compiled each ProfiledExecutable holds) key purely on (site,
# dispatch-table key) — a pure function of executable identity, immune
# to every world event by construction (the underlying dispatch tables
# declare their own registries at their owning modules)
__cache_registry__ = {
    "devprof-executable-profiles": {"keyed": ("site", "executable-key")},
}

_KEY_MAX = 96


def key_str(key: Tuple) -> str:
    """Compact, bounded label form of a dispatch-table key."""
    s = "/".join(str(x) for x in key)
    return s if len(s) <= _KEY_MAX else s[:_KEY_MAX - 1] + "~"


def shape_bucket(key: Tuple) -> str:
    """The shape-bucket label for recompile counters: the key minus its
    leading family/func atoms collapses to the numeric bucket tuple
    (pow2-padded dims), which is what churns under load."""
    nums = [str(x) for x in key if isinstance(x, (int, float))]
    return "x".join(nums) if nums else key_str(key)


def arg_sig(args) -> Tuple:
    """Recursive (shape, dtype) signature of a call's dynamic args —
    the identity under which one compiled executable is reusable."""
    out = []
    for a in args:
        if isinstance(a, (tuple, list)):
            out.append(arg_sig(a))
        else:
            shape = getattr(a, "shape", None)
            dtype = getattr(a, "dtype", None)
            if shape is not None:
                out.append((tuple(shape), str(dtype)))
            else:
                out.append(type(a).__name__)
    return tuple(out)


def cost_from_compiled(compiled) -> Optional[Dict[str, float]]:
    """FLOPs / bytes-accessed from a ``Compiled``'s cost_analysis
    (dict in new jax, [dict] in 0.4.x; None when the backend doesn't
    provide one)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:   # noqa: BLE001 — cost is best-effort telemetry
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: Dict[str, float] = {}
    if ca.get("flops") is not None:
        out["flops"] = float(ca["flops"])
    if ca.get("bytes accessed") is not None:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out or None


class _Entry:
    """One cached executable's running profile (mutation under the
    profiler's lock)."""

    __slots__ = ("site", "key", "key_s", "bucket", "builds", "hits",
                 "recompiles", "build_s_total", "last_build_s", "cost",
                 "sigs", "lazy_probe", "created_s")

    def __init__(self, site: str, key: Tuple):
        self.site = site
        self.key = key
        self.key_s = key_str(key)
        self.bucket = shape_bucket(key)
        self.builds = 0
        self.hits = 0
        self.recompiles = 0
        self.build_s_total = 0.0
        self.last_build_s = 0.0
        self.cost: Optional[Dict[str, float]] = None
        self.sigs: set = set()
        # () -> Compiled; set by sites that defer cost capture
        self.lazy_probe: Optional[Callable] = None
        self.created_s = time.monotonic()

    def to_json(self) -> Dict[str, object]:
        d = {"site": self.site, "executable": self.key_s,
             "bucket": self.bucket, "builds": self.builds,
             "hits": self.hits, "recompiles": self.recompiles,
             "build_s_total": round(self.build_s_total, 6),
             "last_build_s": round(self.last_build_s, 6)}
        if self.cost is not None:
            d.update(self.cost)
        return d


@guarded_by("_lock", "_entries")
class DeviceProfiler:
    """Process-global registry of executable profiles (one per cached
    executable across the tilestore dispatch tables, the packed kernel
    family, and the mesh executors)."""

    # safety valve: label cardinality on the cost gauges is bounded by
    # the pow2 shape bucketing, but a pathological workload could still
    # churn keys — cap the table (oldest entries beyond it are dropped
    # from the PROFILE only; the underlying executables live in their
    # own caches)
    MAX_ENTRIES = 512

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, Tuple], _Entry] = {}

    def _entry_locked(self, site: str, key: Tuple) -> _Entry:
        e = self._entries.get((site, key))
        if e is None:
            if len(self._entries) >= self.MAX_ENTRIES:
                oldest = min(self._entries,
                             key=lambda k: self._entries[k].created_s)
                del self._entries[oldest]
            e = _Entry(site, key)
            self._entries[(site, key)] = e
        return e

    def note_build(self, site: str, key: Tuple, seconds: float,
                   cost: Optional[Dict[str, float]] = None,
                   sig: Optional[Tuple] = None,
                   lazy_probe: Optional[Callable] = None) -> bool:
        """Record one compile event; returns True when this was a
        RECOMPILE (the site+bucket family already had a build — shape
        churn, cache invalidation)."""
        with self._lock:
            e = self._entry_locked(site, key)
            recompile = e.builds > 0
            e.builds += 1
            e.build_s_total += float(seconds)
            e.last_build_s = float(seconds)
            if cost is not None:
                e.cost = cost
            if sig is not None:
                e.sigs.add(sig)
            if lazy_probe is not None and e.lazy_probe is None \
                    and e.cost is None:
                e.lazy_probe = lazy_probe
            if recompile:
                e.recompiles += 1
        return recompile

    def note_call(self, site: str, key: Tuple,
                  sig: Optional[Tuple] = None) -> bool:
        """Record one dispatch through an already-built executable;
        returns True when ``sig`` is NEW for the entry (the call fell
        back to a jit retrace — counted as a recompile)."""
        with self._lock:
            e = self._entry_locked(site, key)
            e.hits += 1
            if sig is not None and sig not in e.sigs:
                e.sigs.add(sig)
                e.recompiles += 1
                return True
        return False

    def set_cost(self, site: str, key: Tuple,
                 cost: Optional[Dict[str, float]]) -> None:
        if cost is None:
            return
        with self._lock:
            self._entry_locked(site, key).cost = cost

    def ensure_cost(self, site: str, key: Tuple
                    ) -> Optional[Dict[str, float]]:
        """Cost-analysis numbers for one executable, computing them via
        the entry's lazy probe on first demand (an ``&explain=analyze``
        request pays this probe compile once per executable; steady
        serving never does)."""
        with self._lock:
            e = self._entries.get((site, key))
            if e is None:
                return None
            if e.cost is not None or e.lazy_probe is None:
                return e.cost
            probe = e.lazy_probe
        # compile OUTSIDE the lock (XLA compiles take ~100ms)
        try:
            compiled = probe()
            cost = cost_from_compiled(compiled)
        except Exception:   # noqa: BLE001 — a probe must never fail a query
            cost = None
        with self._lock:
            e = self._entries.get((site, key))
            if e is not None:
                e.lazy_probe = None     # one attempt; don't re-pay failures
                if cost is not None and e.cost is None:
                    e.cost = cost
            return cost

    def lookup(self, site: str, key_s: str) -> Optional[Dict]:
        """Entry JSON by (site, rendered key) — the analyze path's view
        (trace events carry the rendered key, not the tuple)."""
        with self._lock:
            for (s, _k), e in self._entries.items():
                if s == site and e.key_s == key_s:
                    ensure = (e.site, e.key)
                    break
            else:
                return None
        self.ensure_cost(*ensure)
        with self._lock:
            for (s, _k), e in self._entries.items():
                if s == site and e.key_s == key_s:
                    return e.to_json()
        return None

    def snapshot(self) -> List[Dict[str, object]]:
        with self._lock:
            entries = list(self._entries.values())
        return [e.to_json() for e in sorted(
            entries, key=lambda e: (e.site, e.key_s))]

    def reset(self) -> None:
        """Test hook."""
        with self._lock:
            self._entries.clear()

    # -- /metrics collector ------------------------------------------------
    def collect(self, builder) -> None:
        """Registry collector: executable-level families into the
        exposition (and therefore into the self-monitoring ingest)."""
        snap = self.snapshot()
        builder.sample("filodb_executables", {}, len(snap),
                       help="Cached device executables with a profile "
                            "entry")
        builds: Dict[Tuple[str, str], int] = {}
        recompiles: Dict[Tuple[str, str], int] = {}
        for e in snap:
            k = (e["site"], e["bucket"])
            builds[k] = builds.get(k, 0) + int(e["builds"])
            recompiles[k] = recompiles.get(k, 0) + int(e["recompiles"])
        for (site, bucket), n in sorted(builds.items()):
            builder.sample("filodb_executable_builds_total",
                           {"site": site, "bucket": bucket}, n,
                           mtype="counter",
                           help="Executable compile events (trace + "
                                "XLA build) by build site and shape "
                                "bucket")
        for (site, bucket), n in sorted(recompiles.items()):
            if n:
                builder.sample("filodb_executable_recompiles_total",
                               {"site": site, "bucket": bucket}, n,
                               mtype="counter",
                               help="Retraces past an executable's "
                                    "first build (shape churn; a "
                                    "storm here is a recompile storm)")
        for e in snap:
            if "flops" not in e and "bytes_accessed" not in e:
                continue
            lbl = {"site": e["site"], "executable": e["executable"]}
            if "flops" in e:
                builder.sample("filodb_executable_flops", lbl,
                               e["flops"],
                               help="XLA cost_analysis FLOPs of the "
                                    "lowered executable")
            if "bytes_accessed" in e:
                builder.sample("filodb_executable_bytes_accessed", lbl,
                               e["bytes_accessed"],
                               help="XLA cost_analysis bytes accessed "
                                    "of the lowered executable")


GLOBAL_PROFILER = DeviceProfiler()


def _register_collector() -> None:
    from filodb_tpu.obs import metrics as obs_metrics
    obs_metrics.GLOBAL_REGISTRY.register_collector(GLOBAL_PROFILER.collect)


_register_collector()


class ProfiledExecutable:
    """The object the tilestore dispatch tables cache: AOT-compiled
    primary dispatch for the build shape + jit fallback for churned
    shapes, with per-call profiling and an ``executable`` trace event
    (no-op when untraced) carrying identity + disposition."""

    __slots__ = ("fn", "site", "key", "key_s", "_compiled", "_sig")

    def __init__(self, fn, site: str, key: Tuple,
                 compiled=None, sig: Optional[Tuple] = None):
        self.fn = fn
        self.site = site
        self.key = key
        self.key_s = key_str(key)
        self._compiled = compiled
        self._sig = sig

    def __call__(self, *args):
        sig = arg_sig(args)
        if self._compiled is not None and sig == self._sig:
            try:
                out = self._compiled(*args)
                GLOBAL_PROFILER.note_call(self.site, self.key, sig)
                obs_trace.event(EXEC_EVENT, site=self.site,
                                key=self.key_s, disposition="aot")
                return out
            except (TypeError, ValueError):
                # aval/weak-type mismatch the signature missed: the jit
                # path below retraces and its own cache takes over
                pass
        retraced = GLOBAL_PROFILER.note_call(self.site, self.key, sig)
        obs_trace.event(EXEC_EVENT, site=self.site, key=self.key_s,
                        disposition="jit-retrace" if retraced else "jit")
        return self.fn(*args)


def build_profiled(site: str, key: Tuple, build: Callable,
                   cost_args: Optional[Sequence] = None
                   ) -> ProfiledExecutable:
    """Build one dispatch-table entry with full compile telemetry.
    ``build()`` returns the jitted callable; with ``cost_args`` (the
    first call's argument tuple) the executable is lowered + compiled
    AOT right here — the one compile the miss was going to pay anyway —
    and cost_analysis is captured from the compiled program."""
    t0 = time.perf_counter()
    fn = build()
    compiled = None
    cost = None
    sig = None
    if cost_args is not None:
        try:
            compiled = fn.lower(*cost_args).compile()
            cost = cost_from_compiled(compiled)
            sig = arg_sig(cost_args)
        except Exception:   # noqa: BLE001 — profiling must not fail builds
            compiled = None
            sig = None
    build_s = time.perf_counter() - t0
    GLOBAL_PROFILER.note_build(site, key, build_s, cost=cost, sig=sig)
    obs_trace.event(EXEC_EVENT, site=site, key=key_str(key),
                    disposition="build")
    return ProfiledExecutable(fn, site, key, compiled=compiled, sig=sig)


def note_dispatch(site: str, key: Tuple, first_seen: bool,
                  probe: Optional[Callable] = None) -> None:
    """Per-dispatch accounting for lazily-profiled sites (the packed
    path's ``_count_exec`` hook, the mesh executors): first sight is
    the compile event (``probe``, when given, is the () -> Compiled
    lazy cost probe), later dispatches count as cache hits. Emits the
    identity trace event either way."""
    if first_seen:
        GLOBAL_PROFILER.note_build(site, key, 0.0, lazy_probe=probe)
    else:
        GLOBAL_PROFILER.note_call(site, key)
    obs_trace.event(EXEC_EVENT, site=site, key=key_str(key),
                    disposition="build" if first_seen else "jit")


# ---------------------------------------------------------------------------
# &explain=analyze payload
# ---------------------------------------------------------------------------

def analyze_payload(spans: List[Dict], stages: Dict,
                    batcher_stats: Optional[Dict] = None,
                    qos_info: Optional[Dict] = None,
                    residency: Optional[Dict] = None) -> Dict:
    """The ``&explain=analyze`` envelope: per-stage timings (the spans
    PR 4's ``&explain=trace`` already records), the executables this
    query's dispatches actually ran — identity, compile disposition,
    cost-analysis FLOPs/bytes (computed on demand) — batcher occupancy
    at dispatch, cache dispositions, and the shed/degrade decision."""
    execs: Dict[Tuple[str, str], Dict] = {}
    dispatches: List[Dict] = []
    for sp in spans:
        tags = sp.get("tags") or {}
        name = sp.get("name")
        if name == EXEC_EVENT:
            k = (str(tags.get("site", "")), str(tags.get("key", "")))
            e = execs.setdefault(k, {"site": k[0], "executable": k[1],
                                     "dispatches": 0,
                                     "dispositions": []})
            e["dispatches"] += 1
            disp = str(tags.get("disposition", ""))
            if disp and disp not in e["dispositions"]:
                e["dispositions"].append(disp)
        elif name in ("device-dispatch", "device-eval", "kernel-build",
                      "batcher-dispatch", "device-sync",
                      "batcher-queue-wait"):
            d = {"span": name, "dur_us": sp.get("dur_us")}
            d.update(tags)
            dispatches.append(d)
    for (site, key_s), e in execs.items():
        entry = GLOBAL_PROFILER.lookup(site, key_s)
        if entry is not None:
            for f in ("builds", "recompiles", "build_s_total",
                      "last_build_s", "flops", "bytes_accessed",
                      "bucket"):
                if f in entry:
                    e[f] = entry[f]
    out: Dict[str, object] = {
        "stages": dict(stages),
        "device": {
            "executables": sorted(execs.values(),
                                  key=lambda e: (e["site"],
                                                 e["executable"])),
            "dispatches": dispatches,
        },
    }
    if batcher_stats is not None:
        out["batcher"] = batcher_stats
    if qos_info is not None:
        out["qos"] = qos_info
    if residency:
        out["residency"] = {
            family: {"shards": dict(shards),
                     "total_bytes": sum(shards.values())}
            for family, shards in residency.items()
        }
    return out
