"""Persistence layer: pluggable ColumnStore (ChunkSink + RawChunkSource +
MetaStore checkpoint table) with a flat-file implementation.

(Reference: store/ChunkSink.scala, store/ChunkSource.scala:25 RawChunkSource,
cassandra/columnstore/CassandraColumnStore.scala:54,
cassandra/metastore/CheckpointTable.scala:26.)"""

from filodb_tpu.store.columnstore import (ColumnStore, FlatFileColumnStore,
                                          NullColumnStore, PartKeyEntry)

__all__ = ["ColumnStore", "FlatFileColumnStore", "NullColumnStore",
           "PartKeyEntry"]
