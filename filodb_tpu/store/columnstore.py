"""ColumnStore: durable chunk + partkey + checkpoint persistence.

The pluggable boundary mirrors the reference's ChunkSink/RawChunkSource
(store/ChunkSink.scala; store/ChunkSource.scala:25) and the Cassandra
implementation's tables (cassandra/columnstore/CassandraColumnStore.scala:54:
TimeSeriesChunksTable, PartitionKeysTable; metastore CheckpointTable.scala:26)
— but the storage engine is TPU-host-native: encoded chunks are already
immutable compressed byte vectors (the interchange format), so persistence is
append-only framed logs per shard, fsync'd per flush group. No external
database is required; an object-store or Cassandra client can implement the
same four-method API.

Layout under root:
    <dataset>/shard=<n>/chunks.log      framed: partkey + chunk meta + vectors
    <dataset>/shard=<n>/partkeys.log    framed: partkey + startTime + endTime
    <dataset>/shard=<n>/checkpoints.json   CRC envelope over {group: offset}
    <dataset>/shard=<n>/quarantine/     sidecar: bad byte ranges + manifest

Integrity (the reference gets this from Cassandra; see store/integrity.py):
every record is wrapped in a checksummed frame on write, and every read —
index build, ODP chunk fetch, partkey scan, checkpoint load — verifies
before decoding. Corrupt records are quarantined and skipped (scan resumes
at the next verified boundary), torn tails are truncated at the writer's
takeover, and legacy unframed records read back unchanged via a per-record
magic sniff (compaction via delete_part_keys rewrites surviving records
framed, migrating the file). ENOSPC and friends propagate to the caller
(the ingestion driver maps them to the ingest-read-only degradation) with
the partial batch truncated away, so a failed write never leaves torn
bytes mid-log.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from typing import Dict, Iterator, List, Sequence, Set, Tuple

from filodb_tpu.store import integrity
from filodb_tpu.testing import chaos

_CHUNK_MAGIC = 0xC4A2
_PK_MAGIC = 0xBE11

# chunk record header: magic u16, pk_len u16, ncols u16, pad u16,
#                      chunk_id i64, num_rows i32, start i64, end i64
_CHUNK_HDR = struct.Struct("<HHHHqiqq")
# partkey record: magic u16, pk_len u16, start i64, end i64
_PK_HDR = struct.Struct("<HHqq")


@dataclass(frozen=True)
class PartKeyEntry:
    """One persisted partkey (PartitionKeysTable row)."""
    part_key: bytes
    start_ts: int
    end_ts: int


@dataclass(frozen=True)
class PersistedChunk:
    """One persisted chunk set (TimeSeriesChunksTable row)."""
    part_key: bytes
    chunk_id: int
    num_rows: int
    start_ts: int
    end_ts: int
    vectors: Tuple[bytes, ...]


# -- record codecs (the frame payload stays the legacy encoding) -------------

def _encode_chunk_record(part_key: bytes, chunk_id: int, num_rows: int,
                         start_ts: int, end_ts: int,
                         vectors: Sequence[bytes]) -> bytes:
    vec_lens = struct.pack(f"<{len(vectors)}i", *[len(v) for v in vectors])
    return (_CHUNK_HDR.pack(_CHUNK_MAGIC, len(part_key), len(vectors), 0,
                            chunk_id, num_rows, start_ts, end_ts)
            + part_key + vec_lens + b"".join(vectors))


def _decode_chunk_record(buf: bytes, off: int = 0) -> PersistedChunk:
    if off + _CHUNK_HDR.size > len(buf):
        raise ValueError("truncated chunk record header")
    magic, pk_len, ncols, _, cid, nrows, st, en = \
        _CHUNK_HDR.unpack_from(buf, off)
    if magic != _CHUNK_MAGIC:
        raise ValueError(f"bad chunk record magic 0x{magic:04x}")
    p = off + _CHUNK_HDR.size
    if p + pk_len + 4 * ncols > len(buf):
        raise ValueError("truncated chunk record body")
    pk = buf[p:p + pk_len]
    p += pk_len
    vec_lens = struct.unpack_from(f"<{ncols}i", buf, p)
    p += 4 * ncols
    vecs = []
    for vl in vec_lens:
        if vl < 0 or p + vl > len(buf):
            raise ValueError("truncated chunk record vectors")
        vecs.append(buf[p:p + vl])
        p += vl
    return PersistedChunk(pk, cid, nrows, st, en, tuple(vecs))


def _encode_pk_record(e: PartKeyEntry) -> bytes:
    return (_PK_HDR.pack(_PK_MAGIC, len(e.part_key), e.start_ts, e.end_ts)
            + e.part_key)


def _decode_pk_record(buf: bytes, off: int = 0) -> PartKeyEntry:
    if off + _PK_HDR.size > len(buf):
        raise ValueError("truncated partkey record header")
    magic, pk_len, st, en = _PK_HDR.unpack_from(buf, off)
    if magic != _PK_MAGIC:
        raise ValueError(f"bad partkey record magic 0x{magic:04x}")
    pk = buf[off + _PK_HDR.size:off + _PK_HDR.size + pk_len]
    if len(pk) < pk_len:
        raise ValueError("truncated partkey record body")
    return PartKeyEntry(pk, st, en)


def legacy_chunk_probe(buf: bytes, off: int) -> int:
    """Integrity-scanner probe for pre-framing chunk records: total
    length when a plausible record starts at ``off``, -1 torn, 0 not
    a legacy chunk record."""
    if off + 2 > len(buf) or \
            struct.unpack_from("<H", buf, off)[0] != _CHUNK_MAGIC:
        return 0
    if off + _CHUNK_HDR.size > len(buf):
        return -1
    _, pk_len, ncols, _, _, _, _, _ = _CHUNK_HDR.unpack_from(buf, off)
    p = off + _CHUNK_HDR.size + pk_len
    if p + 4 * ncols > len(buf):
        return -1
    vec_lens = struct.unpack_from(f"<{ncols}i", buf, p)
    if any(vl < 0 for vl in vec_lens):
        return 0
    total = _CHUNK_HDR.size + pk_len + 4 * ncols + sum(vec_lens)
    if total > integrity.MAX_PAYLOAD:
        return 0
    return total if off + total <= len(buf) else -1


def legacy_pk_probe(buf: bytes, off: int) -> int:
    """Integrity-scanner probe for pre-framing partkey records."""
    if off + 2 > len(buf) or \
            struct.unpack_from("<H", buf, off)[0] != _PK_MAGIC:
        return 0
    if off + _PK_HDR.size > len(buf):
        return -1
    _, pk_len, _, _ = _PK_HDR.unpack_from(buf, off)
    total = _PK_HDR.size + pk_len
    return total if off + total <= len(buf) else -1


class ColumnStore:
    """Abstract persistence API (ChunkSink + RawChunkSource + checkpoints)."""

    def write_chunks(self, dataset: str, shard: int, part_key: bytes,
                     chunks: Sequence) -> None:
        raise NotImplementedError

    def read_chunks(self, dataset: str, shard: int, part_key: bytes,
                    start_ts: int = 0, end_ts: int = 1 << 62
                    ) -> List[PersistedChunk]:
        raise NotImplementedError

    def write_part_keys(self, dataset: str, shard: int,
                        entries: Sequence[PartKeyEntry]) -> None:
        raise NotImplementedError

    def scan_part_keys(self, dataset: str, shard: int
                       ) -> Iterator[PartKeyEntry]:
        raise NotImplementedError

    def write_checkpoint(self, dataset: str, shard: int, group: int,
                         offset: int) -> None:
        raise NotImplementedError

    def read_checkpoints(self, dataset: str, shard: int) -> Dict[int, int]:
        raise NotImplementedError

    def delete_part_keys(self, dataset: str, shard: int,
                         part_keys: Sequence[bytes]) -> None:
        """Remove series (index entries + chunks) — the cardinality
        buster's primitive."""
        raise NotImplementedError

    def quarantined_records(self, dataset: str, shard: int) -> int:
        """Corrupt records this store has quarantined for the shard
        (0 for sinks with no durable files)."""
        return 0

    def close(self) -> None:
        pass


class NullColumnStore(ColumnStore):
    """No-op sink (store/ChunkSink.scala:126 NullColumnStore): memstore-only
    deployments and tests."""

    def write_chunks(self, dataset, shard, part_key, chunks) -> None:
        pass

    def read_chunks(self, dataset, shard, part_key, start_ts=0,
                    end_ts=1 << 62):
        return []

    def write_part_keys(self, dataset, shard, entries) -> None:
        pass

    def scan_part_keys(self, dataset, shard):
        return iter(())

    def write_checkpoint(self, dataset, shard, group, offset) -> None:
        pass

    def read_checkpoints(self, dataset, shard):
        return {}

    def delete_part_keys(self, dataset, shard, part_keys) -> None:
        pass


class FlatFileColumnStore(ColumnStore):
    """Append-only framed-log store. One writer per shard (the ingest
    thread), readers tolerate torn tails and quarantine corrupt records."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # (dataset, shard) -> {part_key: {chunk_id: file offset}} lazy ODP
        # index; keyed by chunk_id so replayed/re-run appends upsert (last
        # record wins), matching the reference's Cassandra upsert semantics
        self._chunk_index: Dict[Tuple[str, int],
                                Dict[bytes, Dict[int, int]]] = {}
        # (dataset, shard) sets whose partkeys.log tail has been validated
        self._pk_validated: set = set()
        # quarantine bookkeeping: per-shard counts for the integrity
        # knob, and (path, offset) pairs already reported so re-scans
        # of a log (partkey scans re-read per call) don't double-count
        self._quarantined: Dict[Tuple[str, int], int] = {}
        self._seen_corrupt: Set[Tuple[str, int]] = set()

    # -- paths ------------------------------------------------------------
    def _shard_dir(self, dataset: str, shard: int) -> str:
        d = os.path.join(self.root, dataset, f"shard={shard}")
        os.makedirs(d, exist_ok=True)
        return d

    def _chunks_path(self, dataset: str, shard: int) -> str:
        return os.path.join(self._shard_dir(dataset, shard), "chunks.log")

    def _pk_path(self, dataset: str, shard: int) -> str:
        return os.path.join(self._shard_dir(dataset, shard), "partkeys.log")

    def _ckpt_path(self, dataset: str, shard: int) -> str:
        return os.path.join(self._shard_dir(dataset, shard),
                            "checkpoints.json")

    # -- integrity bookkeeping --------------------------------------------
    def _note_corrupt(self, path: str, kind: str, dataset: str, shard: int,
                      offset: int, data: bytes, reason: str,
                      action: str = "quarantined") -> None:
        mk = (path, int(offset))
        if mk in self._seen_corrupt:
            return
        self._seen_corrupt.add(mk)
        integrity.quarantine(path, kind, offset, data, reason,
                             action=action)
        key = (dataset, shard)
        self._quarantined[key] = self._quarantined.get(key, 0) + 1

    def quarantined_records(self, dataset: str, shard: int) -> int:
        return self._quarantined.get((dataset, shard), 0)

    def _scan_log(self, path: str, kind: str, read_point: str,
                  probe, dataset: str, shard: int,
                  truncate_tail: bool = True
                  ) -> Tuple[bytes, List[integrity.ScanRecord]]:
        """Load + classify one log. Corrupt regions quarantine (deduped
        across re-scans); a non-clean tail is truncated when the caller
        owns the writer side (a corrupt tail quarantines first — the
        truncate must never destroy the only copy of the bad bytes)."""
        if not os.path.exists(path):
            return b"", []
        with open(path, "rb") as f:
            buf = f.read()
        buf = chaos.filter_read(read_point, buf, dataset=dataset,
                                shard=shard)
        res = integrity.scan_buffer(buf, probe=probe)
        for reg in res.corrupt:
            self._note_corrupt(path, kind, dataset, shard, reg.offset,
                               buf[reg.offset:reg.offset + reg.length],
                               reg.reason)
        if res.tail_state != "clean" and truncate_tail:
            if res.tail_state == "corrupt":
                self._note_corrupt(path, kind, dataset, shard,
                                   res.tail_off, buf[res.tail_off:],
                                   res.tail_reason,
                                   action="quarantined-truncated")
            os.truncate(path, res.consumed)
        return buf, res.records

    # -- chunks (TimeSeriesChunksTable) ------------------------------------
    def write_chunks(self, dataset, shard, part_key, chunks) -> None:
        if not chunks:
            return
        path = self._chunks_path(dataset, shard)
        # building the index first also truncates any torn tail left by a
        # crash, so appends land at a valid record boundary (otherwise
        # everything after the torn bytes would be unreachable on replay)
        idx = self._ensure_chunk_index(dataset, shard)
        staged: List[Tuple[int, int]] = []
        f = open(path, "ab")
        start = f.tell()
        try:
            for c in chunks:
                off = f.tell()
                rec = _encode_chunk_record(part_key, c.id, c.num_rows,
                                           c.start_ts, c.end_ts, c.vectors)
                chaos.write("chunklog.write", f, integrity.encode_frame(rec),
                            dataset=dataset, shard=shard)
                staged.append((c.id, off))
            f.flush()
            os.fsync(f.fileno())
        except OSError:
            # all-or-nothing batch: flush whatever the buffer holds,
            # then cut the file back so no torn bytes stay mid-log
            try:
                f.close()
            except OSError:
                pass
            os.truncate(path, start)
            raise
        f.close()
        for cid, off in staged:
            idx.setdefault(part_key, {})[cid] = off

    def _iter_chunks(self, dataset, shard, offsets: Sequence[int]
                     ) -> Iterator[PersistedChunk]:
        """Read chunk records at known offsets (from _ensure_chunk_index,
        which validated framing). Every framed record is CRC-verified
        AGAIN here — the ODP read path never serves bytes that rotted
        between index build and fetch; a failing record quarantines and
        is skipped, never returned."""
        path = self._chunks_path(dataset, shard)
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            for off in offsets:
                f.seek(off)
                hdr = f.read(_CHUNK_HDR.size)
                if len(hdr) < 2:
                    return
                (magic,) = struct.unpack_from("<H", hdr, 0)
                if magic == integrity.FRAME_MAGIC:
                    if len(hdr) < integrity.FRAME_HDR.size:
                        return
                    plen = integrity.FRAME_HDR.unpack_from(hdr, 0)[3]
                    total = integrity.FRAME_HDR.size + plen
                    if plen > integrity.MAX_PAYLOAD:
                        self._note_corrupt(
                            path, "chunklog", dataset, shard, off, hdr,
                            f"implausible frame length {plen}",
                            action="skipped")
                        continue
                    full = (hdr + f.read(max(0, total - len(hdr))))[:total]
                    full = chaos.filter_read("chunklog.read", full,
                                             dataset=dataset, shard=shard,
                                             offset=off)
                    try:
                        payload, _ = integrity.decode_frame(full)
                        if payload is None:
                            raise integrity.FrameError("truncated frame")
                        yield _decode_chunk_record(payload)
                    except (integrity.FrameError, ValueError,
                            struct.error) as e:
                        self._note_corrupt(
                            path, "chunklog", dataset, shard, off, full,
                            f"read-time verification failed: {e}",
                            action="skipped")
                    continue
                # legacy unframed record (no CRC: struct checks only)
                if len(hdr) < _CHUNK_HDR.size:
                    return
                magic, pk_len, ncols, _, cid, nrows, st, en = \
                    _CHUNK_HDR.unpack(hdr)
                if magic != _CHUNK_MAGIC:
                    self._note_corrupt(path, "chunklog", dataset, shard,
                                       off, hdr,
                                       f"bad chunk record magic "
                                       f"0x{magic:04x}", action="skipped")
                    continue
                rest = f.read(pk_len + 4 * ncols)
                if len(rest) < pk_len + 4 * ncols:
                    return
                try:
                    vec_lens = struct.unpack(f"<{ncols}i", rest[pk_len:])
                except struct.error:
                    self._note_corrupt(path, "chunklog", dataset, shard,
                                       off, hdr + rest,
                                       "undecodable vector lengths",
                                       action="skipped")
                    continue
                vbytes = f.read(sum(max(0, vl) for vl in vec_lens))
                full = chaos.filter_read("chunklog.read",
                                         hdr + rest + vbytes,
                                         dataset=dataset, shard=shard,
                                         offset=off)
                try:
                    yield _decode_chunk_record(full)
                except (ValueError, struct.error) as e:
                    self._note_corrupt(
                        path, "chunklog", dataset, shard, off, full,
                        f"read-time decode failed: {e}", action="skipped")

    def _ensure_chunk_index(self, dataset, shard
                            ) -> Dict[bytes, Dict[int, int]]:
        """Scan the log once, building {pk: {chunk_id: offset}}. The
        scan verifies every frame, quarantines corrupt regions (the
        index simply omits them — they can never reach a query), and
        truncates the tail to the last valid boundary so subsequent
        appends stay reachable."""
        key = (dataset, shard)
        idx = self._chunk_index.get(key)
        if idx is not None:
            return idx
        idx = {}
        path = self._chunks_path(dataset, shard)
        buf, records = self._scan_log(path, "chunklog", "chunklog.read",
                                      legacy_chunk_probe, dataset, shard)
        for rec in records:
            payload = buf[rec.payload_off:rec.payload_off + rec.payload_len]
            try:
                chunk = _decode_chunk_record(payload)
            except (ValueError, struct.error) as e:
                self._note_corrupt(path, "chunklog", dataset, shard,
                                   rec.offset,
                                   buf[rec.offset:rec.offset + rec.length],
                                   f"undecodable chunk record: {e}")
                continue
            idx.setdefault(chunk.part_key, {})[chunk.chunk_id] = rec.offset
        self._chunk_index[key] = idx
        return idx

    def read_chunks(self, dataset, shard, part_key, start_ts=0,
                    end_ts=1 << 62) -> List[PersistedChunk]:
        """ODP read path (readRawPartitions, CassandraColumnStore.scala:699).
        First call per shard builds an in-memory offset index (one scan).
        Duplicate appends of the same chunk_id (crash replay, re-run batch
        jobs) dedupe via the index — last record wins, like a C* upsert."""
        idx = self._ensure_chunk_index(dataset, shard)
        offs = sorted(idx.get(part_key, {}).values())
        out = [c for c in self._iter_chunks(dataset, shard, offs)
               if c.end_ts >= start_ts and c.start_ts <= end_ts]
        out.sort(key=lambda c: c.start_ts)
        return out

    # -- partkeys (PartitionKeysTable) -------------------------------------
    def _validate_pk_log(self, dataset, shard) -> None:
        """Scan partkeys.log once: quarantine corrupt regions, truncate
        the tail so appends stay reachable."""
        key = (dataset, shard)
        if key in self._pk_validated:
            return
        self._scan_log(self._pk_path(dataset, shard), "partkeys",
                       "partkeys.read", legacy_pk_probe, dataset, shard)
        self._pk_validated.add(key)

    def write_part_keys(self, dataset, shard, entries) -> None:
        if not entries:
            return
        self._validate_pk_log(dataset, shard)
        path = self._pk_path(dataset, shard)
        f = open(path, "ab")
        start = f.tell()
        try:
            for e in entries:
                chaos.write("partkeys.write", f,
                            integrity.encode_frame(_encode_pk_record(e)),
                            dataset=dataset, shard=shard)
            f.flush()
            os.fsync(f.fileno())
        except OSError:
            try:
                f.close()
            except OSError:
                pass
            os.truncate(path, start)
            raise
        f.close()

    def scan_part_keys(self, dataset, shard) -> Iterator[PartKeyEntry]:
        """Latest entry wins per partkey (upsert-by-append). Corrupt
        records quarantine and are skipped — a damaged entry never
        resurrects a series nor hides a healthy one behind a halt."""
        self._validate_pk_log(dataset, shard)
        path = self._pk_path(dataset, shard)
        # no tail truncate on the read path: validate above owns that
        buf, records = self._scan_log(path, "partkeys", "partkeys.read",
                                      legacy_pk_probe, dataset, shard,
                                      truncate_tail=False)
        latest: Dict[bytes, PartKeyEntry] = {}
        for rec in records:
            payload = buf[rec.payload_off:rec.payload_off + rec.payload_len]
            try:
                e = _decode_pk_record(payload)
            except (ValueError, struct.error) as err:
                self._note_corrupt(path, "partkeys", dataset, shard,
                                   rec.offset,
                                   buf[rec.offset:rec.offset + rec.length],
                                   f"undecodable partkey record: {err}")
                continue
            latest[e.part_key] = e
        return iter(latest.values())

    def delete_part_keys(self, dataset, shard, part_keys) -> None:
        """Compact both logs without the doomed series (the append-only
        analogue of the reference cardbuster's Cassandra deletes). One
        writer per shard is the store's standing contract, so the
        rewrite is safe against concurrent appends. Survivors are
        rewritten FRAMED — compaction migrates legacy files to the
        checksummed format."""
        doomed = set(part_keys)
        if not doomed:
            return
        # part keys: rewrite keeping the LATEST entry per surviving key
        self._validate_pk_log(dataset, shard)
        pk_path = self._pk_path(dataset, shard)
        survivors = [e for e in self.scan_part_keys(dataset, shard)
                     if e.part_key not in doomed]
        tmp = pk_path + ".tmp"
        with open(tmp, "wb") as f:
            for e in survivors:
                f.write(integrity.encode_frame(_encode_pk_record(e)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, pk_path)
        # chunks: rewrite the log without the doomed keys' records
        idx = self._ensure_chunk_index(dataset, shard)
        ch_path = self._chunks_path(dataset, shard)
        keep_offs = sorted(off for pk, chunks in idx.items()
                           if pk not in doomed
                           for off in chunks.values())
        tmp = ch_path + ".tmp"
        with open(tmp, "wb") as f:
            for c in self._iter_chunks(dataset, shard, keep_offs):
                f.write(integrity.encode_frame(_encode_chunk_record(
                    c.part_key, c.chunk_id, c.num_rows, c.start_ts,
                    c.end_ts, c.vectors)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, ch_path)
        self._chunk_index.pop((dataset, shard), None)
        # the rewritten files have fresh offsets: drop stale dedup marks
        self._seen_corrupt = {mk for mk in self._seen_corrupt
                              if mk[0] not in (pk_path, ch_path)}

    # -- checkpoints (CheckpointTable.scala:26) ----------------------------
    def write_checkpoint(self, dataset, shard, group, offset) -> None:
        path = self._ckpt_path(dataset, shard)
        cur = self.read_checkpoints(dataset, shard)
        cur[group] = offset
        data = integrity.encode_checkpoint(
            {str(k): v for k, v in cur.items()})
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                chaos.write("checkpoint.write", f, data,
                            dataset=dataset, shard=shard)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            # the atomic-replace never ran: the live checkpoint is
            # intact, just drop the partial temp file
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.replace(tmp, path)
        self._seen_corrupt.discard((path, 0))

    def read_checkpoints(self, dataset, shard) -> Dict[int, int]:
        path = self._ckpt_path(dataset, shard)
        if not os.path.exists(path):
            return {}
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return {}
        raw = chaos.filter_read("checkpoint.read", raw, dataset=dataset,
                                shard=shard)
        try:
            data, _ = integrity.decode_checkpoint(raw)
            return {int(k): int(v) for k, v in data.items()}
        except (integrity.FrameError, TypeError, ValueError) as e:
            # a damaged checkpoint quarantines and reads as empty:
            # replay restarts from offset 0, which is safe (chunk and
            # partkey appends upsert; re-ingest is idempotent)
            self._note_corrupt(path, "checkpoint", dataset, shard, 0,
                               raw, f"checkpoint verification failed: {e}")
            return {}
