"""ColumnStore: durable chunk + partkey + checkpoint persistence.

The pluggable boundary mirrors the reference's ChunkSink/RawChunkSource
(store/ChunkSink.scala; store/ChunkSource.scala:25) and the Cassandra
implementation's tables (cassandra/columnstore/CassandraColumnStore.scala:54:
TimeSeriesChunksTable, PartitionKeysTable; metastore CheckpointTable.scala:26)
— but the storage engine is TPU-host-native: encoded chunks are already
immutable compressed byte vectors (the interchange format), so persistence is
append-only framed logs per shard, fsync'd per flush group. No external
database is required; an object-store or Cassandra client can implement the
same four-method API.

Layout under root:
    <dataset>/shard=<n>/chunks.log      framed: partkey + chunk meta + vectors
    <dataset>/shard=<n>/partkeys.log    framed: partkey + startTime + endTime
    <dataset>/shard=<n>/checkpoints.json   {group: offset} (atomic replace)

Log framing is little-endian struct records with a magic + length prefix so
readers can skip torn tails after a crash (the reference gets atomicity from
Cassandra; here a torn final record is simply ignored — the checkpoint
watermark re-ingests anything after it).
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

_CHUNK_MAGIC = 0xC4A2
_PK_MAGIC = 0xBE11

# chunk record header: magic u16, pk_len u16, ncols u16, pad u16,
#                      chunk_id i64, num_rows i32, start i64, end i64
_CHUNK_HDR = struct.Struct("<HHHHqiqq")
# partkey record: magic u16, pk_len u16, start i64, end i64
_PK_HDR = struct.Struct("<HHqq")


@dataclass(frozen=True)
class PartKeyEntry:
    """One persisted partkey (PartitionKeysTable row)."""
    part_key: bytes
    start_ts: int
    end_ts: int


@dataclass(frozen=True)
class PersistedChunk:
    """One persisted chunk set (TimeSeriesChunksTable row)."""
    part_key: bytes
    chunk_id: int
    num_rows: int
    start_ts: int
    end_ts: int
    vectors: Tuple[bytes, ...]


class ColumnStore:
    """Abstract persistence API (ChunkSink + RawChunkSource + checkpoints)."""

    def write_chunks(self, dataset: str, shard: int, part_key: bytes,
                     chunks: Sequence) -> None:
        raise NotImplementedError

    def read_chunks(self, dataset: str, shard: int, part_key: bytes,
                    start_ts: int = 0, end_ts: int = 1 << 62
                    ) -> List[PersistedChunk]:
        raise NotImplementedError

    def write_part_keys(self, dataset: str, shard: int,
                        entries: Sequence[PartKeyEntry]) -> None:
        raise NotImplementedError

    def scan_part_keys(self, dataset: str, shard: int
                       ) -> Iterator[PartKeyEntry]:
        raise NotImplementedError

    def write_checkpoint(self, dataset: str, shard: int, group: int,
                         offset: int) -> None:
        raise NotImplementedError

    def read_checkpoints(self, dataset: str, shard: int) -> Dict[int, int]:
        raise NotImplementedError

    def delete_part_keys(self, dataset: str, shard: int,
                         part_keys: Sequence[bytes]) -> None:
        """Remove series (index entries + chunks) — the cardinality
        buster's primitive."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullColumnStore(ColumnStore):
    """No-op sink (store/ChunkSink.scala:126 NullColumnStore): memstore-only
    deployments and tests."""

    def write_chunks(self, dataset, shard, part_key, chunks) -> None:
        pass

    def read_chunks(self, dataset, shard, part_key, start_ts=0,
                    end_ts=1 << 62):
        return []

    def write_part_keys(self, dataset, shard, entries) -> None:
        pass

    def scan_part_keys(self, dataset, shard):
        return iter(())

    def write_checkpoint(self, dataset, shard, group, offset) -> None:
        pass

    def read_checkpoints(self, dataset, shard):
        return {}

    def delete_part_keys(self, dataset, shard, part_keys) -> None:
        pass


class FlatFileColumnStore(ColumnStore):
    """Append-only framed-log store. One writer per shard (the ingest
    thread), readers tolerate torn tails."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # (dataset, shard) -> {part_key: {chunk_id: file offset}} lazy ODP
        # index; keyed by chunk_id so replayed/re-run appends upsert (last
        # record wins), matching the reference's Cassandra upsert semantics
        self._chunk_index: Dict[Tuple[str, int],
                                Dict[bytes, Dict[int, int]]] = {}
        # (dataset, shard) sets whose partkeys.log tail has been validated
        self._pk_validated: set = set()

    # -- paths ------------------------------------------------------------
    def _shard_dir(self, dataset: str, shard: int) -> str:
        d = os.path.join(self.root, dataset, f"shard={shard}")
        os.makedirs(d, exist_ok=True)
        return d

    def _chunks_path(self, dataset: str, shard: int) -> str:
        return os.path.join(self._shard_dir(dataset, shard), "chunks.log")

    def _pk_path(self, dataset: str, shard: int) -> str:
        return os.path.join(self._shard_dir(dataset, shard), "partkeys.log")

    def _ckpt_path(self, dataset: str, shard: int) -> str:
        return os.path.join(self._shard_dir(dataset, shard),
                            "checkpoints.json")

    # -- chunks (TimeSeriesChunksTable) ------------------------------------
    def write_chunks(self, dataset, shard, part_key, chunks) -> None:
        if not chunks:
            return
        path = self._chunks_path(dataset, shard)
        # building the index first also truncates any torn tail left by a
        # crash, so appends land at a valid record boundary (otherwise
        # everything after the torn bytes would be unreachable on replay)
        idx = self._ensure_chunk_index(dataset, shard)
        with open(path, "ab") as f:
            for c in chunks:
                off = f.tell()
                vec_lens = struct.pack(f"<{len(c.vectors)}i",
                                       *[len(v) for v in c.vectors])
                f.write(_CHUNK_HDR.pack(_CHUNK_MAGIC, len(part_key),
                                        len(c.vectors), 0, c.id, c.num_rows,
                                        c.start_ts, c.end_ts))
                f.write(part_key)
                f.write(vec_lens)
                for v in c.vectors:
                    f.write(v)
                idx.setdefault(part_key, {})[c.id] = off
            f.flush()
            os.fsync(f.fileno())

    def _iter_chunks(self, dataset, shard, offsets: Sequence[int]
                     ) -> Iterator[PersistedChunk]:
        """Read chunk records at known offsets (from _ensure_chunk_index,
        which validated framing)."""
        path = self._chunks_path(dataset, shard)
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            for off in offsets:
                f.seek(off)
                hdr = f.read(_CHUNK_HDR.size)
                if len(hdr) < _CHUNK_HDR.size:
                    return
                magic, pk_len, ncols, _, cid, nrows, st, en = \
                    _CHUNK_HDR.unpack(hdr)
                if magic != _CHUNK_MAGIC:
                    return                       # torn/corrupt tail
                pk = f.read(pk_len)
                lens_buf = f.read(4 * ncols)
                if len(pk) < pk_len or len(lens_buf) < 4 * ncols:
                    return
                vec_lens = struct.unpack(f"<{ncols}i", lens_buf)
                vecs = []
                for vl in vec_lens:
                    b = f.read(vl)
                    if len(b) < vl:
                        return
                    vecs.append(b)
                yield PersistedChunk(pk, cid, nrows, st, en, tuple(vecs))

    def _ensure_chunk_index(self, dataset, shard
                            ) -> Dict[bytes, Dict[int, int]]:
        """Scan the log once, building {pk: {chunk_id: offset}}.  The scan
        also truncates any torn tail to the last valid record boundary so
        subsequent appends stay reachable."""
        key = (dataset, shard)
        idx = self._chunk_index.get(key)
        if idx is not None:
            return idx
        idx = {}
        path = self._chunks_path(dataset, shard)
        if os.path.exists(path):
            valid_end = 0
            with open(path, "rb") as f:
                size = os.fstat(f.fileno()).st_size
                while True:
                    off = f.tell()
                    hdr = f.read(_CHUNK_HDR.size)
                    if len(hdr) < _CHUNK_HDR.size:
                        break
                    magic, pk_len, ncols, _, cid, *_rest = \
                        _CHUNK_HDR.unpack(hdr)
                    if magic != _CHUNK_MAGIC:
                        break
                    pk = f.read(pk_len)
                    lens_buf = f.read(4 * ncols)
                    if len(pk) < pk_len or len(lens_buf) < 4 * ncols:
                        break
                    skip = sum(struct.unpack(f"<{ncols}i", lens_buf))
                    if f.tell() + skip > size:
                        break
                    idx.setdefault(pk, {})[cid] = off
                    f.seek(skip, os.SEEK_CUR)
                    valid_end = f.tell()
            if valid_end < os.path.getsize(path):
                os.truncate(path, valid_end)
        self._chunk_index[key] = idx
        return idx

    def read_chunks(self, dataset, shard, part_key, start_ts=0,
                    end_ts=1 << 62) -> List[PersistedChunk]:
        """ODP read path (readRawPartitions, CassandraColumnStore.scala:699).
        First call per shard builds an in-memory offset index (one scan).
        Duplicate appends of the same chunk_id (crash replay, re-run batch
        jobs) dedupe via the index — last record wins, like a C* upsert."""
        idx = self._ensure_chunk_index(dataset, shard)
        offs = sorted(idx.get(part_key, {}).values())
        out = [c for c in self._iter_chunks(dataset, shard, offs)
               if c.end_ts >= start_ts and c.start_ts <= end_ts]
        out.sort(key=lambda c: c.start_ts)
        return out

    # -- partkeys (PartitionKeysTable) -------------------------------------
    def _validate_pk_log(self, dataset, shard) -> None:
        """Truncate a torn partkeys.log tail so appends stay reachable."""
        key = (dataset, shard)
        if key in self._pk_validated:
            return
        path = self._pk_path(dataset, shard)
        if os.path.exists(path):
            valid_end = 0
            with open(path, "rb") as f:
                while True:
                    hdr = f.read(_PK_HDR.size)
                    if len(hdr) < _PK_HDR.size:
                        break
                    magic, pk_len, _, _ = _PK_HDR.unpack(hdr)
                    if magic != _PK_MAGIC:
                        break
                    if len(f.read(pk_len)) < pk_len:
                        break
                    valid_end = f.tell()
            if valid_end < os.path.getsize(path):
                os.truncate(path, valid_end)
        self._pk_validated.add(key)

    def write_part_keys(self, dataset, shard, entries) -> None:
        if not entries:
            return
        self._validate_pk_log(dataset, shard)
        path = self._pk_path(dataset, shard)
        with open(path, "ab") as f:
            for e in entries:
                f.write(_PK_HDR.pack(_PK_MAGIC, len(e.part_key),
                                     e.start_ts, e.end_ts))
                f.write(e.part_key)
            f.flush()
            os.fsync(f.fileno())

    def scan_part_keys(self, dataset, shard) -> Iterator[PartKeyEntry]:
        """Latest entry wins per partkey (upsert-by-append)."""
        path = self._pk_path(dataset, shard)
        latest: Dict[bytes, PartKeyEntry] = {}
        if os.path.exists(path):
            with open(path, "rb") as f:
                while True:
                    hdr = f.read(_PK_HDR.size)
                    if len(hdr) < _PK_HDR.size:
                        break
                    magic, pk_len, st, en = _PK_HDR.unpack(hdr)
                    if magic != _PK_MAGIC:
                        break
                    pk = f.read(pk_len)
                    if len(pk) < pk_len:
                        break
                    latest[pk] = PartKeyEntry(pk, st, en)
        return iter(latest.values())

    def delete_part_keys(self, dataset, shard, part_keys) -> None:
        """Compact both logs without the doomed series (the append-only
        analogue of the reference cardbuster's Cassandra deletes). One
        writer per shard is the store's standing contract, so the
        rewrite is safe against concurrent appends."""
        doomed = set(part_keys)
        if not doomed:
            return
        # part keys: rewrite keeping the LATEST entry per surviving key
        self._validate_pk_log(dataset, shard)
        pk_path = self._pk_path(dataset, shard)
        survivors = [e for e in self.scan_part_keys(dataset, shard)
                     if e.part_key not in doomed]
        tmp = pk_path + ".tmp"
        with open(tmp, "wb") as f:
            for e in survivors:
                f.write(_PK_HDR.pack(_PK_MAGIC, len(e.part_key),
                                     e.start_ts, e.end_ts))
                f.write(e.part_key)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, pk_path)
        # chunks: rewrite the log without the doomed keys' records
        idx = self._ensure_chunk_index(dataset, shard)
        ch_path = self._chunks_path(dataset, shard)
        keep_offs = sorted(off for pk, chunks in idx.items()
                           if pk not in doomed
                           for off in chunks.values())
        tmp = ch_path + ".tmp"
        with open(tmp, "wb") as f:
            for c in self._iter_chunks(dataset, shard, keep_offs):
                vec_lens = struct.pack(f"<{len(c.vectors)}i",
                                       *[len(v) for v in c.vectors])
                f.write(_CHUNK_HDR.pack(
                    _CHUNK_MAGIC, len(c.part_key), len(c.vectors), 0,
                    c.chunk_id, c.num_rows, c.start_ts, c.end_ts))
                f.write(c.part_key)
                f.write(vec_lens)
                for v in c.vectors:
                    f.write(v)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, ch_path)
        self._chunk_index.pop((dataset, shard), None)

    # -- checkpoints (CheckpointTable.scala:26) ----------------------------
    def write_checkpoint(self, dataset, shard, group, offset) -> None:
        path = self._ckpt_path(dataset, shard)
        cur = self.read_checkpoints(dataset, shard)
        cur[group] = offset
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({str(k): v for k, v in cur.items()}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def read_checkpoints(self, dataset, shard) -> Dict[int, int]:
        path = self._ckpt_path(dataset, shard)
        if not os.path.exists(path):
            return {}
        try:
            with open(path) as f:
                return {int(k): int(v) for k, v in json.load(f).items()}
        except (json.JSONDecodeError, OSError):
            return {}
