"""Storage-integrity rail: per-record checksummed framing, corruption
scanning/resync, and the quarantine sidecar — shared by the WAL
(ingest/stream.py), the chunk/partkey logs and checkpoint files
(store/columnstore.py), and the offline checker (filodb_tpu/fsck.py).

The reference delegates durable-tier atomicity and integrity to
Cassandra; our local durable tier validated records only by struct
plausibility, so a flipped bit mid-log silently stopped replay
indexing and lost every record after it. This module makes corruption
a detected, contained, first-class event:

  * **Frame format** (version 1): every record a writer appends is
    wrapped in a 12-byte little-endian header ::

        magic u16 | version u8 | flags u8 | payload_len u32 | crc u32

    The CRC covers header bytes [2:8] (version, flags, payload_len)
    plus the payload, so a flip in the length field fails the check
    exactly like a flip in the data. ``flags`` bit 0 records the
    checksum algorithm: 0 = CRC32C (Castagnoli — used when a native
    implementation is importable), 1 = zlib CRC-32 (the stdlib
    fallback; C speed, no new dependency). Readers verify with
    whichever algorithm the frame declares, so files written on a host
    with native crc32c read back fine on one without (and vice versa).

  * **Format sniff**: the payload is the UNCHANGED legacy record
    encoding, and the frame magic is distinct from every legacy record
    magic — so a reader peeks one u16 at each record boundary and
    handles framed and unframed (pre-integrity) records in the same
    file. Existing stream dirs survive the upgrade with no migration.

  * **Scanner** (:func:`scan_buffer`): walks a byte range classifying
    it into records, corrupt regions (quarantine + resync at the next
    verifiable boundary), and a tail that is either clean, torn
    (incomplete record — the writer may still be appending; readers
    wait, takeover truncates) or corrupt (bad bytes with no resync
    point yet — more appends may reveal one, fsck can repair).

  * **Quarantine sidecar**: bad byte ranges are copied, before any
    truncation or skip, into a ``quarantine/`` directory next to the
    damaged file with a ``MANIFEST.jsonl`` recording file, offset,
    length and reason — so "skipped" never means "destroyed", and
    repair/forensics has the original bytes.

Every detection increments
``filodb_storage_corruption_total{file_kind,action}``, emits a
structured event on the global ring (obs/events.py) and a trace event
when a trace is active.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from filodb_tpu.obs import events as obs_events
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.obs import trace as obs_trace

FRAME_MAGIC = 0xF7A3          # distinct from 0xF10D / 0xC4A2 / 0xBE11
FRAME_VERSION = 1
FLAG_ZLIB_CRC = 0x01          # checksum algo: set = zlib CRC-32
FRAME_HDR = struct.Struct("<HBBII")
# a single record (one WAL container / one chunk set) is far below
# this; anything larger in a length field is a corrupt header, not a
# torn tail, so the scanner can resync instead of waiting forever
MAX_PAYLOAD = 64 << 20

_CORRUPTION_HELP = ("Corrupt records detected in durable files, by "
                    "file kind and action taken")
_QUARANTINE_BYTES_HELP = ("Bytes copied to quarantine/ sidecars, by "
                          "file kind")


# -- CRC32C (Castagnoli) ----------------------------------------------------
# native implementations are optional (the container may not ship one);
# the pure-Python table fallback below is only used to VERIFY frames
# that declare crc32c — the write path prefers zlib's C-speed CRC-32
# when no native crc32c is importable, recording the choice in flags.

def _load_native_crc32c() -> Optional[Callable[[bytes, int], int]]:
    try:
        import crc32c as _c           # pypi "crc32c"
        return lambda data, crc=0: _c.crc32c(data, crc)
    except ImportError:
        pass
    try:
        import google_crc32c as _g    # pypi "google-crc32c"
        return lambda data, crc=0: _g.extend(crc, data)
    except ImportError:
        return None


_native_crc32c = _load_native_crc32c()

_CRC32C_POLY = 0x82F63B78
_crc32c_table: List[int] = []


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    """Table-based pure-Python CRC32C — correctness fallback for
    verifying frames written with a native crc32c; never on the write
    path (zlib is the no-dependency fast default there)."""
    if not _crc32c_table:
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
            _crc32c_table.append(c)
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _crc32c_table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def crc32c(data: bytes, crc: int = 0) -> int:
    if _native_crc32c is not None:
        return _native_crc32c(data, crc) & 0xFFFFFFFF
    return _crc32c_py(data, crc)


WRITE_FLAGS = 0 if _native_crc32c is not None else FLAG_ZLIB_CRC
CRC_ALGO = "crc32c" if _native_crc32c is not None else "zlib-crc32"


def _crc_for_flags(flags: int, data: bytes) -> int:
    if flags & FLAG_ZLIB_CRC:
        return zlib.crc32(data) & 0xFFFFFFFF
    return crc32c(data)


# -- frame codec ------------------------------------------------------------

class FrameError(ValueError):
    """A frame that parsed structurally but failed verification."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def encode_frame(payload: bytes, flags: int = None) -> bytes:
    """Wrap one record's bytes in a checksummed frame."""
    if flags is None:
        flags = WRITE_FLAGS
    hdr_tail = struct.pack("<BBI", FRAME_VERSION, flags, len(payload))
    crc = _crc_for_flags(flags, hdr_tail + payload)
    return (struct.pack("<H", FRAME_MAGIC) + hdr_tail
            + struct.pack("<I", crc) + payload)


def decode_frame(buf: bytes, off: int = 0) -> Tuple[Optional[bytes], int]:
    """Decode + verify one frame at ``off``. Returns ``(payload,
    next_off)``, ``(None, off)`` when the frame is incomplete (torn /
    writer mid-append), or raises :class:`FrameError` on a bad
    version, an implausible length, or a checksum mismatch."""
    if off + FRAME_HDR.size > len(buf):
        return None, off
    magic, version, flags, plen, crc = FRAME_HDR.unpack_from(buf, off)
    if magic != FRAME_MAGIC:
        raise FrameError(f"bad frame magic 0x{magic:04x} at {off}")
    if version != FRAME_VERSION:
        raise FrameError(f"unsupported frame version {version} at {off}")
    if plen > MAX_PAYLOAD:
        raise FrameError(f"implausible frame length {plen} at {off}")
    end = off + FRAME_HDR.size + plen
    if end > len(buf):
        return None, off
    body = buf[off + 2:off + 8] + buf[off + FRAME_HDR.size:end]
    if _crc_for_flags(flags, body) != crc:
        raise FrameError(f"frame checksum mismatch at {off}")
    return buf[off + FRAME_HDR.size:end], end


# -- scanning ----------------------------------------------------------------
# legacy_probe(buf, off) -> record length when a plausible legacy
# (unframed) record starts at off; -1 when one starts but is cut off by
# the end of the buffer (torn); 0 when the bytes are not a legacy record.

LegacyProbe = Callable[[bytes, int], int]


@dataclass(frozen=True)
class ScanRecord:
    offset: int            # absolute offset (base + buffer position)
    length: int            # total bytes including any frame header
    payload_off: int       # absolute offset of the inner record bytes
    payload_len: int
    framed: bool


@dataclass(frozen=True)
class CorruptRegion:
    offset: int
    length: int
    reason: str


@dataclass
class ScanResult:
    records: List[ScanRecord] = field(default_factory=list)
    corrupt: List[CorruptRegion] = field(default_factory=list)
    consumed: int = 0            # bytes classified (resume/append point)
    tail_state: str = "clean"    # "clean" | "torn" | "corrupt"
    tail_off: int = 0            # absolute offset where the tail starts
    tail_reason: str = ""


def _frame_at(buf: bytes, off: int) -> int:
    """Length of a fully verified frame at ``off``; -1 torn; 0 not a
    valid frame (resync-candidate rejection)."""
    try:
        payload, end = decode_frame(buf, off)
    except FrameError:
        return 0
    if payload is None:
        return -1
    return end - off


def _resync(buf: bytes, start: int, probe: Optional[LegacyProbe]) -> int:
    """First offset > ``start`` where a verified frame or a plausible
    legacy record begins, or -1 when none exists in the buffer."""
    q = start + 1
    limit = len(buf) - 1
    while q < limit:
        (magic,) = struct.unpack_from("<H", buf, q)
        if magic == FRAME_MAGIC and _frame_at(buf, q) != 0:
            return q
        if probe is not None and probe(buf, q) != 0:
            return q
        q += 1
    return -1


def scan_buffer(buf: bytes, probe: Optional[LegacyProbe] = None,
                base: int = 0) -> ScanResult:
    """Classify ``buf`` (which starts at file offset ``base``) into
    records, corrupt regions, and the tail state. Mixed framed/legacy
    files are handled per record boundary via the magic sniff."""
    res = ScanResult()
    p = 0
    n = len(buf)
    while p < n:
        if p + 2 > n:
            res.tail_state = "torn"
            res.tail_off = base + p
            res.tail_reason = "trailing partial record magic"
            break
        (magic,) = struct.unpack_from("<H", buf, p)
        if magic == FRAME_MAGIC:
            try:
                payload, end = decode_frame(buf, p)
            except FrameError as e:
                payload, end, err = None, p, e.reason
            else:
                err = ""
            if err == "" and payload is None:
                res.tail_state = "torn"
                res.tail_off = base + p
                res.tail_reason = "incomplete frame (writer mid-append?)"
                break
            if err == "":
                res.records.append(ScanRecord(
                    base + p, end - p, base + p + FRAME_HDR.size,
                    len(payload), True))
                p = end
                continue
            if err.startswith("frame checksum mismatch"):
                # header parsed and the frame is complete: trust the
                # declared length for the quarantine span — the next
                # boundary is verified independently below anyway
                plen = FRAME_HDR.unpack_from(buf, p)[3]
                end = p + FRAME_HDR.size + plen
                res.corrupt.append(CorruptRegion(base + p, end - p, err))
                p = end
                continue
            reason = err
        elif probe is not None:
            plen = probe(buf, p)
            if plen > 0:
                res.records.append(ScanRecord(
                    base + p, plen, base + p, plen, False))
                p += plen
                continue
            if plen == -1:
                res.tail_state = "torn"
                res.tail_off = base + p
                res.tail_reason = ("incomplete legacy record "
                                   "(writer mid-append?)")
                break
            reason = f"unrecognized record magic 0x{magic:04x}"
        else:
            reason = f"unrecognized record magic 0x{magic:04x}"
        q = _resync(buf, p, probe)
        if q < 0:
            res.tail_state = "corrupt"
            res.tail_off = base + p
            res.tail_reason = reason + " (no resync point in file)"
            break
        res.corrupt.append(CorruptRegion(base + p, q - p, reason))
        p = q
    else:
        res.tail_off = base + n
    if res.tail_state == "clean":
        res.consumed = n
    else:
        res.consumed = res.tail_off - base
    return res


# -- quarantine sidecar ------------------------------------------------------

def quarantine_dir(path: str) -> str:
    return os.path.join(os.path.dirname(os.path.abspath(path)),
                        "quarantine")


def quarantine(path: str, file_kind: str, offset: int, data: bytes,
               reason: str, action: str = "quarantined") -> str:
    """Copy a bad byte range to the ``quarantine/`` sidecar next to
    ``path``, append a MANIFEST.jsonl entry, and emit the corruption
    metric + structured event + trace event. Returns the sidecar file
    path. Never raises: containment must not take down the caller
    (a full disk while quarantining still records the event)."""
    import time as _time
    qpath = ""
    try:
        qdir = quarantine_dir(path)
        os.makedirs(qdir, exist_ok=True)
        base = os.path.basename(path)
        qpath = os.path.join(qdir, f"{base}.{offset}.bad")
        with open(qpath, "wb") as f:
            f.write(data)
        entry = {"file": os.path.abspath(path), "kind": file_kind,
                 "offset": int(offset), "length": len(data),
                 "reason": reason, "action": action,
                 "time": _time.time()}
        with open(os.path.join(qdir, "MANIFEST.jsonl"), "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        qpath = ""
    record_corruption(file_kind, path, offset, len(data), reason,
                      action=action)
    obs_metrics.GLOBAL_REGISTRY.counter(
        "filodb_storage_quarantined_bytes_total",
        _QUARANTINE_BYTES_HELP).inc(len(data), file_kind=file_kind)
    return qpath


def record_corruption(file_kind: str, path: str, offset: int,
                      length: int, reason: str,
                      action: str = "detected") -> None:
    """Metric + structured event + trace event for one detection —
    the no-sidecar variant (suspected corrupt tails, read-time CRC
    failures whose bytes a separate path quarantines)."""
    obs_metrics.GLOBAL_REGISTRY.counter(
        "filodb_storage_corruption_total", _CORRUPTION_HELP).inc(
        file_kind=file_kind, action=action)
    obs_events.emit("corruption", file_kind=file_kind,
                    file=os.path.abspath(path), offset=int(offset),
                    length=int(length), reason=reason, action=action)
    obs_trace.event("storage.corruption", file_kind=file_kind,
                    offset=int(offset), reason=reason, action=action)


# -- checkpoint envelope -----------------------------------------------------
# checkpoints are small JSON documents, not append-only logs: the
# integrity envelope carries the CRC of the canonical data encoding.

def encode_checkpoint(data: dict) -> bytes:
    canon = json.dumps(data, sort_keys=True, separators=(",", ":"))
    crc = _crc_for_flags(WRITE_FLAGS, canon.encode())
    return json.dumps({"v": 1, "algo": CRC_ALGO,
                       "crc": f"{crc:08x}", "data": data}).encode()


def decode_checkpoint(raw: bytes) -> Tuple[dict, bool]:
    """Parse + verify a checkpoint document. Returns ``(data,
    framed)`` — framed False for legacy bare-dict files (accepted
    unchanged). Raises :class:`FrameError` on damage."""
    try:
        doc = json.loads(raw)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise FrameError(f"checkpoint is not valid JSON: {e}") from e
    if not isinstance(doc, dict):
        raise FrameError("checkpoint is not a JSON object")
    if "crc" not in doc or "data" not in doc:
        return doc, False                       # legacy bare mapping
    data = doc.get("data")
    if not isinstance(data, dict):
        raise FrameError("checkpoint envelope has no data object")
    canon = json.dumps(data, sort_keys=True, separators=(",", ":"))
    flags = 0 if doc.get("algo") == "crc32c" else FLAG_ZLIB_CRC
    crc = _crc_for_flags(flags, canon.encode())
    if f"{crc:08x}" != str(doc.get("crc")):
        raise FrameError("checkpoint checksum mismatch")
    return data, True
