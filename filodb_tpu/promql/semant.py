"""promlint: semantic analysis of the PromQL surface.

graftlint (PRs 2/7/10) made the *Python* source statically safe; this
module does the same for the *query language*. It runs over the parsed
AST (:mod:`filodb_tpu.promql.parser` — the exact grammar the engine
evaluates, no second parser to drift) and emits spanned
:class:`Diagnostic` findings in three families:

* **Type & schema checking** — every node gets a type from
  ``{scalar, string, instant vector, range vector}``; range functions
  require range-vector arguments, aggregations require instant
  vectors, subquery inners must be instant vectors, binary-operator
  operand rules and ``bool``-modifier placement are enforced.
  Counter/gauge semantics resolve through a :class:`MetricSchemas`
  (ingest-schema suffix heuristic + explicit ``schema:`` declarations
  from rule files): ``rate()`` on an explicitly gauge-schema metric is
  an ERROR; ``delta()``/``deriv()`` on a counter is a WARNING.

* **Label dataflow** — the statically-known label set propagates
  through ``by``/``without`` aggregations and ``on``/``ignoring``/
  ``group_*`` vector matching. Matching on a label an upstream
  aggregation provably dropped is an ERROR; a provably-ambiguous
  many-to-many match with no ``group_*`` modifier is a WARNING.

* **Static cost bounds** — :func:`static_cost_bound` computes a
  per-node cost lattice over the LogicalPlan (steps x window/step
  overlap x cardinality upper bound via
  ``TagIndex.posting_upper_bound``) that is guaranteed to upper-bound
  :func:`filodb_tpu.query.qos.estimate_plan_cost`'s runtime price for
  the same plan — cross-checked in tests so the QoS admission price
  can never silently under-charge a plan shape.

Suppression: a query may carry an in-query pragma comment
``# promlint: disable=<rule>[,<rule>] (reason)`` — same syntax as
graftlint source pragmas; a reason string is required. The pragma
scopes to the whole expression (queries are single expressions).

The inversion that turns this from a linter into a correctness rail
lives next door: :mod:`filodb_tpu.promql.gen` generates random queries
*through these typing rules* (well-typed by construction) and
:mod:`filodb_tpu.promql.refeval` is the obviously-correct reference
those queries are differentially checked against.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from filodb_tpu.promql import parser as pp
from filodb_tpu.query.rangefn import (COUNTER_FUNCTIONS, GAUGE_FUNCTIONS,
                                      RANGE_FN_SCALAR_ARITY)

ERROR = "error"
WARNING = "warning"

# -- types ------------------------------------------------------------------

SCALAR = "scalar"
STRING = "string"
INSTANT = "instant vector"
RANGE = "range vector"

_METRIC_LABELS = ("_metric_", "__name__")

_PRAGMA_RE = re.compile(
    r"#\s*promlint:\s*disable=([\w\-,]+)\s*(?:\(([^)]*)\))?")


@dataclass(frozen=True)
class Diagnostic:
    """One semantic finding at a character span of the query text."""
    rule: str
    message: str
    pos: int = -1
    end: int = -1
    severity: str = ERROR

    def render(self, query: Optional[str] = None) -> str:
        loc = f"col {self.pos}" if self.pos >= 0 else "?"
        head = f"[{self.rule}] {self.message} (at {loc})"
        if query is None or self.pos < 0:
            return head
        width = max(1, min(self.end, len(query)) - self.pos)
        return f"{head}\n  {query}\n  {' ' * self.pos}{'^' * width}"


# -- rule catalog (mirrors graftlint's register_rule shape) -----------------

RULES: Dict[str, Tuple[str, str]] = {
    "promql-syntax": (ERROR, "the query does not parse"),
    "promql-range-arg": (ERROR,
                         "a range function requires a range-vector "
                         "argument ([window] selector or subquery)"),
    "promql-instant-arg": (ERROR,
                           "an aggregation / instant function requires "
                           "an instant-vector argument"),
    "promql-scalar-arg": (ERROR,
                          "a function parameter must be a scalar "
                          "(number) expression"),
    "promql-string-arg": (ERROR,
                          "a function parameter must be a string "
                          "literal"),
    "promql-arity": (ERROR, "wrong number of arguments to a function"),
    "promql-subquery-inner": (ERROR,
                              "a subquery body must be an instant-"
                              "vector expression"),
    "promql-top-level-range": (ERROR,
                               "a query must not evaluate to a bare "
                               "range vector; wrap it in a range "
                               "function"),
    "promql-bool-modifier": (ERROR,
                             "the bool modifier applies only to "
                             "comparison operators"),
    "promql-cmp-scalar-needs-bool": (ERROR,
                                     "a scalar-to-scalar comparison "
                                     "requires the bool modifier"),
    "promql-setop-operand": (ERROR,
                             "set operators (and/or/unless) require "
                             "instant-vector operands"),
    "promql-string-operand": (ERROR,
                              "binary operators do not apply to "
                              "string operands"),
    "promql-matching-with-scalar": (ERROR,
                                    "vector matching (on/ignoring/"
                                    "group_*) requires vector operands "
                                    "on both sides"),
    "promql-counter-fn-on-gauge": (ERROR,
                                   "rate()/increase()/irate()/resets() "
                                   "on a metric whose declared schema "
                                   "is gauge"),
    "promql-gauge-fn-on-counter": (WARNING,
                                   "delta()/idelta()/deriv() on a "
                                   "counter ignores resets — use the "
                                   "rate family"),
    "promql-match-on-dropped-label": (ERROR,
                                      "vector matching on a label an "
                                      "upstream aggregation provably "
                                      "dropped"),
    "promql-include-dropped-label": (WARNING,
                                     "group_left/right include-label "
                                     "provably dropped on the 'one' "
                                     "side"),
    "promql-many-to-many": (WARNING,
                            "vector match key provably cannot "
                            "distinguish series on either side; a "
                            "many-to-many match fails at eval time "
                            "without group_left/group_right"),
    "promql-by-absent-label": (WARNING,
                               "grouping by a label the inner "
                               "expression provably cannot carry"),
    "promql-unknown-function": (ERROR, "unknown function name"),
    "promql-pragma-no-reason": (ERROR,
                                "a promlint disable pragma must carry "
                                "a (reason) string"),
    "promql-pragma-unknown-rule": (ERROR,
                                   "a pragma disables a rule id that "
                                   "does not exist"),
}


# -- metric schema resolution ----------------------------------------------

_COUNTER_SUFFIX_RE = re.compile(r".*(_total|_count|_sum|_bucket)$")


class MetricSchemas:
    """Metric name -> ingest schema kind ("counter" | "gauge" |
    "histogram" | "delta-counter"). Explicit entries come from the rule
    file's ``schema:`` extension (PR 12) or the ingest schema registry;
    everything else falls back to the counter-suffix heuristic the
    selfmon rail uses (``*_total``/``_count``/``_sum``/``_bucket`` ->
    counter). ``resolve`` returns ``(kind | None, explicit)`` —
    severity policy keys off ``explicit`` (a heuristic guess must
    never hard-fail a query)."""

    def __init__(self, explicit: Optional[Dict[str, str]] = None):
        self.explicit = dict(explicit or {})

    def declare(self, metric: str, kind: str) -> None:
        self.explicit[metric] = kind

    @classmethod
    def from_rule_groups(cls, groups) -> "MetricSchemas":
        """Seed from parsed rule groups: every recording rule's output
        series gets its declared ``schema:`` (or stays heuristic)."""
        out = cls()
        for g in groups:
            for r in getattr(g, "rules", ()):
                if getattr(r, "kind", "") == "recording" and \
                        getattr(r, "schema", None):
                    out.declare(r.name, r.schema)
        return out

    def resolve(self, metric: Optional[str]
                ) -> Tuple[Optional[str], bool]:
        if not metric:
            return None, False
        kind = self.explicit.get(metric)
        if kind is not None:
            return kind, True
        if _COUNTER_SUFFIX_RE.match(metric):
            return "counter", False
        return None, False


# -- label dataflow lattice -------------------------------------------------

@dataclass(frozen=True)
class LabelInfo:
    """Statically-known label facts about a vector expression.

    ``upper`` is the CLOSED upper set of labels the result can carry
    (None = open — any label may appear). A ``by (a, b)`` aggregation
    closes the set to exactly {a, b}; ``without`` subtracts from
    whatever the inner carries. ``known`` is the set of labels that
    are definitely present-and-pinned (equality matchers)."""
    known: frozenset = frozenset()
    upper: Optional[frozenset] = None     # None = open world

    def may_carry(self, label: str) -> bool:
        return self.upper is None or label in self.upper

    def drop(self, labels) -> "LabelInfo":
        s = frozenset(labels)
        return LabelInfo(self.known - s,
                         None if self.upper is None else self.upper - s)

    def add(self, label: str) -> "LabelInfo":
        return LabelInfo(self.known,
                         None if self.upper is None
                         else self.upper | {label})


_OPEN = LabelInfo()

# -- function signature tables ---------------------------------------------

# instant functions: (scalar-arg count before vector?, scalars after)
_INSTANT_ARITY: Dict[str, Tuple[int, int]] = {
    # name -> (min extra scalars, max extra scalars) after the vector
    "clamp": (2, 2), "clamp_min": (1, 1), "clamp_max": (1, 1),
    "round": (0, 1),
}
# (scalar, vector) ordered instant functions all take exactly 2 args
_SCALAR_FIRST = set(pp.INSTANT_FN_SCALAR_FIRST)

_CMP_OPS = set(pp._CMP_OPS)
_SET_OPS = {"and", "or", "unless"}


def parse_pragmas(query: str
                  ) -> Tuple[frozenset, List[Diagnostic]]:
    """Disabled-rule ids from in-query ``# promlint:`` pragma comments,
    plus meta-diagnostics (missing reason / unknown rule id)."""
    disabled: set = set()
    diags: List[Diagnostic] = []
    for m in _PRAGMA_RE.finditer(query):
        ids = {x.strip() for x in m.group(1).split(",") if x.strip()}
        if not m.group(2) or not m.group(2).strip():
            diags.append(Diagnostic(
                "promql-pragma-no-reason",
                "disable pragma without a (reason) string",
                pos=m.start(), end=m.end()))
        for rid in ids:
            if rid != "all" and rid not in RULES:
                diags.append(Diagnostic(
                    "promql-pragma-unknown-rule",
                    f"pragma disables unknown rule {rid!r}",
                    pos=m.start(), end=m.end()))
        disabled |= ids
    return frozenset(disabled), diags


class _Analyzer:
    def __init__(self, schemas: Optional[MetricSchemas] = None):
        self.schemas = schemas or MetricSchemas()
        self.diags: List[Diagnostic] = []

    # -- helpers ---------------------------------------------------------
    def _diag(self, rule: str, message: str, node) -> None:
        sev, _doc = RULES[rule]
        pos, end = pp.ast_span(node)
        self.diags.append(Diagnostic(rule, message, pos=pos, end=end,
                                     severity=sev))

    # -- walk ------------------------------------------------------------
    def walk(self, node) -> Tuple[str, LabelInfo]:
        """Returns (type, LabelInfo). Appends diagnostics as it goes;
        on a type error it reports and recovers with a plausible type
        so one mistake doesn't cascade."""
        if isinstance(node, pp.NumLit):
            return SCALAR, _OPEN
        if isinstance(node, pp.StrLit):
            return STRING, _OPEN
        if isinstance(node, pp.Unary):
            t, li = self.walk(node.expr)
            if t == STRING:
                self._diag("promql-string-operand",
                           "unary minus on a string", node)
            return (t if t in (SCALAR, INSTANT) else SCALAR), li
        if isinstance(node, pp.Selector):
            known = frozenset(m.label for m in node.matchers
                              if m.op == "=" and
                              m.label not in _METRIC_LABELS)
            li = LabelInfo(known, None)
            return (RANGE if node.window_ms is not None else INSTANT), li
        if isinstance(node, pp.Subquery):
            t, li = self.walk(node.expr)
            if t not in (INSTANT,):
                self._diag("promql-subquery-inner",
                           f"subquery body is a {t}; the engine "
                           f"evaluates subqueries over instant "
                           f"vectors only", node)
            return RANGE, li
        if isinstance(node, pp.Agg):
            return self._agg(node)
        if isinstance(node, pp.Call):
            return self._call(node)
        if isinstance(node, pp.BinOp):
            return self._binop(node)
        return INSTANT, _OPEN

    # -- aggregations ----------------------------------------------------
    def _agg(self, node: pp.Agg) -> Tuple[str, LabelInfo]:
        t, li = self.walk(node.expr)
        if t != INSTANT:
            self._diag("promql-instant-arg",
                       f"{node.op}() aggregates instant vectors, got "
                       f"a {t}", node)
        for p in node.params:
            pt, _ = self.walk(p)
            if node.op == "count_values":
                if pt != STRING:
                    self._diag("promql-string-arg",
                               f"count_values takes a string label "
                               f"name parameter, got a {pt}", node)
            elif pt != SCALAR:
                self._diag("promql-scalar-arg",
                           f"{node.op}() parameter must be a scalar, "
                           f"got a {pt}", node)
        if node.by:
            for l in node.by:
                if not li.may_carry(l) and l not in _METRIC_LABELS:
                    self._diag("promql-by-absent-label",
                               f"by({l}) — the inner expression "
                               f"provably cannot carry label {l!r}",
                               node)
            out = LabelInfo(li.known & frozenset(node.by),
                            frozenset(node.by))
        elif node.without:
            out = li.drop(node.without)
        else:
            out = LabelInfo(frozenset(), frozenset())
        if node.op == "count_values" and node.params:
            p = node.params[0]
            if isinstance(p, pp.StrLit):
                out = out.add(p.value)
        return INSTANT, out

    # -- function calls --------------------------------------------------
    def _call(self, node: pp.Call) -> Tuple[str, LabelInfo]:
        name = node.name
        nargs = len(node.args)

        def arity(lo: int, hi: Optional[int] = None) -> bool:
            hi = lo if hi is None else hi
            if not (lo <= nargs <= hi):
                want = str(lo) if lo == hi else f"{lo}..{hi}"
                self._diag("promql-arity",
                           f"{name}() takes {want} argument(s), got "
                           f"{nargs}", node)
                return False
            return True

        if name in pp.RANGE_FN_NAMES:
            return self._range_call(node, arity)
        if name in pp.INSTANT_FNS:
            return self._instant_call(node, arity)
        if name in pp.MISC_FNS:
            return self._misc_call(node, arity)
        if name in ("scalar", "absent"):
            if arity(1):
                t, li = self.walk(node.args[0])
                if t != INSTANT:
                    self._diag("promql-instant-arg",
                               f"{name}() requires an instant vector, "
                               f"got a {t}", node)
                if name == "absent":
                    inner = node.args[0]
                    known = frozenset(
                        m.label for m in getattr(inner, "matchers", ())
                        if m.op == "=" and m.label not in _METRIC_LABELS)
                    return INSTANT, LabelInfo(known, known)
            return (SCALAR if name == "scalar" else INSTANT), _OPEN
        if name == "vector":
            if arity(1):
                t, _ = self.walk(node.args[0])
                if t != SCALAR:
                    self._diag("promql-scalar-arg",
                               f"vector() requires a scalar, got a "
                               f"{t}", node)
            return INSTANT, LabelInfo(frozenset(), frozenset())
        if name in ("time", "pi"):
            arity(0)
            return SCALAR, _OPEN
        if name in ("sort", "sort_desc", "timestamp"):
            if arity(1):
                t, li = self.walk(node.args[0])
                if t != INSTANT:
                    self._diag("promql-instant-arg",
                               f"{name}() requires an instant vector, "
                               f"got a {t}", node)
                return INSTANT, li
            return INSTANT, _OPEN
        if name == "limit":
            if arity(2):
                kt, _ = self.walk(node.args[0])
                if kt != SCALAR:
                    self._diag("promql-scalar-arg",
                               "limit() k must be a scalar", node)
                t, li = self.walk(node.args[1])
                if t != INSTANT:
                    self._diag("promql-instant-arg",
                               "limit() requires an instant vector",
                               node)
                return INSTANT, li
            return INSTANT, _OPEN
        self._diag("promql-unknown-function",
                   f"unknown function {name!r}", node)
        return INSTANT, _OPEN

    def _range_call(self, node: pp.Call, arity) -> Tuple[str, LabelInfo]:
        name = node.name
        engine_name = pp.RANGE_FN_NAMES[name]
        n_scalars = RANGE_FN_SCALAR_ARITY.get(engine_name, 0)
        scalar_first = name in pp.RANGE_FN_SCALAR_FIRST
        if not arity(1 + n_scalars):
            # recover: still type-check whatever args exist
            pass
        args = list(node.args)
        rv_idx = 1 if scalar_first and args else 0
        scalar_args = [a for i, a in enumerate(args) if i != rv_idx]
        for a in scalar_args:
            t, _ = self.walk(a)
            if t != SCALAR:
                self._diag("promql-scalar-arg",
                           f"{name}() parameter must be a scalar, got "
                           f"a {t}", node)
        li = _OPEN
        if rv_idx < len(args):
            rv = args[rv_idx]
            t, li = self.walk(rv)
            if t != RANGE:
                self._diag("promql-range-arg",
                           f"{name}() expects a range vector "
                           f"(selector[window] or subquery), got a "
                           f"{t}", node)
            self._schema_check(name, engine_name, rv, node)
        return INSTANT, li

    def _schema_check(self, name: str, engine_name: str, rv,
                      node) -> None:
        """Counter/gauge semantics of the metric under a range
        function, resolved from the ingest schema."""
        metric = getattr(rv, "metric", None)
        if not isinstance(rv, pp.Selector) or not metric:
            return
        kind, explicit = self.schemas.resolve(metric)
        if kind is None:
            return
        is_counter = kind in ("counter", "histogram", "delta-counter")
        if engine_name in COUNTER_FUNCTIONS and not is_counter:
            if explicit:
                self._diag("promql-counter-fn-on-gauge",
                           f"{name}() on {metric!r} whose declared "
                           f"schema is {kind}: reset correction over "
                           f"a gauge produces garbage — use "
                           f"{'deriv' if name == 'rate' else 'delta'}"
                           f"() or fix the schema", node)
            return
        if engine_name in GAUGE_FUNCTIONS and is_counter:
            self._diag("promql-gauge-fn-on-counter",
                       f"{name}() on counter {metric!r} ignores "
                       f"counter resets — use "
                       f"{'rate' if name == 'deriv' else 'increase'}"
                       f"() instead", node)

    def _instant_call(self, node: pp.Call, arity
                      ) -> Tuple[str, LabelInfo]:
        name = node.name
        if name in _SCALAR_FIRST:
            ok = arity(2)
            li = _OPEN
            if node.args:
                t, _ = self.walk(node.args[0])
                if t != SCALAR:
                    self._diag("promql-scalar-arg",
                               f"{name}() first argument must be a "
                               f"scalar, got a {t}", node)
            if ok and len(node.args) > 1:
                t, li = self.walk(node.args[1])
                if t != INSTANT:
                    self._diag("promql-instant-arg",
                               f"{name}() requires an instant vector, "
                               f"got a {t}", node)
            return INSTANT, li
        lo, hi = _INSTANT_ARITY.get(name, (0, 0))
        ok = arity(1 + lo, 1 + hi)
        li = _OPEN
        if node.args:
            t, li = self.walk(node.args[0])
            if t != INSTANT:
                self._diag("promql-instant-arg",
                           f"{name}() requires an instant vector, got "
                           f"a {t}", node)
        for a in node.args[1:]:
            t, _ = self.walk(a)
            if t != SCALAR:
                self._diag("promql-scalar-arg",
                           f"{name}() parameter must be a scalar, got "
                           f"a {t}", node)
        return INSTANT, li

    def _misc_call(self, node: pp.Call, arity) -> Tuple[str, LabelInfo]:
        name = node.name
        if name == "label_replace":
            ok = arity(5)
        else:
            ok = arity(3, 99)
        li = _OPEN
        if node.args:
            t, li = self.walk(node.args[0])
            if t != INSTANT:
                self._diag("promql-instant-arg",
                           f"{name}() requires an instant vector, got "
                           f"a {t}", node)
        for a in node.args[1:]:
            t, _ = self.walk(a)
            if t != STRING:
                self._diag("promql-string-arg",
                           f"{name}() label arguments must be string "
                           f"literals, got a {t}", node)
        if ok and node.args and isinstance(node.args[1], pp.StrLit):
            li = li.add(node.args[1].value)
        return INSTANT, li

    # -- binary operators -------------------------------------------------
    def _binop(self, node: pp.BinOp) -> Tuple[str, LabelInfo]:
        lt, lli = self.walk(node.lhs)
        rt, rli = self.walk(node.rhs)
        for t, side in ((lt, "left"), (rt, "right")):
            if t == STRING:
                self._diag("promql-string-operand",
                           f"{node.op} on a string operand "
                           f"({side}-hand side)", node)
            elif t == RANGE:
                self._diag("promql-instant-arg",
                           f"{node.op} on a range vector "
                           f"({side}-hand side); wrap it in a range "
                           f"function", node)
        if node.return_bool and node.op not in _CMP_OPS:
            self._diag("promql-bool-modifier",
                       f"bool modifier on {node.op!r}", node)
        if node.op in _SET_OPS:
            if lt != INSTANT or rt != INSTANT:
                self._diag("promql-setop-operand",
                           f"{node.op} requires instant vectors on "
                           f"both sides (got {lt} {node.op} {rt})",
                           node)
            if node.op == "or":
                upper = None if (lli.upper is None or rli.upper is None) \
                    else lli.upper | rli.upper
                return INSTANT, LabelInfo(lli.known & rli.known, upper)
            return INSTANT, lli
        scalar_sides = (lt == SCALAR) + (rt == SCALAR)
        if scalar_sides == 2:
            if node.op in _CMP_OPS and not node.return_bool:
                self._diag("promql-cmp-scalar-needs-bool",
                           f"comparison between two scalars requires "
                           f"the bool modifier ({node.op})", node)
            return SCALAR, _OPEN
        if scalar_sides == 1:
            if node.on is not None or node.ignoring or \
                    node.group_left or node.group_right:
                self._diag("promql-matching-with-scalar",
                           "on/ignoring/group_* vector matching with "
                           "a scalar operand", node)
            return INSTANT, (rli if lt == SCALAR else lli)
        # vector <op> vector
        self._check_matching(node, lli, rli)
        if node.group_right:
            return INSTANT, rli
        return INSTANT, lli

    def _check_matching(self, node: pp.BinOp, lli: LabelInfo,
                        rli: LabelInfo) -> None:
        if node.on is not None:
            for l in node.on:
                if l in _METRIC_LABELS:
                    continue
                for li, side in ((lli, "left"), (rli, "right")):
                    if not li.may_carry(l):
                        self._diag(
                            "promql-match-on-dropped-label",
                            f"on({l}) — the {side}-hand side cannot "
                            f"carry label {l!r}: an upstream "
                            f"aggregation dropped it (carries only "
                            f"{sorted(li.upper or ())})", node)
        if node.include and (node.group_left or node.group_right):
            one = rli if node.group_left else lli
            for l in node.include:
                if not one.may_carry(l):
                    self._diag(
                        "promql-include-dropped-label",
                        f"group_*({l}) — the 'one' side cannot carry "
                        f"include label {l!r}", node)
        # provable many-to-many ambiguity: both sides closed, the match
        # key strictly coarser than both identities
        if node.group_left or node.group_right or node.op in _SET_OPS:
            return
        if node.on is None:
            return
        key = frozenset(node.on)
        sides_ambiguous = 0
        for li in (lli, rli):
            if li.upper is not None and (li.upper - key):
                sides_ambiguous += 1
        if sides_ambiguous == 2:
            self._diag(
                "promql-many-to-many",
                f"on({','.join(sorted(key))}) cannot distinguish "
                f"series that differ in "
                f"{sorted((lli.upper | rli.upper) - key)} on both "
                f"sides; a many-to-many match fails at eval time — "
                f"add group_left/group_right or extend on(...)", node)


def lint_ast(ast, query: str = "",
             schemas: Optional[MetricSchemas] = None
             ) -> List[Diagnostic]:
    """Analyze a parsed AST. ``query`` (when given) supplies pragma
    comments and better top-level spans."""
    an = _Analyzer(schemas)
    t, _li = an.walk(ast)
    if t == RANGE:
        an._diag("promql-top-level-range",
                 "the query evaluates to a bare range vector; wrap it "
                 "in a range function (e.g. rate(...), avg_over_time)",
                 ast)
    diags = an.diags
    if query:
        disabled, meta = parse_pragmas(query)
        if disabled:
            diags = [d for d in diags
                     if d.rule not in disabled and "all" not in disabled]
        diags = diags + meta
    diags.sort(key=lambda d: (d.pos, d.rule))
    return diags


def lint_query(query: str,
               schemas: Optional[MetricSchemas] = None
               ) -> List[Diagnostic]:
    """Parse + analyze one query; a syntax failure comes back as a
    single spanned ``promql-syntax`` diagnostic (never raises)."""
    try:
        ast = pp.Parser(query).parse()
    except pp.ParseError as e:
        return [Diagnostic("promql-syntax", str(e),
                           pos=getattr(e, "pos", -1),
                           end=getattr(e, "end", -1))]
    except Exception as e:    # noqa: BLE001 — a linter must not crash
        return [Diagnostic("promql-syntax", f"query rejected: {e}")]
    return lint_ast(ast, query=query, schemas=schemas)


def errors(diags: Sequence[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity == ERROR]


# ---------------------------------------------------------------------------
# static cost bounds
# ---------------------------------------------------------------------------

@dataclass
class CostBound:
    """A static upper bound on the QoS runtime price of a plan.

    Invariant (pinned by tests/test_promql_cost_bound.py): for any
    plan over any shard set, ``bound.total >= estimate_plan_cost(plan,
    shards, metering).total``. Every factor here dominates the
    estimator's corresponding factor: per-leaf series bounds skip the
    estimator's extra-equality damping, the window factor rounds UP,
    the shape weight uses a larger per-node increment, and unknown
    grids fall back to the worst periodic grid in the plan instead of
    1. The bound rides ``&explain=analyze`` so an operator can see the
    admission headroom of a plan shape."""
    total: float
    series_ub: int
    steps_ub: int
    window_factor_ub: float
    shape_weight_ub: float
    leaves: List[Dict] = field(default_factory=list)

    def to_json(self) -> Dict:
        return {"total": round(self.total, 1),
                "seriesUpperBound": int(self.series_ub),
                "stepsUpperBound": int(self.steps_ub),
                "windowFactorUpperBound": round(self.window_factor_ub, 3),
                "shapeWeightUpperBound": round(self.shape_weight_ub, 3),
                "leaves": self.leaves}


def _leaf_series_upper_bound(filters, shards, metering) -> Tuple[int, Dict]:
    """Per-leaf series upper bound. Mirrors
    ``qos._leaf_series_estimate``'s sources but NEVER comes out below
    it: same tracker/posting inputs with the ``>> 2*extra_eq`` damping
    removed, and on remote legs BOTH the metering count and the
    unknown-leg guess are summed (the estimator takes one or the
    other)."""
    from filodb_tpu.core.cardinality import SHARD_KEY_LABELS
    from filodb_tpu.query.qos import _UNKNOWN_SERIES_GUESS
    eq = {f.label: str(f.value) for f in filters
          if getattr(f, "op", "") == "eq"}
    prefix: List[str] = []
    for lbl in SHARD_KEY_LABELS:
        if lbl in eq:
            prefix.append(eq[lbl])
        else:
            break
    total = 0
    found = False
    remote = 0
    detail: Dict = {"prefix": list(prefix)}
    for s in shards:
        tracker = getattr(s, "card_tracker", None)
        if tracker is None:
            if hasattr(s, "fetch_raw"):
                remote += 1
            continue
        n = tracker.series_count(prefix)
        if n is None:
            continue
        idx = getattr(s, "index", None)
        if idx is not None and hasattr(idx, "posting_upper_bound"):
            ub = idx.posting_upper_bound(filters)
            if ub is not None:
                n = min(n, ub)
        total += n
        found = True
    if remote:
        counted = None
        if metering is not None and prefix:
            counted = metering.count_for(tuple(prefix))
        total += int(counted or 0) + _UNKNOWN_SERIES_GUESS * remote
        found = True
    if not found:
        total = _UNKNOWN_SERIES_GUESS
    total = max(1, total)
    detail["seriesUpperBound"] = int(total)
    return total, detail


def static_cost_bound(plan, shards: Sequence[object],
                      metering: Optional[object] = None) -> CostBound:
    """Static price ceiling of a LogicalPlan over ``shards`` — see
    :class:`CostBound` for the dominance argument."""
    from filodb_tpu.query import logical as lp
    from filodb_tpu.query.planner import (plan_range, walk_leaf_filters,
                                          walk_plan_tree)
    rng = plan_range(plan)
    worst_steps = [1]
    worst_wf = [1.0]
    if rng is not None:
        start, step, end, window, _lookback = rng
        if step > 0:
            worst_steps[0] = (end - start) // step + 1
        # dominate the estimator's min-window factor with the MAX
        # window over periodic nodes, rounded up

    def visit(p):
        if isinstance(p, (lp.PeriodicSeries,
                          lp.PeriodicSeriesWithWindowing,
                          lp.SubqueryWithWindowing)):
            w = getattr(p, "window_ms", 0) or \
                getattr(p, "lookback_ms", 0)
            st = p.step_ms
            if st > 0:
                worst_steps[0] = max(worst_steps[0],
                                     (p.end_ms - p.start_ms) // st + 1)
                if w and w < (1 << 61):
                    worst_wf[0] = max(worst_wf[0],
                                      1.0 + math.ceil(w / st))
            if isinstance(p, lp.SubqueryWithWindowing):
                return False    # descend: inner grids may be denser
            return True
        return False

    walk_plan_tree(plan, visit)
    nodes = [0]
    walk_plan_tree(plan, lambda p: nodes.__setitem__(0, nodes[0] + 1))
    shape_weight_ub = 1.0 + 0.2 * max(0, nodes[0] - 1)
    leaves = walk_leaf_filters(plan)
    series_ub = 0
    leaf_details: List[Dict] = []
    for f in leaves:
        n, detail = _leaf_series_upper_bound(f, shards, metering)
        series_ub += n
        leaf_details.append(detail)
    series_ub = max(1, series_ub)
    total = (float(series_ub) * max(1, worst_steps[0]) * worst_wf[0]
             * shape_weight_ub)
    return CostBound(total=total, series_ub=series_ub,
                     steps_ub=int(worst_steps[0]),
                     window_factor_ub=float(worst_wf[0]),
                     shape_weight_ub=shape_weight_ub,
                     leaves=leaf_details)
