"""PromQL parser: query text -> LogicalPlan.

Hand-written recursive-descent parser with the same surface as the reference's
ANTLR grammar (prometheus/src/main/java/filodb/prometheus/antlr/PromQL.g4;
AST -> LogicalPlan conversion in prometheus/src/main/scala/filodb/prometheus/
ast/Vectors.scala, Functions.scala, Aggregates.scala, Expressions.scala).

Supported: literals, vector selectors with matchers, range + subquery
selectors, offset, all range/instant/aggregation functions in the engine
registry, binary operators with Prometheus precedence/associativity, bool
modifier, on/ignoring + group_left/group_right vector matching, by/without
grouping (both positions), scalar()/vector()/time()/absent().
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple, Union

from filodb_tpu.core.index import ColumnFilter
from filodb_tpu.query import logical as lp
from filodb_tpu.query.rangefn import RANGE_FUNCTIONS

DEFAULT_LOOKBACK_MS = 300_000   # Prometheus default staleness period

METRIC_COLUMN = "_metric_"

# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<WS>\s+|\#[^\n]*)
  | (?P<DURATION>[0-9]+(?:\.[0-9]+)?(?:ms|s|m|h|d|w|y)(?:[0-9]+(?:\.[0-9]+)?(?:ms|s|m|h|d|w|y))*)
  | (?P<NUMBER>
        0x[0-9a-fA-F]+
      | (?:[0-9]+\.?[0-9]*|\.[0-9]+)(?:[eE][+-]?[0-9]+)?
      | [iI][nN][fF]
      | [nN][aA][nN])
  | (?P<IDENT>[a-zA-Z_][a-zA-Z0-9_:.]*)
  | (?P<STRING>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*'|`[^`]*`)
  | (?P<OP>=~|!~|==|!=|<=|>=|[-+*/%^(){}\[\],=<>@:])
""", re.VERBOSE)

_DUR_UNIT_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
                "d": 86_400_000, "w": 7 * 86_400_000, "y": 365 * 86_400_000}
_DUR_PART_RE = re.compile(r"([0-9]+(?:\.[0-9]+)?)(ms|s|m|h|d|w|y)")


def parse_duration_ms(text: str) -> int:
    """Duration string -> milliseconds. Rejects empty/malformed text —
    every part must parse, and the parts must cover the whole string
    (``5mm``, ``5``, ``m5`` and "" all raise ValueError)."""
    total = 0.0
    covered = 0
    for m in _DUR_PART_RE.finditer(text):
        if m.start() != covered:
            break
        total += float(m.group(1)) * _DUR_UNIT_MS[m.group(2)]
        covered = m.end()
    if covered != len(text) or not text:
        raise ValueError(f"invalid duration {text!r}")
    return int(total)


@dataclass
class Token:
    kind: str
    text: str
    pos: int

    @property
    def end(self) -> int:
        return self.pos + len(self.text)


class ParseError(ValueError):
    """Syntax/semantic rejection at parse time. ``pos``/``end`` are
    character offsets into the query text (-1 = unknown) so callers can
    render a caret span (promlint diagnostics reuse these spans)."""

    def __init__(self, message: str, pos: int = -1, end: int = -1):
        super().__init__(message)
        self.pos = int(pos)
        self.end = int(end) if end >= 0 else \
            (int(pos) + 1 if pos >= 0 else -1)


def tokenize(q: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    while pos < len(q):
        m = _TOKEN_RE.match(q, pos)
        if not m:
            raise ParseError(f"unexpected character {q[pos]!r} at {pos}",
                             pos=pos)
        kind = m.lastgroup
        if kind != "WS":
            out.append(Token(kind, m.group(), pos))
        pos = m.end()
    return out


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclass
class Matcher:
    label: str
    op: str     # = != =~ !~
    value: str


@dataclass
class Selector:
    metric: Optional[str]
    matchers: List[Matcher]
    window_ms: Optional[int] = None
    offset_ms: int = 0
    # int ms, or "start"/"end" (@ start()/@ end()), resolved against the
    # query range at plan conversion
    at_ms: object = None
    column: Optional[str] = None   # FiloDB ::column suffix
    pos: int = -1                  # char span in the query text
    end: int = -1


@dataclass
class NumLit:
    value: float
    pos: int = -1
    end: int = -1


@dataclass
class StrLit:
    value: str
    pos: int = -1
    end: int = -1


@dataclass
class Call:
    name: str
    args: List
    pos: int = -1
    end: int = -1


@dataclass
class Agg:
    op: str
    expr: object
    params: List
    by: Tuple[str, ...] = ()
    without: Tuple[str, ...] = ()
    pos: int = -1
    end: int = -1


@dataclass
class BinOp:
    op: str
    lhs: object
    rhs: object
    return_bool: bool = False
    on: Optional[Tuple[str, ...]] = None
    ignoring: Tuple[str, ...] = ()
    group_left: bool = False
    group_right: bool = False
    include: Tuple[str, ...] = ()
    pos: int = -1                  # span of the operator token
    end: int = -1


@dataclass
class Subquery:
    expr: object
    window_ms: int
    step_ms: Optional[int]
    offset_ms: int = 0
    # int ms, or "start"/"end" (@ start()/@ end()), resolved against the
    # query range at plan conversion
    at_ms: object = None
    pos: int = -1
    end: int = -1


@dataclass
class Unary:
    op: str
    expr: object
    pos: int = -1
    end: int = -1


def ast_span(node) -> Tuple[int, int]:
    """(pos, end) char span of any AST node (-1, -1 when unknown)."""
    return (getattr(node, "pos", -1), getattr(node, "end", -1))


AGG_OPS = {"sum", "avg", "min", "max", "count", "stddev", "stdvar", "group",
           "topk", "bottomk", "quantile", "count_values", "absent_hack"}

# aggregations taking a leading parameter
AGG_PARAM_OPS = {"topk", "bottomk", "quantile", "count_values", "limitk"}

# PromQL surface name -> engine range function name (identity for most)
RANGE_FN_NAMES = {name: name for name in RANGE_FUNCTIONS} | {
    "zscore": "z_score",
    "median_absolute_deviation_over_time": "mad_over_time",
}
# functions with (scalar, range-vector) argument order
RANGE_FN_SCALAR_FIRST = {"quantile_over_time"}
# functions with (range-vector, scalar...) order
RANGE_FN_SCALAR_AFTER = {"predict_linear", "holt_winters"}
# instant functions with (scalar, vector) order; all others take the
# vector first (shared with the plan printer — planparser.py)
INSTANT_FN_SCALAR_FIRST = ("histogram_quantile", "histogram_bucket",
                           "histogram_max_quantile")

INSTANT_FNS = {
    "abs", "ceil", "floor", "exp", "ln", "log2", "log10", "sqrt", "round",
    "sgn", "clamp", "clamp_min", "clamp_max", "histogram_quantile",
    "histogram_bucket", "histogram_max_quantile", "acos", "asin", "atan",
    "cos", "cosh", "sin", "sinh", "tan", "tanh", "deg", "rad",
    "days_in_month", "day_of_month", "day_of_week", "day_of_year", "hour",
    "minute", "month", "year",
}

MISC_FNS = {"label_replace", "label_join"}

_CMP_OPS = {"==", "!=", ">", "<", ">=", "<="}

# precedence (higher binds tighter); ^ is right-associative
_PRECEDENCE = [
    ({"or"}, "left"),
    ({"and", "unless"}, "left"),
    (_CMP_OPS, "left"),
    ({"+", "-"}, "left"),
    ({"*", "/", "%", "atan2"}, "left"),
    ({"^"}, "right"),
]


class Parser:
    def __init__(self, query: str):
        self.toks = tokenize(query)
        self.i = 0

    # -- token helpers ---------------------------------------------------
    def peek(self, ahead: int = 0) -> Optional[Token]:
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> Token:
        t = self.peek()
        if t is None:
            raise ParseError("unexpected end of query", pos=self._eof_pos())
        self.i += 1
        return t

    def accept(self, text: str) -> bool:
        t = self.peek()
        if t is not None and t.text == text:
            self.i += 1
            return True
        return False

    def expect(self, text: str) -> Token:
        t = self.peek()
        if t is None or t.text != text:
            got = t.text if t else "<eof>"
            raise ParseError(f"expected {text!r}, got {got!r}",
                             pos=t.pos if t else self._eof_pos(),
                             end=t.end if t else -1)
        return self.next()

    def at_end(self) -> bool:
        return self.i >= len(self.toks)

    def _eof_pos(self) -> int:
        return self.toks[-1].end if self.toks else 0

    def _last_end(self) -> int:
        return self.toks[self.i - 1].end if self.i else 0

    # -- grammar ---------------------------------------------------------
    def parse(self):
        e = self.parse_expr(0)
        if not self.at_end():
            t = self.peek()
            raise ParseError(f"trailing input at {t.text!r}",
                             pos=t.pos, end=t.end)
        return e

    def parse_expr(self, level: int):
        if level >= len(_PRECEDENCE):
            return self.parse_unary()
        ops, assoc = _PRECEDENCE[level]
        lhs = self.parse_expr(level + 1)
        while True:
            t = self.peek()
            if t is None or t.text not in ops:
                break
            op_tok = self.next()
            op = op_tok.text
            return_bool = False
            if self.peek() is not None and self.peek().text == "bool":
                self.next()
                return_bool = True
            on = None
            ignoring: Tuple[str, ...] = ()
            gl = gr = False
            include: Tuple[str, ...] = ()
            t2 = self.peek()
            if t2 is not None and t2.text in ("on", "ignoring"):
                which = self.next().text
                labels = self._label_list()
                if which == "on":
                    on = labels
                else:
                    ignoring = labels
                t3 = self.peek()
                if t3 is not None and t3.text in ("group_left", "group_right"):
                    which = self.next().text
                    gl = which == "group_left"
                    gr = which == "group_right"
                    if self.peek() is not None and self.peek().text == "(":
                        include = self._label_list()
            if assoc == "right":
                rhs = self.parse_expr(level)  # right-assoc recursion
            else:
                rhs = self.parse_expr(level + 1)
            lhs = BinOp(op, lhs, rhs, return_bool, on, ignoring, gl, gr,
                        include, pos=op_tok.pos, end=op_tok.end)
            lhs = self._postfix(lhs)
            if assoc == "right":
                break
        return lhs

    def _label_list(self) -> Tuple[str, ...]:
        self.expect("(")
        labels = []
        while not self.accept(")"):
            t = self.next()
            if t.kind not in ("IDENT",):
                raise ParseError(f"expected label name, got {t.text!r}",
                                 pos=t.pos, end=t.end)
            labels.append(t.text)
            if not self.accept(","):
                self.expect(")")
                break
        return tuple(labels)

    def parse_unary(self):
        t = self.peek()
        if t is not None and t.text in ("+", "-"):
            self.next()
            inner = self.parse_unary()
            if t.text == "-":
                if isinstance(inner, NumLit):
                    return NumLit(-inner.value, pos=t.pos,
                                  end=getattr(inner, "end", -1))
                return Unary("-", inner, pos=t.pos,
                             end=getattr(inner, "end", -1))
            return inner
        return self.parse_postfix()

    def parse_postfix(self):
        e = self.parse_primary()
        return self._postfix(e)

    def _postfix(self, e):
        while True:
            t = self.peek()
            if t is None:
                break
            if t.text == "[":
                self.next()
                d = self.next()
                window = self._duration_token(d, "duration")
                if window <= 0:
                    # a zero/empty window selects nothing a range
                    # function could ever evaluate — reject at parse
                    # time instead of returning all-NaN at eval time
                    raise ParseError(
                        f"zero-length range window {d.text!r}",
                        pos=d.pos, end=d.end)
                if self.accept(":"):
                    step = None
                    nt = self.peek()
                    if nt is not None and nt.text != "]":
                        sd = self.next()
                        step = self._duration_token(sd, "subquery step")
                        if step <= 0:
                            # Prometheus rejects explicit zero subquery
                            # resolution ([5m:0s]) — pinned behavior
                            raise ParseError(
                                f"zero subquery step {sd.text!r}",
                                pos=sd.pos, end=sd.end)
                    self.expect("]")
                    e = Subquery(e, window, step,
                                 pos=getattr(e, "pos", t.pos),
                                 end=self._last_end())
                else:
                    self.expect("]")
                    if not isinstance(e, Selector):
                        raise ParseError(
                            "range selector applies only to vector selectors",
                            pos=t.pos, end=self._last_end())
                    e.window_ms = window
                    e.end = self._last_end()
            elif t.text == "offset":
                self.next()
                d = self.next()
                sign = 1
                if d.text == "-":
                    sign = -1
                    d = self.next()
                off = self._duration_token(d, "offset duration")
                off *= sign
                if isinstance(e, Selector):
                    e.offset_ms = off
                elif isinstance(e, Subquery):
                    e.offset_ms = off
                else:
                    raise ParseError("offset applies to selectors",
                                     pos=t.pos, end=d.end)
                e.end = self._last_end()
            elif t.text == "@":
                self.next()
                at = self.next()
                if at.text in ("start", "end"):
                    # @ start() / @ end() (LogicalPlan.scala:349 pins to
                    # the query range; resolved at plan conversion)
                    self.expect("(")
                    self.expect(")")
                    at_ms: object = at.text
                else:
                    sign = 1
                    if at.text == "-":
                        sign = -1
                        at = self.next()
                    at_ms = sign * int(float(at.text) * 1000)
                if isinstance(e, (Selector, Subquery)):
                    e.at_ms = at_ms
                    e.end = self._last_end()
                else:
                    raise ParseError(
                        "@ modifier is only supported on vector and range "
                        "selectors and subqueries",
                        pos=t.pos, end=self._last_end())
            else:
                break
        return e

    def _duration_token(self, d: Token, what: str) -> int:
        """ms value of a DURATION/NUMBER token, with a spanned error on
        anything else (the old path crashed on malformed text)."""
        try:
            if d.kind == "DURATION":
                return parse_duration_ms(d.text)
            if d.kind == "NUMBER":
                return int(float(d.text) * 1000)
        except ValueError:
            pass
        raise ParseError(f"expected {what}, got {d.text!r}",
                         pos=d.pos, end=d.end)

    def parse_primary(self):
        t = self.peek()
        if t is None:
            raise ParseError("unexpected end of query", pos=self._eof_pos())
        if t.text == "(":
            self.next()
            e = self.parse_expr(0)
            self.expect(")")
            return e
        if t.kind == "NUMBER":
            self.next()
            txt = t.text.lower()
            if txt.startswith("0x"):
                return NumLit(float(int(txt, 16)), pos=t.pos, end=t.end)
            if txt == "inf":
                return NumLit(float("inf"), pos=t.pos, end=t.end)
            if txt == "nan":
                return NumLit(float("nan"), pos=t.pos, end=t.end)
            return NumLit(float(t.text), pos=t.pos, end=t.end)
        if t.kind == "STRING":
            self.next()
            return StrLit(_unquote(t.text), pos=t.pos, end=t.end)
        if t.kind == "DURATION":
            # bare duration as number of seconds (PromQL durations-as-numbers)
            self.next()
            return NumLit(parse_duration_ms(t.text) / 1000.0,
                          pos=t.pos, end=t.end)
        if t.text == "{":
            return self._selector(None, t.pos)
        if t.kind == "IDENT":
            # aggregation with leading grouping: sum by (x) (...)
            if t.text in AGG_OPS and t.text != "absent_hack":
                return self._aggregation()
            nxt = self.peek(1)
            if nxt is not None and nxt.text == "(" and _is_function(t.text):
                return self._call()
            self.next()
            return self._selector(t.text, t.pos)
        raise ParseError(f"unexpected token {t.text!r}", pos=t.pos,
                         end=t.end)

    def _selector(self, metric: Optional[str], pos: int = -1) -> Selector:
        column = None
        if metric and "::" in metric:
            metric, column = metric.split("::", 1)
        matchers: List[Matcher] = []
        if self.peek() is not None and self.peek().text == "{":
            self.next()
            while not self.accept("}"):
                lt = self.next()
                if lt.kind not in ("IDENT",) and not lt.kind == "STRING":
                    raise ParseError(f"expected label, got {lt.text!r}",
                                     pos=lt.pos, end=lt.end)
                label = lt.text
                opt = self.next()
                if opt.text not in ("=", "!=", "=~", "!~"):
                    raise ParseError(f"bad matcher op {opt.text!r}",
                                     pos=opt.pos, end=opt.end)
                vt = self.next()
                if vt.kind != "STRING":
                    raise ParseError("matcher value must be a string",
                                     pos=vt.pos, end=vt.end)
                matchers.append(Matcher(label, opt.text, _unquote(vt.text)))
                if not self.accept(","):
                    self.expect("}")
                    break
        if metric is None and not matchers:
            raise ParseError("empty selector", pos=pos,
                             end=self._last_end())
        return Selector(metric, matchers, column=column, pos=pos,
                        end=self._last_end())

    def _aggregation(self) -> Agg:
        op_tok = self.next()
        op = op_tok.text
        by: Tuple[str, ...] = ()
        without: Tuple[str, ...] = ()
        t = self.peek()
        if t is not None and t.text in ("by", "without"):
            which = self.next().text
            labels = self._label_list()
            if which == "by":
                by = labels
            else:
                without = labels
        self.expect("(")
        args: List = []
        while True:
            args.append(self.parse_expr(0))
            if not self.accept(","):
                break
        self.expect(")")
        t = self.peek()
        if t is not None and t.text in ("by", "without"):
            which = self.next().text
            labels = self._label_list()
            if which == "by":
                by = labels
            else:
                without = labels
        params = args[:-1]
        expr = args[-1]
        if op in AGG_PARAM_OPS and len(args) < 2:
            raise ParseError(f"{op} requires a parameter",
                             pos=op_tok.pos, end=op_tok.end)
        return Agg(op, expr, params, by, without, pos=op_tok.pos,
                   end=self._last_end())

    def _call(self) -> Call:
        name_tok = self.next()
        name = name_tok.text
        self.expect("(")
        args: List = []
        if not self.accept(")"):
            while True:
                args.append(self.parse_expr(0))
                if not self.accept(","):
                    break
            self.expect(")")
        return Call(name, args, pos=name_tok.pos, end=self._last_end())


def _is_function(name: str) -> bool:
    return (name in RANGE_FN_NAMES or name in INSTANT_FNS or
            name in MISC_FNS or
            name in ("scalar", "vector", "time", "absent", "sort",
                     "sort_desc", "limit", "rate", "timestamp", "pi"))


def _unquote(s: str) -> str:
    if s[0] == "`":
        return s[1:-1]
    body = s[1:-1]
    return bytes(body, "utf-8").decode("unicode_escape")


# ---------------------------------------------------------------------------
# AST -> LogicalPlan
# ---------------------------------------------------------------------------

def _matchers_to_filters(sel: Selector) -> Tuple[ColumnFilter, ...]:
    filters: List[ColumnFilter] = []
    if sel.metric:
        filters.append(ColumnFilter.eq(METRIC_COLUMN, sel.metric))
    for m in sel.matchers:
        label = METRIC_COLUMN if m.label == "__name__" else m.label
        if m.op == "=":
            filters.append(ColumnFilter.eq(label, m.value))
        elif m.op == "!=":
            filters.append(ColumnFilter.neq(label, m.value))
        elif m.op == "=~":
            filters.append(ColumnFilter.regex(label, m.value))
        elif m.op == "!~":
            filters.append(ColumnFilter.not_regex(label, m.value))
    return tuple(filters)


@dataclass
class TimeStepParams:
    """start/step/end in SECONDS (HTTP API units, prometheus TimeStepParams).
    """
    start_s: int
    step_s: int
    end_s: int


class PlanBuilder:
    def __init__(self, start_ms: int, step_ms: int, end_ms: int,
                 lookback_ms: int = DEFAULT_LOOKBACK_MS):
        self.start_ms = start_ms
        self.step_ms = max(step_ms, 1)
        self.end_ms = end_ms
        self.lookback_ms = lookback_ms

    def build(self, ast) -> lp.LogicalPlan:
        return self._vec(ast)

    def _resolve_at(self, at) -> Optional[int]:
        """@ modifier value -> pinned ms (start()/end() pin to the query
        range, LogicalPlan.scala:349 / ast/SubqueryUtils)."""
        if at == "start":
            return self.start_ms
        if at == "end":
            return self.end_ms
        return at

    # -- scalar plans -----------------------------------------------------
    def _scalar(self, ast) -> lp.LogicalPlan:
        if isinstance(ast, NumLit):
            return lp.ScalarFixedDoublePlan(ast.value, self.start_ms,
                                            self.step_ms, self.end_ms)
        if isinstance(ast, Unary) and ast.op == "-":
            inner = self._scalar(ast.expr)
            return lp.ScalarBinaryOperation(
                "-", 0.0, inner, self.start_ms, self.step_ms, self.end_ms)
        if isinstance(ast, Call) and ast.name == "time":
            return lp.ScalarTimeBasedPlan("time", self.start_ms, self.step_ms,
                                          self.end_ms)
        if isinstance(ast, Call) and ast.name == "pi":
            import math
            return lp.ScalarFixedDoublePlan(math.pi, self.start_ms,
                                            self.step_ms, self.end_ms)
        if isinstance(ast, Call) and ast.name == "scalar":
            return lp.ScalarVaryingDoublePlan(self._vec(ast.args[0]))
        if isinstance(ast, BinOp) and self._is_scalar(ast.lhs) and \
                self._is_scalar(ast.rhs):
            return lp.ScalarBinaryOperation(
                ast.op, self._scalar(ast.lhs), self._scalar(ast.rhs),
                self.start_ms, self.step_ms, self.end_ms)
        raise ParseError(f"expected scalar expression, got {ast}")

    def _is_scalar(self, ast) -> bool:
        if isinstance(ast, NumLit):
            return True
        if isinstance(ast, Unary):
            return self._is_scalar(ast.expr)
        if isinstance(ast, Call) and ast.name in ("time", "scalar", "pi"):
            return True
        if isinstance(ast, BinOp):
            return self._is_scalar(ast.lhs) and self._is_scalar(ast.rhs)
        return False

    def _const(self, ast) -> float:
        if isinstance(ast, NumLit):
            return ast.value
        if isinstance(ast, Unary) and ast.op == "-":
            return -self._const(ast.expr)
        if isinstance(ast, StrLit):
            return ast.value  # type: ignore[return-value]
        raise ParseError(f"expected constant, got {ast}")

    # -- vector plans -----------------------------------------------------
    def _vec(self, ast) -> lp.LogicalPlan:
        if isinstance(ast, Selector):
            if ast.window_ms is not None:
                raise ParseError(
                    "range vector must be wrapped in a range function")
            raw = lp.RawSeriesPlan(
                _matchers_to_filters(ast),
                self.start_ms - self.lookback_ms - ast.offset_ms,
                self.end_ms - ast.offset_ms,
                column=ast.column, offset_ms=ast.offset_ms)
            return lp.PeriodicSeries(raw, self.start_ms, self.step_ms,
                                     self.end_ms, self.lookback_ms,
                                     ast.offset_ms,
                                     self._resolve_at(ast.at_ms))
        if isinstance(ast, Agg):
            inner = self._vec(ast.expr)
            params = tuple(self._const(p) for p in ast.params)
            return lp.Aggregate(ast.op, inner, params, ast.by, ast.without)
        if isinstance(ast, Call):
            return self._call_plan(ast)
        if isinstance(ast, BinOp):
            return self._binop_plan(ast)
        if isinstance(ast, Unary):
            inner = self._vec(ast.expr)
            return lp.ScalarVectorBinaryOperation(
                "-", lp.ScalarFixedDoublePlan(0.0, self.start_ms,
                                              self.step_ms, self.end_ms),
                inner, scalar_is_lhs=True)
        if isinstance(ast, NumLit):
            # bare scalar at vector position
            return lp.ScalarFixedDoublePlan(ast.value, self.start_ms,
                                            self.step_ms, self.end_ms)
        if isinstance(ast, Subquery):
            raise ParseError(
                "subquery must be wrapped in a range function")
        raise ParseError(f"cannot convert {ast} to plan")

    def _call_plan(self, ast: Call) -> lp.LogicalPlan:
        name = ast.name
        if name in ("sort", "sort_desc"):
            return lp.ApplySortFunction(self._vec(ast.args[0]),
                                        descending=(name == "sort_desc"))
        if name == "limit":
            return lp.ApplyLimitFunction(self._vec(ast.args[1]),
                                         int(self._const(ast.args[0])))
        if name == "absent":
            inner_ast = ast.args[0]
            filters = _matchers_to_filters(inner_ast) \
                if isinstance(inner_ast, Selector) else ()
            return lp.ApplyAbsentFunction(
                self._vec(inner_ast), tuple(filters), self.start_ms,
                self.step_ms, self.end_ms)
        if name == "vector":
            return lp.VectorPlan(self._scalar(ast.args[0]))
        if name == "scalar":
            return lp.ScalarVaryingDoublePlan(self._vec(ast.args[0]))
        if name == "time":
            return lp.ScalarTimeBasedPlan("time", self.start_ms, self.step_ms,
                                          self.end_ms)
        if name in MISC_FNS:
            inner = self._vec(ast.args[0])
            str_args = tuple(self._const(a) for a in ast.args[1:])
            return lp.ApplyMiscellaneousFunction(inner, name, str_args)
        if name in RANGE_FN_NAMES:
            return self._range_fn_plan(ast)
        if name in INSTANT_FNS:
            # arg order: histogram_quantile(q, v); clamp(v, a, b); round(v, n)
            if name in INSTANT_FN_SCALAR_FIRST:
                scalar_args = (self._const(ast.args[0]),)
                inner = self._vec(ast.args[1])
            else:
                inner = self._vec(ast.args[0])
                scalar_args = tuple(self._const(a) for a in ast.args[1:])
            return lp.ApplyInstantFunction(inner, name, scalar_args)
        raise ParseError(f"unknown function {name}")

    def _range_fn_plan(self, ast: Call) -> lp.LogicalPlan:
        name = ast.name
        fn = RANGE_FN_NAMES[name]
        args = list(ast.args)
        scalars: List[float] = []
        if name in RANGE_FN_SCALAR_FIRST:
            scalars.append(self._const(args.pop(0)))
        if name in RANGE_FN_SCALAR_AFTER:
            scalars.extend(self._const(a) for a in args[1:])
            args = args[:1]
        rv = args[0]
        if isinstance(rv, Selector):
            if rv.window_ms is None:
                raise ParseError(f"{name} expects a range vector")
            raw = lp.RawSeriesPlan(
                _matchers_to_filters(rv),
                self.start_ms - rv.window_ms - rv.offset_ms,
                self.end_ms - rv.offset_ms,
                column=rv.column, offset_ms=rv.offset_ms)
            return lp.PeriodicSeriesWithWindowing(
                raw, fn, rv.window_ms, self.start_ms, self.step_ms,
                self.end_ms, tuple(scalars), rv.offset_ms,
                self._resolve_at(rv.at_ms))
        if isinstance(rv, Subquery):
            sub_step = rv.step_ms if rv.step_ms else self.step_ms
            inner = self._vec(rv.expr)  # placeholder range; engine rewrites
            return lp.SubqueryWithWindowing(
                inner, fn, rv.window_ms, sub_step, self.start_ms,
                self.step_ms, self.end_ms, tuple(scalars), rv.offset_ms,
                self._resolve_at(rv.at_ms))
        raise ParseError(f"{name} expects a range vector argument")

    def _binop_plan(self, ast: BinOp) -> lp.LogicalPlan:
        lhs_scalar = self._is_scalar(ast.lhs)
        rhs_scalar = self._is_scalar(ast.rhs)
        if lhs_scalar and rhs_scalar:
            return lp.ScalarBinaryOperation(
                ast.op, self._scalar(ast.lhs), self._scalar(ast.rhs),
                self.start_ms, self.step_ms, self.end_ms)
        if lhs_scalar or rhs_scalar:
            scalar = self._scalar(ast.lhs if lhs_scalar else ast.rhs)
            vector = self._vec(ast.rhs if lhs_scalar else ast.lhs)
            return lp.ScalarVectorBinaryOperation(
                ast.op, scalar, vector, scalar_is_lhs=lhs_scalar,
                return_bool=ast.return_bool)
        card = "one-to-one"
        if ast.group_left:
            card = "many-to-one"
        elif ast.group_right:
            card = "one-to-many"
        return lp.BinaryJoin(
            self._vec(ast.lhs), ast.op, self._vec(ast.rhs), card,
            ast.on, ast.ignoring, ast.include, ast.return_bool)


# ---------------------------------------------------------------------------
# Public API (parse/Parser.scala:183 queryRangeToLogicalPlan equivalent)
# ---------------------------------------------------------------------------

def parse_query_range(query: str, params: TimeStepParams,
                      lookback_ms: int = DEFAULT_LOOKBACK_MS
                      ) -> lp.LogicalPlan:
    ast = Parser(query).parse()
    b = PlanBuilder(params.start_s * 1000, params.step_s * 1000,
                    params.end_s * 1000, lookback_ms)
    return b.build(ast)


def parse_query(query: str, time_s: int,
                lookback_ms: int = DEFAULT_LOOKBACK_MS) -> lp.LogicalPlan:
    """Instant query at one timestamp (step=0 -> single step)."""
    return parse_query_range(query, TimeStepParams(time_s, 1, time_s),
                             lookback_ms)


def _fmt_num(v: float) -> str:
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_dur(ms: int) -> str:
    return f"{int(ms)}ms"


def _quote(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def ast_to_text(ast) -> str:
    """Canonical (normalized) rendering of a parsed AST: one spacing,
    sorted matchers/grouping labels, ms-unit durations, explicit parens.
    Two queries with the same rendering are SYNTACTICALLY equivalent
    modulo whitespace/comments/label order — the rules loader's
    duplicate detection compares these instead of raw text."""
    if isinstance(ast, NumLit):
        return _fmt_num(ast.value)
    if isinstance(ast, StrLit):
        return _quote(ast.value)
    if isinstance(ast, Unary):
        return f"(-{ast_to_text(ast.expr)})"
    if isinstance(ast, Selector):
        parts = []
        for m in sorted(ast.matchers, key=lambda m: (m.label, m.op,
                                                     m.value)):
            parts.append(f"{m.label}{m.op}{_quote(m.value)}")
        name = ast.metric or ""
        if ast.column:
            name += f"::{ast.column}"
        out = name + ("{" + ",".join(parts) + "}" if parts else
                      ("{}" if not name else ""))
        if ast.window_ms is not None:
            out += f"[{_fmt_dur(ast.window_ms)}]"
        return out + _mods(ast)
    if isinstance(ast, Subquery):
        step = _fmt_dur(ast.step_ms) if ast.step_ms else ""
        return (f"{ast_to_text(ast.expr)}[{_fmt_dur(ast.window_ms)}:"
                f"{step}]" + _mods(ast))
    if isinstance(ast, Call):
        return (f"{ast.name}(" +
                ",".join(ast_to_text(a) for a in ast.args) + ")")
    if isinstance(ast, Agg):
        grp = ""
        if ast.by:
            grp = " by (" + ",".join(sorted(ast.by)) + ") "
        elif ast.without:
            grp = " without (" + ",".join(sorted(ast.without)) + ") "
        args = list(ast.params) + [ast.expr]
        return (f"{ast.op}{grp}(" +
                ",".join(ast_to_text(a) for a in args) + ")")
    if isinstance(ast, BinOp):
        mods = []
        if ast.return_bool:
            mods.append("bool")
        if ast.on is not None:
            mods.append("on(" + ",".join(sorted(ast.on)) + ")")
        elif ast.ignoring:
            mods.append("ignoring(" + ",".join(sorted(ast.ignoring)) + ")")
        if ast.group_left or ast.group_right:
            g = "group_left" if ast.group_left else "group_right"
            if ast.include:
                g += "(" + ",".join(sorted(ast.include)) + ")"
            mods.append(g)
        mid = " ".join([ast.op] + mods)
        return f"({ast_to_text(ast.lhs)} {mid} {ast_to_text(ast.rhs)})"
    raise ValueError(f"cannot render {type(ast).__name__}")


def _mods(ast) -> str:
    out = ""
    if getattr(ast, "offset_ms", 0):
        out += f" offset {_fmt_dur(ast.offset_ms)}"
    at = getattr(ast, "at_ms", None)
    if at is not None:
        out += f" @ {at}()" if at in ("start", "end") else \
            f" @ {at / 1000.0:g}"
    return out


def normalize_query(query: str) -> str:
    """Whitespace/comment/label-order-insensitive normal form of a
    query (parses, then renders canonically). Raises ParseError on
    invalid input."""
    return ast_to_text(Parser(query).parse())


def selector_to_filters(selector: str) -> Tuple[ColumnFilter, ...]:
    """Parse a bare series selector (`metric{label="x"}`) into column
    filters — the HTTP `match[]` parameter (PrometheusApiRoute series/
    labels endpoints)."""
    ast = Parser(selector).parse()
    if not isinstance(ast, Selector):
        raise ValueError(f"not a series selector: {selector}")
    return _matchers_to_filters(ast)
