"""Deliberately slow, obviously-correct pure-Python PromQL reference
evaluator.

The engine (:mod:`filodb_tpu.query.engine` + the device backends)
evaluates dense ``[series, steps]`` grids with vectorized prefix sums,
searchsorted window bounds, and fused device kernels — fast, but every
one of those transformations is a chance to drift from the semantics.
This module is the other arm of the differential rail: it evaluates the
SAME parsed AST with nothing but per-step Python loops over ``(ts,
value)`` sample lists, written to be auditable line-by-line against the
Prometheus semantics (inclusive windows, staleness lookback,
extrapolated rates with counter-reset correction, NaN propagation).

``tests/test_promql_differential.py`` runs generated well-typed queries
(:mod:`filodb_tpu.promql.gen`) through the real engine (oracle + cache
paths) and through this evaluator; any numeric discrepancy is a bug in
one of them and lands as a pinned regression test.

Scope: the generator's surface — selectors, the rate/over_time range
families, subqueries, sum/avg/min/max/count/group/stddev/stdvar
aggregations with by/without, topk/bottomk (per-step top-k selection
keeping the member series), scalar and vector binary operators
(incl. bool / filtering comparisons, and/or/unless, and
group_left/group_right many-to-one joins with include labels), the
pure instant functions, classic-bucket ``histogram_quantile`` (the
`le`-series join with Prometheus bucket interpolation), offsets,
scalar()/vector()/time(). sort/label_replace, native-histogram
columns and @-pinning remain engine-test territory.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from filodb_tpu.promql import parser as pp

NAN = float("nan")
INF = float("inf")

DEFAULT_LOOKBACK_MS = pp.DEFAULT_LOOKBACK_MS

_METRIC_LABELS = ("_metric_", "__name__")


class RefEvalError(Exception):
    """The reference evaluator hit a case outside its scope or an
    eval-time semantic error (many-to-many match, unknown function)."""


@dataclass
class RefSeries:
    """One input series: labels + sorted (ts_ms, value) samples."""
    labels: Dict[str, str]
    ts: List[int]
    values: List[float]


def _strip_metric(labels: Mapping[str, str]) -> Dict[str, str]:
    return {k: v for k, v in labels.items() if k not in _METRIC_LABELS}


def _key(labels: Mapping[str, str]) -> Tuple:
    return tuple(sorted(labels.items()))


@dataclass
class _Vec:
    """Instant-vector value: per-series rows on the shared step grid."""
    rows: List[Tuple[Dict[str, str], List[float]]] \
        = field(default_factory=list)


def _isnan(x: float) -> bool:
    return x != x


# ---------------------------------------------------------------------------
# scalar math with IEEE/numpy semantics
# ---------------------------------------------------------------------------

def _div(a: float, b: float) -> float:
    if _isnan(a) or _isnan(b):
        return NAN
    if b == 0.0:
        if a == 0.0:
            return NAN
        return math.copysign(INF, a) * math.copysign(1.0, b)
    try:
        return a / b
    except OverflowError:
        return math.copysign(INF, a) * math.copysign(1.0, b)


def _fmod(a: float, b: float) -> float:
    if _isnan(a) or _isnan(b) or b == 0.0 or math.isinf(a):
        return NAN
    return math.fmod(a, b)


def _pow(a: float, b: float) -> float:
    if _isnan(a) or _isnan(b):
        # numpy: 1 ** nan == 1, nan ** 0 == 1
        if a == 1.0:
            return 1.0
        if b == 0.0:
            return 1.0
        return NAN
    if a == 0.0 and b < 0:
        return INF
    try:
        return math.pow(a, b)
    except ValueError:          # (-8) ** 0.5 -> nan (numpy semantics)
        return NAN
    except OverflowError:
        odd_neg = a < 0 and float(b).is_integer() and int(b) % 2 == 1
        return -INF if odd_neg else INF


_ARITH = {
    "+": lambda a, b: a + b if not (_isnan(a) or _isnan(b)) else NAN,
    "-": lambda a, b: a - b if not (_isnan(a) or _isnan(b)) else NAN,
    "*": lambda a, b: a * b if not (_isnan(a) or _isnan(b)) else NAN,
    "/": _div,
    "%": _fmod,
    "^": _pow,
    "atan2": lambda a, b: NAN if (_isnan(a) or _isnan(b))
    else math.atan2(a, b),
}

_COMP = {
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
    ">": lambda a, b: a > b, "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b, "<=": lambda a, b: a <= b,
}


def _apply_op(op: str, a: float, b: float, return_bool: bool,
              keep: Optional[float] = None) -> float:
    """One sample of the engine's ``_apply_op``. ``keep`` is the value
    a filtering comparison retains (the VECTOR side's sample; defaults
    to ``a`` — the engine's vector-vector join semantics)."""
    if op in _ARITH:
        # mirror numpy: inf - inf = nan, 0 * inf = nan arise naturally
        try:
            return _ARITH[op](a, b)
        except OverflowError:
            return INF
    if op in _COMP:
        if return_bool:
            if _isnan(a) or _isnan(b):
                return NAN
            return 1.0 if _COMP[op](a, b) else 0.0
        m = (not _isnan(a)) and (not _isnan(b)) and _COMP[op](a, b)
        return (a if keep is None else keep) if m else NAN
    raise RefEvalError(f"unknown binary op {op}")


# ---------------------------------------------------------------------------
# windowed range functions — per-window sample-list loops
# ---------------------------------------------------------------------------

def _in_window(ts: List[int], vals: List[float], ws: int, we: int
               ) -> Tuple[List[int], List[float]]:
    ot, ov = [], []
    for t, v in zip(ts, vals):
        if ws <= t <= we:       # inclusive both ends (reference default)
            ot.append(t)
            ov.append(v)
    return ot, ov


def _drop_nan(ts: List[int], vals: List[float]
              ) -> Tuple[List[int], List[float]]:
    ot, ov = [], []
    for t, v in zip(ts, vals):
        if not _isnan(v):
            ot.append(t)
            ov.append(v)
    return ot, ov


def _corrected(vals: List[float]) -> List[float]:
    """Counter-reset corrected values (memory.vectors.counter_correction
    semantics over an already NaN-free list): each drop adds the
    pre-drop value to every later sample."""
    out = []
    corr = 0.0
    prev = None
    for v in vals:
        if prev is not None and v < prev:
            corr += prev
        out.append(v + corr)
        prev = v
    return out


def _extrapolated(ws: int, we: int, sts: List[int], svs: List[float],
                  is_counter: bool, is_rate: bool) -> float:
    """Prometheus extrapolation (RateFunctions.scala extrapolatedRate),
    one window at a time. ``svs`` are already reset-corrected."""
    if len(sts) < 2:
        return NAN
    first_ts, first_val = sts[0], svs[0]
    last_ts, last_val = sts[-1], svs[-1]
    duration_to_start = (first_ts - ws) / 1000.0
    duration_to_end = (we - last_ts) / 1000.0
    sampled_interval = (last_ts - first_ts) / 1000.0
    if sampled_interval == 0:
        return NAN
    avg_duration = sampled_interval / (len(sts) - 1)
    delta = last_val - first_val
    if is_counter and delta > 0 and first_val >= 0:
        duration_to_zero = sampled_interval * (first_val / delta)
        duration_to_start = min(duration_to_start, duration_to_zero)
    threshold = avg_duration * 1.1
    extrap = sampled_interval \
        + (duration_to_start if duration_to_start < threshold
           else avg_duration / 2.0) \
        + (duration_to_end if duration_to_end < threshold
           else avg_duration / 2.0)
    scaled = delta * (extrap / sampled_interval)
    if is_rate:
        scaled = scaled / (we - ws) * 1000.0
    return scaled


def _mean(xs: List[float]) -> float:
    return sum(xs) / len(xs)


def _variance(xs: List[float]) -> float:
    m = _mean(xs)
    return max(sum((x - m) ** 2 for x in xs) / len(xs), 0.0)


def eval_range_fn(func: str, ts: List[int], vals: List[float],
                  ws: int, we: int) -> float:
    """One range function over one window of one series. ``ts``/``vals``
    are the series' full (clipped) samples; correction for the rate
    family accumulates from the start of the clipped span, exactly like
    the engine applies ``counter_correction`` to the clipped array."""
    if func == "last_sample":
        # instant lookback: NaN (stale-marker) samples are NOT dropped
        last = None
        for t, v in zip(ts, vals):
            if ws <= t <= we:
                last = v
        return NAN if last is None else last
    cts, cvs = _drop_nan(ts, vals)
    if func in ("rate", "increase", "delta"):
        use = _corrected(cvs) if func != "delta" else cvs
        sts, svs = _in_window(cts, use, ws, we)
        return _extrapolated(ws, we, sts, svs, func != "delta",
                             func == "rate")
    sts, svs = _in_window(cts, cvs, ws, we)
    n = len(sts)
    if func in ("irate", "idelta"):
        if n < 2:
            return NAN
        dv = svs[-1] - svs[-2]
        if func == "idelta":
            return dv
        if dv < 0:
            dv = svs[-1]        # counter reset: raw last value
        dt = (sts[-1] - sts[-2]) / 1000.0
        return NAN if dt == 0 else dv / dt
    if func == "present_over_time":
        return 1.0 if n else NAN
    if n == 0:
        return NAN
    if func == "sum_over_time":
        return sum(svs)
    if func == "count_over_time":
        return float(n)
    if func == "avg_over_time":
        return _mean(svs)
    if func == "min_over_time":
        return min(svs)
    if func == "max_over_time":
        return max(svs)
    if func == "last_over_time":
        return svs[-1]
    if func == "first_over_time":
        return svs[0]
    if func == "stddev_over_time":
        return math.sqrt(_variance(svs))
    if func == "stdvar_over_time":
        return _variance(svs)
    if func == "changes":
        return float(sum(1 for i in range(1, n)
                         if svs[i] != svs[i - 1]))
    if func == "resets":
        return float(sum(1 for i in range(1, n)
                         if svs[i] < svs[i - 1]))
    if func == "deriv":
        return _linreg(sts, svs)[0]
    raise RefEvalError(f"range function {func} outside refeval scope")


def _linreg(sts: List[int], svs: List[float]) -> Tuple[float, float]:
    """Least-squares slope/intercept over (seconds-since-first, value)
    (the engine's _deriv_predict loop)."""
    if len(sts) < 2:
        return NAN, NAN
    t = [(x / 1000.0) for x in sts]
    t0 = [x - t[0] for x in t]
    tm = _mean(t0)
    vm = _mean(svs)
    cov = sum((a - tm) * (b - vm) for a, b in zip(t0, svs))
    var = sum((a - tm) ** 2 for a in t0)
    if var == 0:
        return NAN, NAN
    slope = cov / var
    return slope, vm - slope * tm


# ---------------------------------------------------------------------------
# instant functions
# ---------------------------------------------------------------------------

def _round_engine(v: float, to_nearest: float) -> float:
    if _isnan(v):
        return NAN
    return math.floor(v / to_nearest + 0.5) * to_nearest


def eval_instant_fn(func: str, v: float, args: Sequence[float]) -> float:
    if func == "round":
        return _round_engine(v, float(args[0]) if args else 1.0)
    if func == "clamp":
        lo, hi = float(args[0]), float(args[1])
        return NAN if _isnan(v) else min(max(v, lo), hi)
    if func == "clamp_min":
        return NAN if _isnan(v) else max(v, float(args[0]))
    if func == "clamp_max":
        return NAN if _isnan(v) else min(v, float(args[0]))
    if _isnan(v):
        return NAN
    if func == "abs":
        return abs(v)
    if func == "ceil":
        return float(math.ceil(v)) if not math.isinf(v) else v
    if func == "floor":
        return float(math.floor(v)) if not math.isinf(v) else v
    if func == "sqrt":
        return math.sqrt(v) if v >= 0 else NAN
    if func == "exp":
        try:
            return math.exp(v)
        except OverflowError:
            return INF
    if func == "ln":
        return math.log(v) if v > 0 else (-INF if v == 0 else NAN)
    if func == "sgn":
        return 0.0 if v == 0 else math.copysign(1.0, v)
    raise RefEvalError(f"instant function {func} outside refeval scope")


def _bucket_quantile(q: float, les: List[float],
                     cum: List[float]) -> float:
    """Prometheus bucketQuantile over one cumulative histogram column
    (pure-Python mirror of memory/histogram.quantile — the engine's
    bucket math — audited against Histogram.scala:17)."""
    if not 0 <= q <= 1:
        return INF if q > 1 else -INF
    if len(les) < 2:
        return NAN
    total = cum[-1]
    if total == 0 or _isnan(total):
        return NAN
    rank = q * total
    b = 0                               # searchsorted(cum, rank, 'left')
    while b < len(cum) and cum[b] < rank:
        b += 1
    b = min(b, len(les) - 1)
    if b == len(les) - 1:
        return float(les[-2])
    if b == 0 and les[0] <= 0:
        return float(les[0])
    bucket_start = 0.0 if b == 0 else float(les[b - 1])
    bucket_end = float(les[b])
    count_start = 0.0 if b == 0 else float(cum[b - 1])
    count_end = float(cum[b])
    if count_end == count_start:
        return bucket_end
    return bucket_start + (bucket_end - bucket_start) * \
        (rank - count_start) / (count_end - count_start)


# ---------------------------------------------------------------------------
# the evaluator
# ---------------------------------------------------------------------------

class RefEvaluator:
    def __init__(self, series: Sequence[RefSeries], start_s: int,
                 step_s: int, end_s: int,
                 lookback_ms: int = DEFAULT_LOOKBACK_MS):
        self.series = list(series)
        self.start_ms = start_s * 1000
        self.step_ms = max(step_s, 1) * 1000
        self.end_ms = end_s * 1000
        self.lookback_ms = lookback_ms
        self.grid = list(range(self.start_ms, self.end_ms + 1,
                               self.step_ms))

    # -- selection -------------------------------------------------------
    def _match(self, sel: pp.Selector, labels: Mapping[str, str]) -> bool:
        if sel.metric is not None and \
                labels.get("_metric_") != sel.metric:
            return False
        for m in sel.matchers:
            lbl = "_metric_" if m.label == "__name__" else m.label
            val = labels.get(lbl, "")
            if m.op == "=":
                if val != m.value:
                    return False
            elif m.op == "!=":
                if val == m.value:
                    return False
            elif m.op == "=~":
                if re.fullmatch(m.value, val) is None:
                    return False
            elif m.op == "!~":
                if re.fullmatch(m.value, val) is not None:
                    return False
        return True

    def _select(self, sel: pp.Selector) -> List[RefSeries]:
        return [s for s in self.series if self._match(sel, s.labels)]

    # -- entry -----------------------------------------------------------
    def eval(self, node) -> _Vec:
        out = self._eval(node, self.grid)
        if isinstance(out, _Vec):
            return out
        # bare scalar expression: the engine returns a ScalarResult;
        # surface it as one anonymous row for comparison
        return _Vec([({}, out)])

    def _eval(self, node, grid: List[int]):
        """-> _Vec or List[float] (scalar-per-step) or str."""
        if isinstance(node, pp.NumLit):
            return [node.value] * len(grid)
        if isinstance(node, pp.StrLit):
            return node.value
        if isinstance(node, pp.Unary):
            inner = self._eval(node.expr, grid)
            if isinstance(inner, _Vec):
                return _Vec([(_strip_metric(l),
                              [_apply_op("-", 0.0, v, False)
                               for v in row])
                             for l, row in inner.rows])
            return [_apply_op("-", 0.0, v, False) for v in inner]
        if isinstance(node, pp.Selector):
            if node.window_ms is not None:
                raise RefEvalError("bare range vector")
            return self._instant_selector(node, grid)
        if isinstance(node, pp.Call):
            return self._call(node, grid)
        if isinstance(node, pp.Agg):
            return self._agg(node, grid)
        if isinstance(node, pp.BinOp):
            return self._binop(node, grid)
        raise RefEvalError(f"node {type(node).__name__} outside scope")

    # -- selectors -------------------------------------------------------
    def _instant_selector(self, sel: pp.Selector, grid: List[int]
                          ) -> _Vec:
        rows = []
        off = sel.offset_ms
        for s in self._select(sel):
            vals = []
            for t in grid:
                we = t - off
                ws = we - self.lookback_ms
                vals.append(eval_range_fn("last_sample", s.ts, s.values,
                                          ws, we))
            rows.append((dict(s.labels), vals))
        return _Vec(rows)

    def _range_series(self, sel: pp.Selector, grid: List[int],
                      func: str) -> _Vec:
        """Range function over a [window] selector: samples clipped to
        the engine's fetch span so rate-family correction accumulates
        over the same prefix."""
        rows = []
        w = sel.window_ms
        off = sel.offset_ms
        clip_lo = grid[0] - w - off
        clip_hi = grid[-1] - off if off else grid[-1]
        for s in self._select(sel):
            ts, vs = [], []
            for t, v in zip(s.ts, s.values):
                if clip_lo <= t <= clip_hi:
                    ts.append(t)
                    vs.append(v)
            vals = []
            for t in grid:
                we = t - off
                ws = we - w
                vals.append(eval_range_fn(func, ts, vs, ws, we))
            rows.append((dict(s.labels), vals))
        return _Vec(rows)

    # -- calls -----------------------------------------------------------
    def _call(self, node: pp.Call, grid: List[int]):
        name = node.name
        if name == "time":
            return [t / 1000.0 for t in grid]
        if name == "pi":
            return [math.pi] * len(grid)
        if name == "scalar":
            v = self._eval(node.args[0], grid)
            if not isinstance(v, _Vec):
                return v
            out = []
            for i in range(len(grid)):
                present = [row[i] for _, row in v.rows
                           if not _isnan(row[i])]
                if len(v.rows) == 1:
                    out.append(v.rows[0][1][i])
                elif len(present) == 1:
                    out.append(present[0])
                else:
                    out.append(NAN)
            return out
        if name == "vector":
            s = self._eval(node.args[0], grid)
            return _Vec([({}, list(s))])
        if name in pp.RANGE_FN_NAMES:
            return self._range_call(node, grid)
        if name == "histogram_quantile":
            return self._histogram_quantile(node, grid)
        if name in pp.INSTANT_FNS:
            return self._instant_call(node, grid)
        raise RefEvalError(f"function {name} outside refeval scope")

    def _histogram_quantile(self, node: pp.Call, grid: List[int]) -> _Vec:
        """Classic per-bucket series: join series sharing all labels
        except `le` into one cumulative histogram per step (the
        engine's _quantile_over_le_series — stale bucket samples
        dropped per step, +Inf bucket required, running-max
        monotonicity, Prometheus bucket interpolation)."""
        q_steps = self._eval(node.args[0], grid)
        if isinstance(q_steps, (_Vec, str)):
            raise RefEvalError("histogram_quantile non-scalar q")
        q = q_steps[0]
        v = self._eval(node.args[1], grid)
        if not isinstance(v, _Vec):
            raise RefEvalError("histogram_quantile over a scalar")
        groups: Dict[Tuple, List[Tuple[float, List[float]]]] = {}
        order: List[Tuple] = []
        for labels, row in v.rows:
            le_s = labels.get("le")
            if le_s is None:
                continue        # non-bucket series ignored (engine too)
            try:
                le = float(str(le_s).replace("+Inf", "inf"))
            except ValueError:
                continue
            base = tuple(sorted((k, val) for k, val
                                in _strip_metric(labels).items()
                                if k != "le"))
            if base not in groups:
                groups[base] = []
                order.append(base)
            groups[base].append((le, row))
        if not groups:
            raise RefEvalError("histogram_quantile requires per-bucket "
                               "series with an 'le' label")
        rows = []
        for base in order:
            members = sorted(groups[base], key=lambda m: m[0])
            les = [m[0] for m in members]
            vals = []
            for i in range(len(grid)):
                col = [(le, r[i]) for le, r in members
                       if not _isnan(r[i])]
                if not col:
                    vals.append(NAN)
                    continue
                lc = [le for le, _x in col]
                if not math.isinf(lc[-1]) or lc[-1] < 0:
                    vals.append(NAN)    # no +Inf sample: NaN
                    continue
                # running max down the buckets (ensureMonotonic)
                cum, run = [], -INF
                for _le, x in col:
                    run = max(run, x)
                    cum.append(run)
                vals.append(_bucket_quantile(q, lc, cum))
            rows.append((dict(base), vals))
        return _Vec(rows)

    def _range_call(self, node: pp.Call, grid: List[int]) -> _Vec:
        name = node.name
        func = pp.RANGE_FN_NAMES[name]
        args = list(node.args)
        if name in pp.RANGE_FN_SCALAR_FIRST:
            args.pop(0)
        if name in pp.RANGE_FN_SCALAR_AFTER:
            args = args[:1]
        rv = args[0]
        if isinstance(rv, pp.Selector):
            return self._range_series(rv, grid, func)
        if isinstance(rv, pp.Subquery):
            return self._subquery(rv, grid, func)
        raise RefEvalError(f"{name} over non-range argument")

    def _subquery(self, sq: pp.Subquery, grid: List[int], func: str
                  ) -> _Vec:
        """func(expr[w:s]): evaluate the inner on the subquery grid,
        then window over the inner step series (the engine's
        _subquery path; inner NaN steps are dropped)."""
        w, off = sq.window_ms, sq.offset_ms
        sub_step = sq.step_ms if sq.step_ms else self.step_ms
        inner_start = grid[0] - w - off
        inner_end = grid[-1] - off if off else grid[-1]
        inner_grid = list(range(inner_start, inner_end + 1, sub_step))
        inner = self._eval(sq.expr, inner_grid)
        if not isinstance(inner, _Vec):
            raise RefEvalError("scalar subquery outside scope")
        rows = []
        for labels, row in inner.rows:
            ts = [t for t, v in zip(inner_grid, row) if not _isnan(v)]
            vs = [v for v in row if not _isnan(v)]
            vals = []
            for t in grid:
                we = t - off
                ws = we - w
                vals.append(eval_range_fn(func, ts, vs, ws, we))
            rows.append((dict(labels), vals))
        return _Vec(rows)

    def _instant_call(self, node: pp.Call, grid: List[int]) -> _Vec:
        name = node.name
        v = self._eval(node.args[0], grid)
        if not isinstance(v, _Vec):
            raise RefEvalError(f"{name} over a scalar outside scope")
        args = []
        for a in node.args[1:]:
            sv = self._eval(a, grid)
            if isinstance(sv, (_Vec, str)):
                raise RefEvalError(f"{name} non-scalar parameter")
            args.append(sv[0])
        return _Vec([(_strip_metric(labels),
                      [eval_instant_fn(name, x, args) for x in row])
                     for labels, row in v.rows])

    # -- aggregation -----------------------------------------------------
    def _agg(self, node: pp.Agg, grid: List[int]) -> _Vec:
        inner = self._eval(node.expr, grid)
        if not isinstance(inner, _Vec):
            raise RefEvalError("aggregation over a scalar")
        op = node.op
        if op in ("topk", "bottomk"):
            return self._topk(node, inner, grid, bottom=(op == "bottomk"))
        groups: Dict[Tuple, Tuple[Dict[str, str], List[List[float]]]] = {}
        order: List[Tuple] = []
        for labels, row in inner.rows:
            l2 = _strip_metric(labels)
            if node.by:
                gk = {l: l2[l] for l in node.by if l in l2}
            elif node.without:
                gk = {l: v for l, v in l2.items()
                      if l not in node.without}
            else:
                gk = {}
            k = _key(gk)
            if k not in groups:
                groups[k] = (gk, [])
                order.append(k)
            groups[k][1].append(row)
        rows = []
        for k in order:
            gk, members = groups[k]
            vals = []
            for i in range(len(grid)):
                xs = [row[i] for row in members if not _isnan(row[i])]
                vals.append(self._agg_step(op, xs))
            rows.append((gk, vals))
        return _Vec(rows)

    def _topk(self, node: pp.Agg, inner: _Vec, grid: List[int],
              bottom: bool) -> _Vec:
        """topk/bottomk: per step, keep the k best series per group;
        output is the union of selected series (FULL labels, like the
        engine's TopBottomK) with NaN at unselected steps."""
        if not node.params:
            raise RefEvalError(f"{node.op} requires a k parameter")
        p = node.params[0]
        if not isinstance(p, pp.NumLit):
            raise RefEvalError(f"{node.op} non-literal k outside scope")
        k = int(p.value)
        # group like the engine (stripped labels), keep member rows
        groups: Dict[Tuple, List[int]] = {}
        order: List[Tuple] = []
        for i, (labels, _row) in enumerate(inner.rows):
            l2 = _strip_metric(labels)
            if node.by:
                gk = tuple(sorted((l, l2[l]) for l in node.by
                                  if l in l2))
            elif node.without:
                gk = tuple(sorted((l, v) for l, v in l2.items()
                                  if l not in node.without))
            else:
                gk = ()
            if gk not in groups:
                groups[gk] = []
                order.append(gk)
            groups[gk].append(i)
        rows = []
        for gk in order:
            idx = groups[gk]
            keep = {i: [False] * len(grid) for i in idx}
            for t in range(len(grid)):
                present = [(inner.rows[i][1][t], i) for i in idx
                           if not _isnan(inner.rows[i][1][t])]
                # stable per-step selection: best value first, input
                # order breaks ties (the engine's stable argsort)
                present.sort(key=lambda pv: pv[0],
                             reverse=not bottom)
                for _v, i in present[:k]:
                    keep[i][t] = True
            for i in idx:
                if any(keep[i]):
                    labels, row = inner.rows[i]
                    rows.append((dict(labels),
                                 [x if keep[i][t] else NAN
                                  for t, x in enumerate(row)]))
        return _Vec(rows)

    @staticmethod
    def _agg_step(op: str, xs: List[float]) -> float:
        if not xs:
            return NAN
        if op == "sum":
            return sum(xs)
        if op == "count":
            return float(len(xs))
        if op == "avg":
            return sum(xs) / len(xs)
        if op == "min":
            return min(xs)
        if op == "max":
            return max(xs)
        if op == "group":
            return 1.0
        if op == "stddev":
            return math.sqrt(_variance(xs))
        if op == "stdvar":
            return _variance(xs)
        raise RefEvalError(f"aggregation {op} outside refeval scope")

    # -- binary operators -------------------------------------------------
    def _binop(self, node: pp.BinOp, grid: List[int]):
        lhs = self._eval(node.lhs, grid)
        rhs = self._eval(node.rhs, grid)
        lvec = isinstance(lhs, _Vec)
        rvec = isinstance(rhs, _Vec)
        op = node.op
        if op in ("and", "or", "unless"):
            return self._set_op(op, lhs, rhs, node)
        if not lvec and not rvec:
            # scalar-scalar: the engine evaluates comparisons as bool
            rb = op in _COMP or node.return_bool
            return [_apply_op(op, a, b, rb)
                    for a, b in zip(lhs, rhs)]
        if lvec != rvec:
            vec, sc = (lhs, rhs) if lvec else (rhs, lhs)
            rows = []
            for labels, row in vec.rows:
                out = []
                for i, x in enumerate(row):
                    a, b = (sc[i], x) if not lvec else (x, sc[i])
                    out.append(_apply_op(op, a, b, node.return_bool,
                                         keep=x))
                rows.append((_strip_metric(labels), out))
            return _Vec(rows)
        return self._vector_join(node, lhs, rhs)

    def _join_key(self, labels: Mapping[str, str],
                  on: Optional[Tuple[str, ...]],
                  ignoring: Tuple[str, ...]) -> Tuple:
        l2 = _strip_metric(labels)
        if on is not None:
            return tuple(sorted((k, v) for k, v in l2.items()
                                if k in on))
        return tuple(sorted((k, v) for k, v in l2.items()
                            if k not in ignoring))

    def _vector_join(self, node: pp.BinOp, lhs: _Vec, rhs: _Vec) -> _Vec:
        if node.group_left or node.group_right:
            return self._grouped_join(node, lhs, rhs)
        rmap: Dict[Tuple, Tuple[Dict[str, str], List[float]]] = {}
        for labels, row in rhs.rows:
            k = self._join_key(labels, node.on, node.ignoring)
            if k in rmap:
                raise RefEvalError("many-to-many: duplicate right side")
            rmap[k] = (labels, row)
        rows = []
        seen = set()
        for labels, row in lhs.rows:
            k = self._join_key(labels, node.on, node.ignoring)
            got = rmap.get(k)
            if got is None:
                continue
            if k in seen:
                raise RefEvalError("many-to-many: duplicate left side")
            seen.add(k)
            out = [_apply_op(node.op, a, b, node.return_bool)
                   for a, b in zip(row, got[1])]
            rows.append((_strip_metric(labels), out))
        return _Vec(rows)

    def _grouped_join(self, node: pp.BinOp, lhs: _Vec, rhs: _Vec) -> _Vec:
        """Many-to-one / one-to-many join (the engine's BinaryJoinExec
        grouped path): operands keep their ORIGINAL sides, output
        labels come from the 'many' side, include labels are copied
        from the 'one' side (or dropped when absent there), and a
        duplicate series on the 'one' side is a many-to-many error."""
        many, one = (lhs, rhs) if node.group_left else (rhs, lhs)
        omap: Dict[Tuple, Tuple[Dict[str, str], List[float]]] = {}
        for labels, row in one.rows:
            k = self._join_key(labels, node.on, node.ignoring)
            if k in omap:
                raise RefEvalError(
                    "many-to-many join: duplicate series on 'one' side")
            omap[k] = (labels, row)
        rows = []
        for labels, row in many.rows:
            k = self._join_key(labels, node.on, node.ignoring)
            got = omap.get(k)
            if got is None:
                continue
            if node.group_left:
                a_row, b_row = row, got[1]
            else:
                a_row, b_row = got[1], row
            out = [_apply_op(node.op, a, b, node.return_bool)
                   for a, b in zip(a_row, b_row)]
            l2 = dict(_strip_metric(labels))
            for l in node.include:
                if l in got[0]:
                    l2[l] = got[0][l]
                else:
                    l2.pop(l, None)
            rows.append((l2, out))
        return _Vec(rows)

    def _set_op(self, op: str, lhs, rhs, node: pp.BinOp) -> _Vec:
        if not isinstance(lhs, _Vec) or not isinstance(rhs, _Vec):
            raise RefEvalError("set op on scalar operand")
        rkeys = {self._join_key(l, node.on, node.ignoring): row
                 for l, row in rhs.rows}
        rows = []
        if op == "and":
            for labels, row in lhs.rows:
                rrow = rkeys.get(self._join_key(labels, node.on,
                                                node.ignoring))
                if rrow is None:
                    continue
                rows.append((dict(labels),
                             [v if not _isnan(r) else NAN
                              for v, r in zip(row, rrow)]))
        elif op == "unless":
            for labels, row in lhs.rows:
                rrow = rkeys.get(self._join_key(labels, node.on,
                                                node.ignoring))
                if rrow is None:
                    rows.append((dict(labels), list(row)))
                else:
                    rows.append((dict(labels),
                                 [v if _isnan(r) else NAN
                                  for v, r in zip(row, rrow)]))
        else:   # or
            lkeys = set()
            for labels, row in lhs.rows:
                lkeys.add(self._join_key(labels, node.on, node.ignoring))
                rows.append((dict(labels), list(row)))
            for labels, row in rhs.rows:
                if self._join_key(labels, node.on,
                                  node.ignoring) not in lkeys:
                    rows.append((dict(labels), list(row)))
        return _Vec(rows)


def ref_eval(query: str, series: Sequence[RefSeries], start_s: int,
             step_s: int, end_s: int,
             lookback_ms: int = DEFAULT_LOOKBACK_MS
             ) -> Dict[Tuple, List[float]]:
    """Evaluate ``query`` over ``series`` on the [start, step, end]
    second grid; returns {sorted-label-items tuple: per-step values}."""
    ast = pp.Parser(query).parse()
    ev = RefEvaluator(series, start_s, step_s, end_s, lookback_ms)
    vec = ev.eval(ast)
    out: Dict[Tuple, List[float]] = {}
    for labels, row in vec.rows:
        k = _key(labels)
        if k in out:
            raise RefEvalError(f"duplicate output series {k}")
        out[k] = row
    return out
