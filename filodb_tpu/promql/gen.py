"""Property-based PromQL generator — well-typed by construction.

Generates random queries THROUGH the promlint type rules
(:mod:`filodb_tpu.promql.semant`): every production site knows the type
it must produce (instant vector / range vector / scalar), counter
metrics feed the rate family and gauges feed the gauge family, binary
joins are built so the match is provably one-to-one, and every emitted
query is double-checked against the analyzer (zero error-severity
findings) and the parser's plan builder before it leaves this module —
a generator bug fails loudly here, not as a mystery discrepancy
downstream.

Determinism: seeded ``random.Random``; the same ``(seed, metrics)``
yields the same query list on every run, so the differential soak
(tests/test_promql_differential.py) is reproducible and a discrepancy
can be pinned by (seed, index) alone.

The function surface deliberately matches what
:mod:`filodb_tpu.promql.refeval` implements — growing one without the
other trips the generator's self-check or the soak immediately.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from filodb_tpu.promql import semant
from filodb_tpu.promql.parser import (Parser, TimeStepParams,
                                      parse_query_range)


@dataclass(frozen=True)
class MetricSpec:
    """One generatable metric: name, schema kind, label universe."""
    name: str
    kind: str                                   # "counter" | "gauge"
    labels: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()

    def label_names(self) -> List[str]:
        return [l for l, _vals in self.labels]


DEFAULT_METRICS: Tuple[MetricSpec, ...] = (
    MetricSpec("http_requests_total", "counter",
               (("job", ("api", "web")),
                ("instance", ("i0", "i1", "i2")))),
    MetricSpec("errors_total", "counter",
               (("job", ("api", "web")),
                ("instance", ("i0", "i1", "i2")))),
    MetricSpec("cpu_usage", "gauge",
               (("job", ("api", "web")),
                ("instance", ("i0", "i1", "i2")))),
    MetricSpec("queue_depth", "gauge",
               (("job", ("api",)),
                ("instance", ("i0", "i1", "i2")))),
)

# classic-bucket histogram metric: generated histogram_quantile shapes
# select it WITHOUT an `le` matcher so every bucket set stays complete
DEFAULT_HISTOGRAM = MetricSpec(
    "http_request_duration_seconds_bucket", "counter",
    (("job", ("api", "web")), ("instance", ("i0", "i1"))))
_HIST_QS = ("0.5", "0.9", "0.95", "0.99")

_COUNTER_FNS = ("rate", "increase", "irate", "resets", "changes")
_GAUGE_FNS = ("delta", "idelta", "deriv", "avg_over_time",
              "min_over_time", "max_over_time", "sum_over_time",
              "stddev_over_time", "stdvar_over_time", "changes")
_ANY_OVER_TIME = ("last_over_time", "first_over_time",
                  "count_over_time", "present_over_time")
_AGG_OPS = ("sum", "avg", "min", "max", "count", "group",
            "stddev", "stdvar")
_INSTANT_FNS = ("abs", "ceil", "floor", "sqrt", "sgn", "round",
                "clamp_min", "clamp_max", "clamp")
_ARITH_OPS = ("+", "-", "*", "/", "%", "^")
_CMP_OPS = ("==", "!=", ">", "<", ">=", "<=")
_SET_OPS = ("and", "or", "unless")
_WINDOWS = ("1m", "90s", "2m", "5m")
_SUB_WINDOWS = ("4m", "6m", "10m")
_SUB_STEPS = ("30s", "1m")
_OFFSETS = ("1m", "2m")
_SUBQ_FNS = ("avg_over_time", "max_over_time", "min_over_time",
             "sum_over_time", "last_over_time", "count_over_time")


class QueryGen:
    """Seeded well-typed query generator over a metric universe."""

    _HIST_DEFAULT = object()    # sentinel: follow the metric universe

    def __init__(self, seed: int = 0,
                 metrics: Sequence[MetricSpec] = DEFAULT_METRICS,
                 max_depth: int = 3, validate: bool = True,
                 histogram=_HIST_DEFAULT):
        self.rng = random.Random(seed)
        self.metrics = list(metrics)
        # the default bucket metric rides only the DEFAULT universe; a
        # custom universe opts in by passing histogram= explicitly
        if histogram is QueryGen._HIST_DEFAULT:
            histogram = DEFAULT_HISTOGRAM \
                if tuple(metrics) == DEFAULT_METRICS else None
        self.histogram: Optional[MetricSpec] = histogram
        self.max_depth = max_depth
        self.validate = validate
        known = {m.name: m.kind for m in self.metrics}
        if histogram is not None:
            known[histogram.name] = histogram.kind
        self.schemas = semant.MetricSchemas(known)
        # the validation range only needs to typecheck plan building
        self._params = TimeStepParams(1_600_000_000, 30, 1_600_000_600)

    # -- helpers ---------------------------------------------------------
    def _pick(self, xs):
        return xs[self.rng.randrange(len(xs))]

    def _metric(self, kind: Optional[str] = None) -> MetricSpec:
        pool = [m for m in self.metrics
                if kind is None or m.kind == kind]
        # a single-kind universe still generates: fall back to any
        # metric (the production sites re-check the actual kind)
        return self._pick(pool or self.metrics)

    def _scalar_lit(self) -> str:
        return self._pick(("0.5", "1", "2", "5", "10", "0.25", "100"))

    def _selector(self, m: MetricSpec, window: Optional[str] = None
                  ) -> str:
        parts = []
        for label, vals in m.labels:
            r = self.rng.random()
            if r < 0.25:
                parts.append(f'{label}="{self._pick(vals)}"')
            elif r < 0.35 and len(vals) > 1:
                alt = "|".join(
                    sorted(self.rng.sample(list(vals),
                                           self.rng.randrange(
                                               2, len(vals) + 1))))
                parts.append(f'{label}=~"{alt}"')
            elif r < 0.42:
                parts.append(f'{label}!="{self._pick(vals)}"')
        sel = m.name + ("{" + ",".join(parts) + "}" if parts else "")
        if window:
            sel += f"[{window}]"
        if self.rng.random() < 0.15:
            sel += f" offset {self._pick(_OFFSETS)}"
        return sel

    # -- productions -----------------------------------------------------
    def _range_fn_expr(self, depth: int) -> str:
        """range_fn(selector[w]) or fn(<instant expr>[w:s])."""
        if depth > 0 and self.rng.random() < 0.2:
            inner = self._vector(depth - 1, allow_binop=False)
            w = self._pick(_SUB_WINDOWS)
            s = self._pick(_SUB_STEPS) if self.rng.random() < 0.8 else ""
            return f"{self._pick(_SUBQ_FNS)}({inner}[{w}:{s}])"
        m = self._metric()
        if m.kind == "counter":
            fn = self._pick(_COUNTER_FNS + _ANY_OVER_TIME)
        else:
            fn = self._pick(_GAUGE_FNS + _ANY_OVER_TIME)
        return f"{fn}({self._selector(m, self._pick(_WINDOWS))})"

    def _agg_expr(self, depth: int) -> str:
        inner = self._vector(depth - 1)
        op = self._pick(_AGG_OPS)
        m_labels = sorted({l for m in self.metrics
                           for l in m.label_names()})
        r = self.rng.random()
        if r < 0.45:
            k = self.rng.randrange(1, len(m_labels) + 1)
            by = ",".join(sorted(self.rng.sample(m_labels, k)))
            return f"{op} by ({by}) ({inner})"
        if r < 0.65:
            drop = self._pick(m_labels)
            return f"{op} without ({drop}) ({inner})"
        return f"{op}({inner})"

    def _binop_expr(self, depth: int) -> str:
        r = self.rng.random()
        if r < 0.45:
            # vector <op> scalar (either side)
            v = self._vector(depth - 1, allow_binop=False)
            s = self._scalar_lit()
            if self.rng.random() < 0.6:
                op = self._pick(_ARITH_OPS)
                return f"({v} {op} {s})" if self.rng.random() < 0.7 \
                    else f"({s} {op} {v})"
            op = self._pick(_CMP_OPS)
            b = "bool " if self.rng.random() < 0.4 else ""
            return f"({v} {op} {b}{s})" if self.rng.random() < 0.7 \
                else f"({s} {op} {b}{v})"
        if r < 0.8:
            # same-metric two-sided op: both sides select the SAME
            # series set, so the full-label-set match is one-to-one
            m = self._metric()
            sel = self._selector(m)
            if m.kind == "counter":
                lhs = f"{self._pick(_COUNTER_FNS)}({sel}[{self._pick(_WINDOWS)}])"
                rhs = f"{self._pick(_COUNTER_FNS)}({sel}[{self._pick(_WINDOWS)}])"
            else:
                lhs = sel
                rhs = f"avg_over_time({sel}[{self._pick(_WINDOWS)}])"
            if self.rng.random() < 0.3:
                op = self._pick(_CMP_OPS)
                b = "bool " if self.rng.random() < 0.5 else ""
                return f"({lhs} {op} {b}{rhs})"
            op = self._pick(_ARITH_OPS)
            return f"({lhs} {op} {rhs})"
        if r < 0.92:
            # closed-set join: agg by (L) on both sides, matched on(L)
            labels = ("job",) if self.rng.random() < 0.5 \
                else ("instance",)
            ls = ",".join(labels)
            lhs = f"sum by ({ls}) ({self._vector(depth - 1, allow_binop=False)})"
            rhs = f"sum by ({ls}) ({self._vector(depth - 1, allow_binop=False)})"
            op = self._pick(_ARITH_OPS)
            on = f" on ({ls}) " if self.rng.random() < 0.6 else " "
            return f"({lhs} {op}{on}{rhs})"
        # set op between selectors of the same metric
        m = self._metric()
        op = self._pick(_SET_OPS)
        return (f"({self._selector(m)} {op} "
                f"{self._selector(m)})")

    def _histogram_expr(self, depth: int) -> str:
        """histogram_quantile over the classic-bucket metric: the
        float-compare + bucket-interpolation shape. The inner is
        rate()/increase() on the bucket counters, optionally re-summed
        by (le, ...) — `le` always survives so every group keeps a
        complete cumulative histogram."""
        m = self.histogram
        q = self._pick(_HIST_QS)
        w = self._pick(_WINDOWS)
        fn = self._pick(("rate", "increase"))
        inner = f"{fn}({self._selector(m, w)})"
        if self.rng.random() < 0.5:
            keep = self._pick(("le", "le,job", "le,instance"))
            inner = f"sum by ({keep}) ({inner})"
        return f"histogram_quantile({q}, {inner})"

    def _topk_expr(self, depth: int) -> str:
        """topk/bottomk over a CONTINUOUS-valued inner (rate/deriv/
        avg_over_time): partial-sort determinism is only well-defined
        engine-vs-reference when per-step ties have measure zero, so
        discrete-valued inners (counts, present) stay out."""
        op = self._pick(("topk", "bottomk"))
        k = self._pick(("1", "2", "3"))
        m = self._metric()
        w = self._pick(_WINDOWS)
        if m.kind == "counter":
            inner = f"{self._pick(('rate', 'increase'))}({self._selector(m, w)})"
        else:
            inner = f"{self._pick(('avg_over_time', 'deriv'))}({self._selector(m, w)})"
        return f"{op}({k}, {inner})"

    def _grouped_join_expr(self, depth: int) -> str:
        """many-to-one join: the 'many' side keeps full series labels,
        the 'one' side is aggregated to exactly the match key, so the
        join is provably many-to-one (semant's group_* rules pass by
        construction)."""
        labels = ("job",) if self.rng.random() < 0.5 else ("instance",)
        ls = ",".join(labels)
        m = self._metric("counter")
        w = self._pick(_WINDOWS)
        many = f"{self._pick(('rate', 'increase'))}({self._selector(m, w)})"
        one_m = self._metric("counter")
        one = (f"sum by ({ls}) "
               f"({self._pick(('rate', 'increase'))}"
               f"({self._selector(one_m, self._pick(_WINDOWS))}))")
        op = self._pick(("/", "*", "+", "-"))
        if self.rng.random() < 0.5:
            return f"({many} {op} on ({ls}) group_left {one})"
        return f"({one} {op} on ({ls}) group_right {many})"

    def _instant_fn_expr(self, depth: int) -> str:
        fn = self._pick(_INSTANT_FNS)
        inner = self._vector(depth - 1)
        if fn == "clamp":
            lo = self._pick(("0", "1"))
            hi = self._pick(("10", "100"))
            return f"clamp({inner}, {lo}, {hi})"
        if fn in ("clamp_min", "clamp_max"):
            return f"{fn}({inner}, {self._scalar_lit()})"
        if fn == "round" and self.rng.random() < 0.5:
            return f"round({inner}, {self._pick(('0.5', '2', '10'))})"
        return f"{fn}({inner})"

    def _vector(self, depth: int, allow_binop: bool = True) -> str:
        if depth <= 0:
            if self.rng.random() < 0.5:
                return self._selector(self._metric("gauge"))
            return self._range_fn_expr(0)
        r = self.rng.random()
        if r < 0.27:
            return self._range_fn_expr(depth)
        if r < 0.48:
            return self._agg_expr(depth)
        if r < 0.66 and allow_binop:
            return self._binop_expr(depth)
        if r < 0.78:
            return self._instant_fn_expr(depth)
        if r < 0.84 and self.histogram is not None:
            return self._histogram_expr(depth)
        if r < 0.9:
            return self._topk_expr(depth)
        if r < 0.95 and allow_binop:
            return self._grouped_join_expr(depth)
        return self._selector(self._metric("gauge"))

    # -- public ----------------------------------------------------------
    def query(self) -> str:
        """One well-typed query (validated: parses, plan-builds, and
        promlint-clean of error-severity findings)."""
        for _attempt in range(64):
            q = self._vector(self.rng.randrange(1, self.max_depth + 1))
            if not self.validate:
                return q
            diags = semant.lint_query(q, self.schemas)
            if semant.errors(diags):
                continue
            try:
                parse_query_range(q, self._params)
            except Exception:   # noqa: BLE001 — regenerate on any reject
                continue
            return q
        raise AssertionError(
            "QueryGen could not produce a valid query in 64 attempts — "
            "generator and type checker have drifted apart")

    def queries(self, n: int) -> List[str]:
        return [self.query() for _ in range(n)]
