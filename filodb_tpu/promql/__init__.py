"""PromQL frontend: parser producing LogicalPlans
(reference: prometheus/src/main/scala/filodb/prometheus/parse/Parser.scala:183,
ast/*.scala; grammar prometheus/src/main/java/filodb/prometheus/antlr/PromQL.g4).
"""

from filodb_tpu.promql.parser import parse_query, parse_query_range  # noqa: F401
