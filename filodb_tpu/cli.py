"""Admin + PromQL CLI (cli/src/main/scala/filodb.cli/CliMain.scala:159-266).

Commands mirror the reference's surface, talking to a running server over
its HTTP API (the reference talks Akka to a cluster; the control plane
here is HTTP), plus local offline debug commands for the binary formats:

  status          shard status of a dataset          (CliMain `status`)
  labels          label names                        (`labels`)
  labelvalues     values of one label                (`labelvalues`)
  timeseries-metadata  series key sets for a filter  (`timeseriesMetadata`)
  query           PromQL instant query               (`timeseries query`)
  query-range     PromQL range query
  tscard          cardinality records by prefix      (`tscard`)
  topkcard        heaviest children of a prefix      (`topkcardlocal`)
  find-query-shards    shards a shard key maps to    (`findqueryshards`)
  validate-schemas     check the built-in schema set (`validateSchemas`)
  decode-vector        hex/b64 BinaryVector -> values (`decodeVector`)
  decode-chunk-info    chunk metadata of a log file  (`decodeChunkInfo`)

Usage: python -m filodb_tpu.cli <command> [--host URL] [args...]
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
import urllib.parse
import urllib.request


def _get(host: str, path: str, **params):
    qs = urllib.parse.urlencode(
        {k: v for k, v in params.items() if v is not None}, doseq=True)
    url = host.rstrip("/") + path + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url, timeout=60) as r:
        return json.loads(r.read())


def _print_json(obj) -> None:
    print(json.dumps(obj, indent=2, sort_keys=True))


def cmd_status(a):
    _print_json(_get(a.host, f"/api/v1/cluster/{a.dataset}/status"))


def cmd_labels(a):
    _print_json(_get(a.host, f"/promql/{a.dataset}/api/v1/labels",
                     **{"match[]": a.match} if a.match else {}))


def cmd_labelvalues(a):
    _print_json(_get(
        a.host, f"/promql/{a.dataset}/api/v1/label/{a.label}/values",
        **{"match[]": a.match} if a.match else {}))


def cmd_series(a):
    _print_json(_get(a.host, f"/promql/{a.dataset}/api/v1/series",
                     **{"match[]": a.match}))


def cmd_query(a):
    _print_json(_get(a.host, f"/promql/{a.dataset}/api/v1/query",
                     query=a.promql, time=a.time))


def cmd_query_range(a):
    _print_json(_get(a.host, f"/promql/{a.dataset}/api/v1/query_range",
                     query=a.promql, start=a.start, end=a.end,
                     step=a.step))


def cmd_tscard(a):
    _print_json(_get(a.host, f"/api/v1/cardinality/{a.dataset}",
                     prefix=a.prefix, depth=a.depth))


def cmd_topkcard(a):
    body = _get(a.host, f"/api/v1/cardinality/{a.dataset}",
                prefix=a.prefix,
                depth=len([p for p in (a.prefix or "").split(",")
                           if p]) + 1)
    recs = sorted(body.get("data", []), key=lambda r: -r["tsCount"])
    _print_json(recs[: a.k])


def cmd_find_query_shards(a):
    from filodb_tpu.core.record import query_shards, shard_key_hash
    values = [v for v in a.shard_key_values.split(",") if v]
    skh = shard_key_hash(values, a.metric)
    shards = query_shards(skh, a.spread, a.num_shards)
    print(json.dumps({"shardKeyHash": skh, "shards": shards}))


def cmd_validate_schemas(a):
    """(Schemas.__post_init__ rejects hash clashes at load; this surfaces
    the registered set + ids like the reference's validateSchemas.)"""
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    out = {s.name: s.schema_id
           for s in DEFAULT_SCHEMAS.schemas.values()}
    print(json.dumps({"schemas": out, "ok": True}, sort_keys=True))


def _read_blob(arg: str) -> bytes:
    if arg.startswith("hex:"):
        return bytes.fromhex(arg[4:])
    if arg.startswith("b64:"):
        return base64.b64decode(arg[4:])
    with open(arg, "rb") as f:
        return f.read()


def cmd_decode_vector(a):
    from filodb_tpu.memory import histogram as bh
    from filodb_tpu.memory import vectors as bv
    buf = _read_blob(a.blob)
    if buf[:1] in (bytes([bh.K_HIST_2D]), bytes([bh.K_HIST_SECT])):
        scheme, counter, rows, drops = bh.decode_histograms_full(buf)
        print(json.dumps({
            "kind": "histogram", "counter": counter,
            "les": [float(x) for x in scheme.les()],
            "numRows": int(rows.shape[0]),
            "dropRows": None if drops is None else drops.tolist(),
            "rows": rows.tolist()[: a.limit]}))
        return
    vals = bv.decode(buf)
    print(json.dumps({"kind": "vector", "numValues": int(vals.size),
                      "values": vals.tolist()[: a.limit]}))


def cmd_decode_chunk_info(a):
    """Chunk metadata from a FlatFileColumnStore chunks.log."""
    from filodb_tpu.store.columnstore import FlatFileColumnStore
    cs = FlatFileColumnStore(a.data_dir)
    out = []
    for e in cs.scan_part_keys(a.dataset, a.shard):
        for c in cs.read_chunks(a.dataset, a.shard, e.part_key):
            out.append({
                "chunkId": c.chunk_id, "numRows": c.num_rows,
                "startTime": c.start_ts, "endTime": c.end_ts,
                "vectorBytes": [len(v) for v in c.vectors]})
            if len(out) >= a.limit:
                break
        if len(out) >= a.limit:
            break
    _print_json(out)


def main(argv=None) -> int:
    # --host/--dataset are accepted both before AND after the subcommand
    # (the docstring shows the latter)
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--host", default="http://127.0.0.1:8080")
    common.add_argument("--dataset", default="timeseries")
    # the subparser copy must NOT re-apply defaults, or an unset
    # post-command --host would clobber a pre-command one
    sub_common = argparse.ArgumentParser(add_help=False)
    sub_common.add_argument("--host", default=argparse.SUPPRESS)
    sub_common.add_argument("--dataset", default=argparse.SUPPRESS)
    p = argparse.ArgumentParser(prog="filodb-tpu-cli", description=__doc__,
                                parents=[common])
    sub = p.add_subparsers(dest="cmd", required=True)

    def add(name):
        return sub.add_parser(name, parents=[sub_common])

    add("status").set_defaults(fn=cmd_status)
    sp = add("labels")
    sp.add_argument("--match", action="append")
    sp.set_defaults(fn=cmd_labels)
    sp = add("labelvalues")
    sp.add_argument("label")
    sp.add_argument("--match", action="append")
    sp.set_defaults(fn=cmd_labelvalues)
    sp = add("timeseries-metadata")
    sp.add_argument("match", nargs="+")
    sp.set_defaults(fn=cmd_series)
    sp = add("query")
    sp.add_argument("promql")
    sp.add_argument("--time", type=int)
    sp.set_defaults(fn=cmd_query)
    sp = add("query-range")
    sp.add_argument("promql")
    sp.add_argument("--start", type=int, required=True)
    sp.add_argument("--end", type=int, required=True)
    sp.add_argument("--step", type=int, default=60)
    sp.set_defaults(fn=cmd_query_range)
    sp = add("tscard")
    sp.add_argument("--prefix", default="")
    sp.add_argument("--depth", type=int)
    sp.set_defaults(fn=cmd_tscard)
    sp = add("topkcard")
    sp.add_argument("--prefix", default="")
    sp.add_argument("-k", type=int, default=10)
    sp.set_defaults(fn=cmd_topkcard)
    sp = add("find-query-shards")
    sp.add_argument("shard_key_values",
                    help="comma-separated non-metric shard key values")
    sp.add_argument("metric")
    sp.add_argument("--spread", type=int, default=1)
    sp.add_argument("--num-shards", type=int, default=4)
    sp.set_defaults(fn=cmd_find_query_shards)
    add("validate-schemas").set_defaults(
        fn=cmd_validate_schemas)
    sp = add("decode-vector")
    sp.add_argument("blob", help="file path, hex:<hex>, or b64:<base64>")
    sp.add_argument("--limit", type=int, default=50)
    sp.set_defaults(fn=cmd_decode_vector)
    sp = add("decode-chunk-info")
    sp.add_argument("data_dir")
    sp.add_argument("--shard", type=int, default=0)
    sp.add_argument("--limit", type=int, default=20)
    sp.set_defaults(fn=cmd_decode_chunk_info)

    a = p.parse_args(argv)
    a.fn(a)
    return 0


if __name__ == "__main__":
    sys.exit(main())
